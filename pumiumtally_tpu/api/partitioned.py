"""Partitioned-mesh facade: the three-call protocol over element
ownership + particle migration (parallel/partition.py).

Same caller contract as ``PumiTally`` — staging, flying-zeroing side
effect, timing, VTK output are all inherited — but the device engine
shards the MESH (each chip owns a contiguous block of elements and only
its slice of the flux) instead of replicating it, and ships particles
between chips when they cross partition boundaries. This is the
TPU-native realization of the reference's latent multi-rank mode
(pumipic picparts + ``search(migrate)``, reference
PumiTallyImpl.cpp:530-539, 111; SURVEY.md §2.3 "mesh-partition
parallelism").

Use when the mesh (or the flux array) is too large to replicate per
chip, or to scale tally bandwidth: flux scatter-adds go to per-chip
owned slices with no cross-chip reduction at all.
"""

from __future__ import annotations

import time
import warnings
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu.api.tally import PumiTally, TallyConfig
from pumiumtally_tpu.io.vtk import write_pvtu
from pumiumtally_tpu.mesh.tetmesh import TetMesh
from pumiumtally_tpu.parallel.partition import PartitionedEngine


class PartitionedPumiTally(PumiTally):
    """Track-length tally with the tet mesh sharded across the device
    mesh (element ownership + particle migration)."""

    # The engine builds its own per-chip (possibly tiered) tables from
    # the partition — see PumiTally._replicated_mesh_walk.
    _replicated_mesh_walk = False

    def __init__(
        self,
        mesh: Union[TetMesh, str],
        num_particles: int = 100_000,
        config: Optional[TallyConfig] = None,
    ):
        t0 = time.perf_counter()
        mesh = self._init_common(mesh, num_particles, config)
        if self.device_mesh is None:
            # Single-device mode: mesh blocking without any multi-chip
            # setup. With walk_vmem_max_elems set this sub-splits the
            # whole mesh into VMEM-scale blocks on the one default
            # device — the block-local walk (vmem or gather kernel)
            # replaces the monolithic-table gather.
            from pumiumtally_tpu.parallel import make_device_mesh

            if (
                jax.device_count() > 1
                and jax.devices()[0].platform != "cpu"
            ):
                # A multi-chip host defaulting to one device is almost
                # always a forgotten TallyConfig.device_mesh — say so
                # instead of silently leaving (n-1) chips idle. CPU
                # "devices" are exempt: multiples of those are virtual
                # (xla_force_host_platform_device_count test rigs), not
                # idle hardware.
                warnings.warn(
                    f"PartitionedPumiTally: no device_mesh configured; "
                    f"running on 1 of the {jax.device_count()} available "
                    f"{jax.devices()[0].platform} devices. Pass "
                    "TallyConfig(device_mesh=make_device_mesh(n)) to "
                    "use them.",
                    stacklevel=2,  # point at the constructor call site
                )
            self.device_mesh = make_device_mesh(1)
        self.engine = PartitionedEngine(
            mesh,
            self.device_mesh,
            self.num_particles,
            capacity_factor=self.config.capacity_factor,
            tol=self._tol,
            max_iters=self._max_iters,
            max_rounds=self.config.max_migration_rounds,
            check_found_all=self.config.check_found_all,
            cond_every=self.config.resolved_cond_every(),
            min_window=self.config.resolved_min_window(),
            vmem_walk_max_elems=self.config.walk_vmem_max_elems,
            block_kernel=self.config.resolved_walk_kernel(),
            partition_method=self.config.resolved_partition_method(),
            table_dtype=self._table_dtype,
            cap_frontier=self.config.cap_frontier,
            scoring=self.config.scoring,
            migrate_collective=self.config.migrate_collective,
            placement=self.config.placement,
            placement_hosts=self.config.placement_hosts,
        )
        self._wire_engine_hooks(self.engine)
        # Scoring runtime AFTER the engine: the DROP sentinel needs the
        # engine's PADDED lane-bank size (nparts·L·B·S).
        self._arm_scoring(
            bank_size=None if self.config.scoring is None else (
                self.engine.nparts * self.engine.part.L
                * self.engine.score_stride
            )
        )
        jax.block_until_ready(self.engine.part.table)
        self.tally_times.initialization_time += time.perf_counter() - t0

    # -- sentinel / recovery wiring ---------------------------------------
    def _wire_engine_hooks(self, engine) -> None:
        """Connect one PartitionedEngine's overflow-recovery ladder to
        the facade: recoveries report into the sentinel health record,
        and a ladder exhaustion triggers a resilience safety save (the
        still-intact pre-overflow state) right before the poisoned
        raise."""
        engine.on_overflow_recovered = self._note_overflow_recovered
        engine.on_poisoned = self._overflow_safety_save

    def _note_overflow_recovered(self, escalated: bool) -> None:
        if self._sentinel is not None:
            self._sentinel.note_overflow_recovery(escalated)

    def _overflow_safety_save(self) -> None:
        if self._resilience is not None:
            self._resilience.save(self, reason="overflow_safety")

    def _engine_poisoned(self) -> bool:
        return self._poisoned or self.engine.poisoned

    # -- dispatch hooks ---------------------------------------------------
    def _dispatch_localize(self, dest: jnp.ndarray):
        return self.engine.localize(dest)  # (found_all, n_exited)

    def _current_lost(self) -> int:
        """The engine's still-lost particle count (lazy device scalar,
        cached as a host int after the first fetch)."""
        return self.engine._n_lost

    def _dispatch_move(self, origins, dests, fly, w, sbin=None, sfac=None):
        # auto_continue applies here too: when the base class detects an
        # origin echo it hands back the device array that staged last
        # move's destinations (caller order), which this engine treats
        # exactly like freshly uploaded origins. Scoring operands are
        # caller-order [n] rows: the engine routes them by pid and
        # migrates them with their particles.
        skw = {}
        if self._scoring is not None:
            skw = {"sbin_n": sbin, "sfac_n": sfac}
        if self._sentinel is None:
            return self.engine.move(origins, dests, fly, w, **skw)
        # Sentinel audit needs the phase-B start in caller order: the
        # staged origins, or (continue mode) the committed positions
        # BEFORE the move (one pid-sort gather; migration permutes
        # slots, so a post-move snapshot would pair wrong particles).
        x0 = (
            origins if origins is not None
            else self.engine.caller_order_view(("x",))["x"]
        )
        ok = self.engine.move(origins, dests, fly, w, **skw)
        return self._sentinel_post_move_partitioned(
            self.engine, x0, dests, fly, w, ok
        )

    def _sentinel_post_move_partitioned(self, engine, x0, dests, fly, w,
                                        ok):
        """Partitioned arm of the sentinel protocol: audit from the
        engine's caller-order views, then the engine-level straggler
        ladder (resume-phase retry with multiplied budgets → declare
        lost + quarantine)."""
        pol = self.config.sentinel
        view = engine.caller_order_view(("x", "done"))
        n_unf, mask = self._sentinel.audit(
            x0, view["x"], fly, w, view["done"],
            engine.flux_original(),
        )
        recovered = lost = 0
        if n_unf and pol.straggler_retry:
            ok = engine.retry_stragglers(pol.retry_iters_factor)
            if not ok:
                self._quarantine_partitioned(engine, x0, dests, fly, w)
                lost = engine.declare_lost_stragglers()
                ok = lost == 0  # residue either lost or (rarely) found
            recovered = max(0, n_unf - lost)
            self._sentinel.resync(engine.flux_original())
        self._sentinel.note_outcome(
            mask, n_unf, recovered, lost, self.iter_count
        )
        return ok

    def _quarantine_partitioned(self, engine, x0, dests, fly, w) -> None:
        """Quarantine records for the particles the engine ladder is
        about to declare lost (caller-order fetch of the residue)."""
        from pumiumtally_tpu.sentinel.quarantine import (
            append_quarantine,
            build_records,
        )

        view = engine.caller_order_view(("done", "elem_orig"))
        done = np.asarray(view["done"])
        idx = np.flatnonzero(~done & (np.asarray(fly) == 1))
        if idx.size == 0:
            return
        sel = jnp.asarray(idx)
        append_quarantine(
            self.config.sentinel.quarantine_dir,
            build_records(
                idx, np.asarray(x0[sel]), np.asarray(dests[sel]),
                np.asarray(view["elem_orig"])[idx], np.asarray(w[sel]),
                self.iter_count,
            ),
        )

    def WriteTallyResults(self, filename: Optional[str] = None) -> None:
        """Normalize and write results; a ``.pvtu`` filename writes one
        binary piece per chip (the elements it owns) plus the index
        file — the rank-aware output path of the reference
        (``vtk::write_parallel``, PumiTallyImpl.cpp:415). Any other
        extension falls through to the monolithic writers."""
        self._check_poisoned()  # the .pvtu branch bypasses super()
        out = filename or self.config.output_filename
        if not out.endswith(".pvtu"):
            return super().WriteTallyResults(filename)
        t0 = time.perf_counter()
        # part.owner is at PART granularity; with the VMEM sub-split a
        # chip owns a contiguous run of blocks_per_chip parts — pieces
        # stay one-per-CHIP (the reference's rank-aware layout).
        owner = self.engine.part.owner // self.engine.blocks_per_chip
        from pumiumtally_tpu.io.vtk import merge_cell_data

        write_pvtu(
            out,
            np.asarray(self.mesh.coords),
            np.asarray(self.mesh.tet2vert),
            owner,
            cell_data=merge_cell_data(
                {
                    "flux": np.asarray(self.normalized_flux()),
                    "volume": np.asarray(self.mesh.volumes),
                    "owner": owner.astype(np.float64),
                },
                # Same optional statistics / scoring payloads as the
                # monolithic writer, split per piece like every other
                # cell array.
                self._stats_vtk_cell_data(),
                self._scoring_vtk_cell_data(),
            ),
            # Campaign-level leakage accounting, replicated into every
            # piece (field data is global, not per-cell).
            field_data=self._vtk_field_data(),
            nparts=int(self.device_mesh.devices.size),
        )
        self.tally_times.vtk_file_write_time += time.perf_counter() - t0
        self.tally_times.print_times()

    # -- state views (caller-visible order) -------------------------------
    @property
    def x(self):  # base class blocks on this after localization
        return self.engine.state["x"]

    @property
    def flux(self) -> jnp.ndarray:
        """Owned per-chip flux assembled into original element order."""
        return self.engine.flux_original()

    @property
    def score_bank(self) -> jnp.ndarray:
        """Owned scoring lanes assembled into the canonical [E·B·S]
        layout (original element order)."""
        self._require_scoring()
        return self.engine.score_original()

    @property
    def positions(self) -> np.ndarray:
        return self.engine.positions()[: self.num_particles]

    @property
    def elem_ids(self) -> np.ndarray:
        return self.engine.elem_ids()[: self.num_particles]
