from pumiumtally_tpu.api.tally import PumiTally, TallyTimes

__all__ = ["PumiTally", "TallyTimes"]
