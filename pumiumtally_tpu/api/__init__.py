from pumiumtally_tpu.api.tally import (
    PumiTally,
    TallyTimes,
    check_finite,
    host_positions,
    host_scalar_field,
    zero_flying_side_effect,
)

# The host-staging helpers are re-exported for layers that prepack
# caller buffers OUTSIDE a protocol call (the service's submit-time
# staging, service/staging.py) — they are the single source of the
# buffer-shape and finite-validation rules, so a prepacked move
# refuses with exactly the errors a direct facade call would raise.
__all__ = [
    "PumiTally",
    "TallyTimes",
    "check_finite",
    "host_positions",
    "host_scalar_field",
    "zero_flying_side_effect",
]
