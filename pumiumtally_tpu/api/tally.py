"""The three-call public API (reference PumiTally.h:34-107).

``PumiTally`` mirrors the reference's PIMPL facade protocol exactly —
``CopyInitialPosition`` / ``MoveToNextLocation`` / ``WriteTallyResults``
— so a physics host app (e.g. the OpenMC ``--ohMesh`` fork,
reference README.md:84-104) can drive it with flat builtin-typed
buffers. Internally everything is jitted JAX; host↔device staging goes
through ``jax.device_put`` in place of the reference's unmanaged-view
``Kokkos::deep_copy`` (PumiTallyImpl.cpp:223-236).

Semantics preserved from the reference:

- Construction seeds all particles at the centroid of element 0
  (PumiTallyImpl.cpp:492-528); ``CopyInitialPosition`` then runs one
  non-tallying search to localize them (PumiTallyImpl.cpp:195-221).
- ``MoveToNextLocation`` is the two-phase move (PumiTallyImpl.cpp:66-149):
  phase A relocates flying particles to their (possibly resampled)
  origins without tallying — the reference does this by zeroing weights
  (cpp:105) — and holds non-flying particles in place (cpp:100-103);
  phase B transports flying particles to their destinations, tallying
  track-length × weight per element.
- The caller's ``flying`` array is ZEROED after the copy — a documented
  side effect OpenMC relies on (PumiTallyImpl.cpp:169-172, pinned by
  test:186-212).
- Particles leaving the domain clamp to the boundary intersection point
  and stay "done" for the remainder of that move (vacuum BC,
  PumiTallyImpl.cpp:256-286).
- ``WriteTallyResults`` normalizes by element volume only — NOT by total
  weight; the reference README claims otherwise but its code never uses
  ``total_initial_weight`` (TODO at PumiTallyImpl.cpp:60,372) — and
  writes a VTK file with "flux" and "volume" cell data
  (PumiTallyImpl.cpp:411-416).

Note on the reference's in-repo oracle test: its second move passes the
ORIGINAL source points as ``particle_origin`` while its expected fluxes
assume the walk starts from the particles' current committed positions
(test:318-320 vs test:371-389 — the test is never built by the
reference's CI due to the PUMITALLYOPENMC_/PUMITALLY_ flag mismatch,
SURVEY.md §2.1). The production contract — which this class implements —
is that ``particle_origin`` equals the committed position for continuing
particles and the resampled birth position for reincarnated ones; our
parity suite passes correct origins and reproduces the oracle values
exactly.
"""

from __future__ import annotations

import time
import warnings
from dataclasses import dataclass
from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pumiumtally_tpu.config import TallyConfig
from pumiumtally_tpu.mesh.tetmesh import TetMesh
from pumiumtally_tpu.ops.walk import walk
from pumiumtally_tpu.io.vtk import write_vtk
from pumiumtally_tpu.utils.profiling import register_entry_point


@dataclass
class TallyTimes:
    """Per-phase wall-clock accumulation (reference PumiTallyImpl.h:18-27).

    Device work is fenced with ``block_until_ready`` before timestamps —
    the reference intended ``Kokkos::fence()`` here but its macro name
    mismatch left timing unfenced (SURVEY.md §5).
    """

    initialization_time: float = 0.0
    total_time_to_tally: float = 0.0
    vtk_file_write_time: float = 0.0

    def print_times(self) -> None:  # reference PrintTimes, PumiTallyImpl.cpp:22-29
        print()
        print(f"[TIME] Initialization time     : {self.initialization_time:f} seconds")
        print(f"[TIME] Total time to tally     : {self.total_time_to_tally:f} seconds")
        print(f"[TIME] VTK file write time     : {self.vtk_file_write_time:f} seconds")
        total = (
            self.initialization_time
            + self.total_time_to_tally
            + self.vtk_file_write_time
        )
        print(f"[TIME] Total PUMI-Tally time   : {total:f} seconds")


# Consecutive origin-echo misses after which a facade stops paying for
# echo snapshots (the driver has proven it resamples every move).
_ECHO_MISS_LIMIT = 8
# While disarmed, one snapshot is retained every this-many moves so the
# NEXT move can probe again: a driver that echoes intermittently (e.g.
# resampling phases longer than the miss limit) regains the upload skip
# within a period instead of losing it until CopyInitialPosition. Cost
# of a retry: one [n,3] snapshot copy per period plus one 64-point
# probe on the following move.
_ECHO_REARM_PERIOD = 64


# time.perf_counter pre-bound at module level: MoveToNextLocation's
# protocol keyword ``time`` (the scoring TimeFilter attribute, round
# 10) shadows the module name inside that method body.
_perf_counter = time.perf_counter


def host_positions(buf, size: Optional[int], n: int) -> np.ndarray:
    """Validate a caller position buffer → flat [3n] float64 host array
    (shared by the monolithic and streaming facades)."""
    a = np.asarray(buf, dtype=np.float64).reshape(-1)
    if size is not None and size != 3 * n:
        raise ValueError(f"size {size} != 3*num_particles {3 * n}")
    if a.shape[0] < 3 * n:
        raise ValueError(
            f"position buffer has {a.shape[0]} values, need {3 * n}"
        )
    return a[: 3 * n]


def host_scalar_field(buf, n: int, what: str) -> np.ndarray:
    """Validate a caller per-particle scalar buffer (``energy``/
    ``time``/...) → flat [n] float64 host array, with SHAPE errors that
    name the argument — without this narrow prevalidation a wrong-shape
    array surfaces later as an opaque jit broadcast failure (shared by
    the monolithic and streaming facades; the finite check happens
    after the working-dtype cast, like positions)."""
    a = np.asarray(buf, dtype=np.float64).reshape(-1)
    if a.shape[0] < n:
        raise ValueError(
            f"{what} buffer has {a.shape[0]} values, need {n}"
        )
    return a[:n]


def check_finite(a: np.ndarray, what: str, offset: int = 0) -> None:
    """Raise on NaN/Inf in a staged host array (TallyConfig.
    validate_inputs): one non-finite destination or weight silently
    poisons the whole accumulated flux (nan scatter-add), so refusing
    BEFORE upload keeps the engine's committed state clean.

    The monolithic facade checks the whole batch after the
    working-dtype cast (so f64 values that overflow f32 to inf are
    caught) before anything is dispatched. The streaming facade checks
    the raw f64 batch at entry AND pre-validates the working-dtype
    casts before ANY chunk dispatches via ``_prevalidate_narrow``
    (api/streaming.py): chunk-at-a-time casts, discarded after the
    check, so the f32-overflow corner also refuses up front without a
    full-batch copy; the per-chunk staging check then only backstops
    it."""
    if not np.isfinite(a).all():
        flat = np.asarray(a).reshape(-1)
        bad = np.flatnonzero(~np.isfinite(flat))
        # ``offset``: flat index of a[0] in the CALLER's buffer (chunked
        # staging passes the chunk base) so the report locates the bad
        # element in what the host actually handed over.
        raise ValueError(
            f"{what} contains {bad.size} non-finite value(s); first at "
            f"flat index {offset + bad[0]} ({flat[bad[0]]!r}). Fix the "
            "host buffer, or set TallyConfig(validate_inputs=False) to "
            "stage unchecked"
        )


def zero_flying_side_effect(flying, n: int) -> None:
    """Zero the caller's flying buffer in place after staging — the
    reference's documented host side effect OpenMC relies on
    (PumiTallyImpl.cpp:169-172). ndarray.flat writes through even for
    non-contiguous arrays; other mutable buffers are zeroed by item
    assignment; unwritable buffers get a warning, never a silent skip."""
    if isinstance(flying, np.ndarray):
        if flying.flags.writeable:
            flying.flat[:n] = 0
        else:
            warnings.warn(
                "flying array is read-only: skipping the in-place "
                "zeroing side effect the host protocol specifies"
            )
    elif isinstance(flying, list):
        flying[:n] = [0] * min(n, len(flying))
    elif flying is not None:
        try:
            for i in range(min(n, len(flying))):
                flying[i] = 0
        except (TypeError, ValueError):
            warnings.warn(
                "flying buffer is not writeable: skipping the "
                "in-place zeroing side effect the host protocol "
                "specifies"
            )


@partial(jax.jit, static_argnames=("tol",))
def _locate_step(mesh, pts, *, tol):
    from pumiumtally_tpu.ops.geometry import locate_by_planes

    return locate_by_planes(mesh.face_normals, mesh.face_offsets, pts, tol)


def adopt_located(x, elem, dest, e0):
    """Locate-mode adoption rule (every facade): located particles
    (``e0 >= 0``) adopt (dest, element) so the follow-up masked walk
    retires them immediately; unlocated ones keep their committed
    (x, elem) and walk/clamp."""
    missing = e0 < 0
    return (
        jnp.where(missing[:, None], x, dest),
        jnp.where(missing, elem, e0),
    )


def locate_or_committed(mesh, x, elem, dest, *, tol):
    """Shared locate-mode pre-pass (monolithic + streaming facades):
    MXU point location of ``dest``, then the adoption rule."""
    return adopt_located(x, elem, dest, _locate_step(mesh, dest, tol=tol))


@partial(jax.jit, static_argnames=("tol", "max_iters", "walk_kw"))
def _localize_step(mesh, x, elem, dest, *, tol, max_iters, walk_kw=()):
    n = x.shape[0]
    in_flight = jnp.ones((n,), jnp.int8)
    weight = jnp.zeros((n,), x.dtype)
    # A tally=False walk never touches flux — zero-size dummy.
    r = walk(
        mesh, x, elem, dest, in_flight, weight, jnp.zeros((0,), x.dtype),
        tally=False, tol=tol, max_iters=max_iters, **dict(walk_kw),
    )
    return r.x, r.elem, r.done, r.exited


def move_step_continue(mesh, x, elem, dests, flying, weights, flux, *, tol,
                       max_iters, walk_kw=(), score_kinds=(),
                       score_ops=None, tally_seg=None):
    """Phase-B-only move: transport from the COMMITTED state straight to
    the destinations, tallying. Semantically identical to ``move_step``
    when the caller's origins equal the committed positions — the common
    case for continuing particles (the reference's phase A then walks
    zero distance, PumiTallyImpl.cpp:88-109). Skipping it halves the
    device work and the host→device staging; a TPU-native extension, not
    part of the reference's 3-call protocol.

    Returns the per-particle ``done`` MASK and the final ray
    coordinate ``s`` (round 9), not a pre-reduced scalar: the facades
    reduce the mask for the found-all check, and the sentinel's
    straggler-escalation ladder consumes both — ``s`` is what lets a
    truncated particle's retry continue the exact original
    parametrization (see ops.walk.WalkResult.s). The walk itself is
    unchanged, so flux/positions/elements stay bitwise identical to
    pre-mask builds.

    ``score_kinds`` (static) + ``score_ops`` — the traced
    ``(bank, bin_off, fac)`` bundle from scoring.ScoringRuntime —
    arm the walk's segment-commit scoring hook (round 10); the return
    then gains the accumulated bank as a SIXTH element. None
    (default) leaves the trace byte-identical to pre-scoring builds.

    ``tally_seg`` (round 12, cross-session fusion) arms the walk's
    SEGMENTED flux commit: per-particle int32 offsets added to every
    flux scatter index, so a slab packing several sessions' particles
    tallies each session into its own ``[E]`` segment of a
    concatenated flux bank (ops/walk.py ``walk(tally_seg=)``). None
    (default, every non-fused path) leaves the trace byte-identical
    to pre-hook builds."""
    is_flying = flying[:, None] == 1
    dest_b = jnp.where(is_flying, dests, x)  # stopped → hold (cpp:100-103)
    sc = None
    if score_ops is not None:
        from pumiumtally_tpu.scoring.binding import ScoreOps

        sc = ScoreOps(score_kinds, *score_ops)
    rb = walk(
        mesh, x, elem, dest_b, flying, weights, flux,
        tally=True, tol=tol, max_iters=max_iters, scoring=sc,
        tally_seg=tally_seg, **dict(walk_kw),
    )
    if score_ops is None:
        return rb.x, rb.elem, rb.flux, rb.done, rb.s
    return rb.x, rb.elem, rb.flux, rb.done, rb.s, rb.score_bank


def move_step(mesh, x, elem, origins, dests, flying, weights, flux, *, tol,
              max_iters, walk_kw=(), score_kinds=(), score_ops=None,
              tally_seg=None):
    """One full MoveToNextLocation: phase A (relocate, no tally) then
    phase B (transport, tally). Reference PumiTallyImpl.cpp:66-149.

    Unjitted and functional — the building block for the jitted
    single-chip path below, the sharded path in ``parallel.sharded``,
    and external drivers that want to fuse it into larger programs.

    When every staged origin equals the committed position bit-for-bit
    (the common physics case: no particle was resampled, and the host
    echoes back the positions it was handed), phase A would walk zero
    distance for every particle and change nothing — a device-side
    check skips the whole pass, so the full reference protocol pays
    only the staging, not a redundant batch sweep.
    """
    in_flight = flying
    is_flying = in_flight[:, None] == 1
    # Phase A: flying → walk to origin (no tally); stopped → hold.
    dest_a = jnp.where(is_flying, origins, x)
    zero_w = jnp.zeros_like(weights)  # reference zeroes weights, cpp:105

    def run_a(op):
        x_, elem_ = op
        # A tally=False walk never touches flux — pass a dummy so the
        # [E]-sized array need not ride through the cond.
        ra = walk(
            mesh, x_, elem_, dest_a, in_flight, zero_w,
            jnp.zeros((0,), x_.dtype),
            tally=False, tol=tol, max_iters=max_iters, **dict(walk_kw),
        )
        return ra.x, ra.elem, ra.done

    trivial = jnp.all(dest_a == x)

    def skip_a(op):
        x_, elem_ = op
        # All-done mask, derived from the particle arrays so it carries
        # the right varying type when this runs inside shard_map — a
        # literal constant would not. (`trivial` is True on this
        # branch by construction.)
        return x_, elem_, elem_ == elem_
    xa, ea, done_a = lax.cond(trivial, skip_a, run_a, (x, elem))
    # Phase B is exactly the continue-mode move from the relocated state.
    res = move_step_continue(
        mesh, xa, ea, dests, flying, weights, flux,
        tol=tol, max_iters=max_iters, walk_kw=walk_kw,
        score_kinds=score_kinds, score_ops=score_ops,
        tally_seg=tally_seg,
    )
    x2, elem2, flux2, done_b, s_b = res[:5]
    # Per-particle mask + phase-B ray coordinate (round 9, see
    # move_step_continue): a particle is "found" only if BOTH phases
    # retired it.
    out = (x2, elem2, flux2, done_a & done_b, s_b)
    return out if score_ops is None else out + (res[5],)


_move_step = register_entry_point(
    "walk",
    partial(
        jax.jit,
        static_argnames=("tol", "max_iters", "walk_kw", "score_kinds"),
    )(move_step),
)
_move_step_continue = register_entry_point(
    "walk_continue",
    partial(
        jax.jit,
        static_argnames=("tol", "max_iters", "walk_kw", "score_kinds"),
    )(move_step_continue),
)
# Rebinds, not bare calls: register_entry_point returns the counting
# wrapper, and only calls through the wrapper are counted.
_locate_step = register_entry_point("locate", _locate_step)
_localize_step = register_entry_point("localize", _localize_step)


@dataclass
class FusedMoveStage:
    """One session's share of a fused cross-session launch (round 12):
    the host half of a move, produced by ``PumiTally._fused_move_stage``
    and consumed by ``service/fusion.py``'s pack step. Position/weight
    buffers are HOST arrays in the working dtype (``None`` weights /
    flying = the unit defaults, packed as ones rows); the scoring
    operands are the per-session device arrays a solo move would stage
    (``None`` with scoring off). ``x_prev`` is the committed position
    array BEFORE the move — the phase-B start the sentinel audit needs
    in continue mode."""

    dests: np.ndarray  # [n,3] working dtype, host
    origins: Optional[np.ndarray]  # [n,3] host, None = continue mode
    fly: Optional[np.ndarray]  # [n] int8 host, None = all in flight
    w: Optional[np.ndarray]  # [n] working dtype host, None = unit
    sbin: Optional[jnp.ndarray]  # [n] int32 device (scoring only)
    sfac: Optional[jnp.ndarray]  # [n,S] device (scoring only)
    x_prev: Optional[jnp.ndarray] = None


class PumiTally:
    """Track-length tally over an unstructured tet mesh — TPU native.

    Args:
      mesh: a ``TetMesh``, or a mesh file path (``.msh`` Gmsh ASCII or
        ``.osh`` Omega_h directory — reference ctor takes the ``.osh``
        path, PumiTally.h:50).
      num_particles: particle-batch capacity (reference default 1e5,
        PumiTallyImpl.h:155).
      config: engine knobs; see ``TallyConfig``.
    """

    def __init__(
        self,
        mesh: Union[TetMesh, str],
        num_particles: int = 100_000,
        config: Optional[TallyConfig] = None,
    ):
        t0 = time.perf_counter()
        mesh = self._init_common(mesh, num_particles, config)
        n = self.num_particles
        # Internal capacity: padded up to a multiple of the device-mesh
        # size so the particle axis shards evenly; padded slots always
        # carry in_flight=0 / dest=x and finish on the first walk
        # iteration with zero flux contribution.
        if self.device_mesh is not None:
            from pumiumtally_tpu.parallel.sharded import axis_name

            axis_name(self.device_mesh)  # fail fast: must be 1-D
            ndev = self.device_mesh.devices.size
            self._cap = -(-n // ndev) * ndev
        else:
            self._cap = n

        # Seed every particle at the centroid of element 0, as the
        # reference does (PumiTallyImpl.cpp:492-528): localization then
        # happens by walking, with no search tree.
        c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0).astype(self.dtype)
        self.x = jnp.broadcast_to(c0, (self._cap, 3))
        self.elem = jnp.zeros((self._cap,), jnp.int32)
        self.flux = jnp.zeros((mesh.nelems,), self.dtype)
        self._arm_scoring()
        if self._scoring is not None:
            self._score_bank = self._scoring.zero_bank()
        jax.block_until_ready(self.x)
        self.tally_times.initialization_time += time.perf_counter() - t0

    # Facades whose walks gather from the replicated ``self.mesh``
    # tables (monolithic/sharded/streaming) adopt the two-tier tables
    # here; the partitioned facades set this False — they build their
    # own per-chip tiered tables in build_partition and a converted
    # monolithic mesh would just pin dead [E]-sized arrays on device.
    _replicated_mesh_walk = True

    def _init_common(self, mesh, num_particles, config) -> TetMesh:
        """Shared construction: config resolution, mesh load, counters."""
        self.config = config or TallyConfig()
        self.device_mesh = self.config.device_mesh
        self.dtype = self.config.resolved_dtype()
        if isinstance(mesh, str):
            from pumiumtally_tpu.io.load import load_mesh

            mesh = load_mesh(mesh, dtype=self.dtype)
        elif self.config.dtype is None:
            # A prebuilt TetMesh fixes the working dtype unless the
            # config asked for one explicitly — mixing dtypes between
            # the mesh tables and particle state breaks jit carries.
            self.dtype = mesh.coords.dtype
        elif mesh.coords.dtype != self.dtype:
            mesh = mesh.astype(self.dtype)
        self._table_dtype = self.config.resolved_table_dtype()
        if self._table_dtype == "bfloat16" and self._replicated_mesh_walk:
            # walk_kwargs() emits the matching static table_dtype key,
            # so the walk kernel runs the two-tier path against these
            # tables (select-in-bf16 / commit-in-f32, docs/DESIGN.md).
            mesh = mesh.with_lowp_tables()
        self.mesh = mesh
        self.num_particles = int(num_particles)
        self._tol = self.config.resolved_tolerance(self.dtype)
        self._max_iters = self.config.resolved_max_iters(mesh.nelems)
        self._walk_kw = self.config.walk_kwargs()  # static jit arg
        self.iter_count = 0
        self.is_initialized = False
        self.tally_times = TallyTimes()
        # Auto-continue bookkeeping: the working-dtype destinations of
        # the previous move, kept BOTH as an owned host array (for the
        # echo compare) and as the device array already staged for that
        # move (substituted for the caller's origins on an echo — no
        # upload, no sync, and phase A still runs on device whenever it
        # must, e.g. after a boundary clamp). Reset whenever something
        # other than a move changes particle state.
        self._last_dests_host: Optional[np.ndarray] = None
        self._last_dests_dev = None
        # Pure input caches (no state dependence, so never invalidated):
        # device ones for flying/weights, and the previous move's
        # weights for the unchanged-weights echo.
        self._ones_cache: dict = {}
        self._last_weights_host: Optional[np.ndarray] = None
        self._last_weights_dev = None
        self.auto_continue_hits = 0  # diagnostic: moves that skipped the origin upload
        self._echo_misses = 0  # consecutive non-echo moves (see _origins_echo_raw)
        # Batch statistics (TallyConfig.batch_stats): an accumulator
        # over the caller-visible [E] flux, or None (default — then no
        # stats code runs anywhere in the protocol path and the engine
        # is bitwise identical to a stats-less build).
        self._stats = None
        if self.config.batch_stats:
            from pumiumtally_tpu.stats import BatchAccumulator

            self._stats = BatchAccumulator(mesh.nelems, self.dtype)
        # Filtered scoring (TallyConfig.scoring, round 10): the
        # per-facade ScoringRuntime, or None (default — no scoring
        # code runs anywhere; every engine is bitwise- and
        # allocation-identical to a scoring-less build). The facades
        # arm it AFTER construction fixes their bank geometry
        # (_arm_scoring): the partitioned ones need the engine's
        # padded lane-bank size for the DROP sentinel.
        self._scoring = None
        self._score_bank = None
        self._score_stats = None
        self._last_score_ops = None  # staged (sbin, sfac) for the ladder
        # Cumulative leakage counter (the rolled part of
        # ``lost_particles``; partitioned facades add the open batch's
        # current lost count on read).
        self._lost_total = 0
        # Fault tolerance (TallyConfig.checkpoint): the autosave/drain
        # runner, or None (default — no resilience code runs anywhere
        # in the protocol path, no signal handlers are installed).
        self._resilience = None
        if self.config.checkpoint is not None:
            from pumiumtally_tpu.resilience import AutosaveRunner

            self._resilience = AutosaveRunner(self.config.checkpoint)
        # Runtime sentinels (TallyConfig.sentinel): the audit/ladder
        # runner, or None (default — no sentinel code runs anywhere in
        # the protocol path; bitwise- and allocation-identical to a
        # sentinel-less build).
        self._sentinel = None
        if self.config.sentinel is not None:
            from pumiumtally_tpu.sentinel import SentinelRunner

            self._sentinel = SentinelRunner(self.config.sentinel,
                                            self.dtype)
        # Poisoned latch (docs/DESIGN.md "Failure taxonomy"): set when
        # a partitioned overflow exhausts the recovery ladder — every
        # subsequent protocol call then refuses with a clear
        # resume-from-checkpoint error instead of computing garbage.
        self._poisoned = False
        return mesh

    def _cached_ones(self, kind: str) -> jnp.ndarray:
        """Device all-ones [n] (int8 flying / working-dtype weights) —
        allocated once, reused every move."""
        a = self._ones_cache.get(kind)
        if a is None:
            dt = jnp.int8 if kind == "fly" else self.dtype
            a = jnp.ones((self.num_particles,), dt)
            self._ones_cache[kind] = a
        return a

    # -- staging helpers -------------------------------------------------
    def _as_positions_cast(self, buf, size: Optional[int],
                       what: Optional[str] = "positions") -> np.ndarray:
        """[n,3] working-dtype host array; MAY be a view of the
        caller's buffer (f64 working dtype). Cast on the host with
        numpy BEFORE handing to jax: letting jnp.asarray do the
        f64→f32 conversion goes through a slow backend path (measured
        ~100× slower than a numpy pre-cast + plain transfer)."""
        a = host_positions(buf, size, self.num_particles)
        cast = np.asarray(
            a.reshape(self.num_particles, 3), dtype=np.dtype(self.dtype)
        )
        if what is not None and self.config.validate_inputs:
            # Checked AFTER the working-dtype cast so an f64 value that
            # overflows f32 to inf is caught too. ``what=None`` opts
            # out for buffers a caller has already validated.
            check_finite(cast, what)
        return cast

    @staticmethod
    def _owned(h: np.ndarray) -> np.ndarray:
        """Materialize an OWNED copy unless ``h`` already owns its
        memory. Anything staged to the device or kept across calls must
        be owned: the CPU backend's jnp.asarray can be zero-copy, and
        the auto-continue bookkeeping outlives the call — a view of a
        recycled caller buffer would corrupt both."""
        return h if (h.base is None and h.flags.owndata) else h.copy()

    def _as_positions_host(self, buf, size: Optional[int],
                       what: Optional[str] = "positions") -> np.ndarray:
        return self._owned(self._as_positions_cast(buf, size, what))

    def _origins_echo_raw(self, buf, size: Optional[int]) -> bool:
        """Shared echo rule for every facade: the caller's origins,
        cast to the working dtype, equal the previous move's
        destinations bit-for-bit. Counts the hit.

        Cheap-first: a 64-point strided sample is cast and compared
        before any full-batch work, so origin streams that never echo
        (fresh samples every move) pay ~nothing instead of a
        full-batch cast + compare per move. After _ECHO_MISS_LIMIT
        consecutive misses the snapshots are dropped and retention
        mostly stops (see _retain_echo_snapshots) — the steady state
        for a never-echoing driver is an attribute test on all but one
        move per _ECHO_REARM_PERIOD, when a snapshot is retained so the
        next move can probe whether the driver started echoing again."""
        if buf is None or not self.config.auto_continue:
            return False
        if self._last_dests_host is None:
            # No snapshot to compare against (start of batch, or
            # disarmed): still count the move so the periodic re-arm
            # clock advances.
            self._echo_misses += 1
            return False
        prev = self._last_dests_host  # [n,3] working dtype, owned
        n = self.num_particles
        raw = host_positions(buf, size, n).reshape(n, 3)
        idx = np.linspace(0, n - 1, num=min(n, 64), dtype=np.int64)
        if np.array_equal(
            np.asarray(raw[idx], dtype=prev.dtype), prev[idx]
        ) and np.array_equal(np.asarray(raw, dtype=prev.dtype), prev):
            self.auto_continue_hits += 1
            self._echo_misses = 0
            return True
        self._echo_misses += 1
        if self._echo_misses >= _ECHO_MISS_LIMIT:
            # This driver resamples origins every move; stop paying
            # for snapshots it will never hit. CopyInitialPosition —
            # or a periodic retry (_ECHO_REARM_PERIOD) — re-arms the
            # detector.
            self._last_dests_host = None
            self._last_dests_dev = None
        return False

    def _retain_echo_snapshots(self) -> bool:
        """Whether this move's destinations should be snapshotted for
        the next move's echo check: origin-passing drivers that have
        not proven themselves never-echoing, plus one retry snapshot
        per _ECHO_REARM_PERIOD while disarmed (an intermittently
        echoing driver then recovers the upload skip within a period)."""
        return self.config.auto_continue and (
            self._echo_misses < _ECHO_MISS_LIMIT
            or self._echo_misses % _ECHO_REARM_PERIOD == _ECHO_REARM_PERIOD - 1
        )

    def _as_positions(self, buf, size: Optional[int]) -> jnp.ndarray:
        return jnp.asarray(self._as_positions_host(buf, size))

    def _pad_particles(self, a: jnp.ndarray, fill) -> jnp.ndarray:
        """Extend [n,...] staged data to the internal [cap,...] capacity."""
        if self._cap == self.num_particles:
            return a
        return jnp.concatenate([a, fill[self.num_particles :]], axis=0)

    # -- fault tolerance (TallyConfig.checkpoint) ------------------------
    def _resilience_roll_batch(self) -> None:
        """Batch-close hook for the autosave runner: fires on every
        ``CopyInitialPosition`` that closes a non-empty source batch
        (and on ``close_batch``/``finalize``). Placed BEFORE the lost
        counter rolls and before new sources rewrite the state, so the
        saved generation is exactly the closed batch's end state. No-op
        without a checkpoint policy."""
        if self._resilience is not None:
            self._resilience.on_batch_close(self)

    def _resilience_note_move(self) -> None:
        """Move-end hook: the preemption-safe drain point and the
        ``every_seconds`` cadence check. No-op without a policy."""
        if self._resilience is not None:
            self._resilience.on_move(self)

    def checkpoint_now(self, **meta):
        """Write one checkpoint generation immediately through the
        configured ``TallyConfig.checkpoint`` policy (e.g. the final
        save after a campaign's last batch, which no re-sourcing will
        ever close). Returns (generation, path). Keyword arguments ride
        along in the generation's metadata (the runner's own
        reason/iter_count/batches_closed keys win on collision).

        A pending drain request (SIGTERM during the final batch, whose
        close this call stands in for) exits cleanly here after the
        save — otherwise a preemption notice received near the end of
        a campaign would be silently absorbed by a runner whose
        batch-close hooks never fire again."""
        if self._resilience is None:
            raise RuntimeError(
                "checkpoint_now() needs TallyConfig(checkpoint="
                "resilience.CheckpointPolicy(...)); for one-off manual "
                "saves use utils.save_tally_state"
            )
        out = self._resilience.save(self, reason="manual", meta=meta)
        if self._resilience.drain_requested:
            self._resilience.close()  # hand the signals back
            raise SystemExit(0)
        return out

    def resume_latest(self):
        """Restore the newest intact checkpoint generation from the
        configured policy's directory into this tally (corruption
        fallback included); returns the ``resilience.ResumeInfo`` or
        None when no generation exists yet."""
        from pumiumtally_tpu.resilience import resume_latest

        return resume_latest(self)

    # -- runtime sentinels (TallyConfig.sentinel) ------------------------
    def _engine_poisoned(self) -> bool:
        """Whether this tally's engine state is known-corrupt (the
        partitioned facades also consult their engines' latches)."""
        return self._poisoned

    def _check_poisoned(self) -> None:
        if self._engine_poisoned():
            from pumiumtally_tpu.sentinel.policy import (
                EnginePoisonedError,
                POISONED_MESSAGE,
            )

            raise EnginePoisonedError(POISONED_MESSAGE)

    def health_report(self):
        """The cumulative ``sentinel.HealthReport`` of this campaign
        (audited moves, anomaly mask union, worst conservation
        residual, straggler/overflow ladder outcomes). Requires
        ``TallyConfig(sentinel=SentinelPolicy(...))``."""
        if self._sentinel is None:
            raise RuntimeError(
                "runtime sentinels are disabled; construct the tally "
                "with TallyConfig(sentinel=sentinel.SentinelPolicy())"
            )
        return self._sentinel.health_report()

    def _sentinel_post_move(self, x_start, dests, fly, w, done, s_b):
        """Audit one committed move and run the straggler-escalation
        ladder over its unfinished residue (sentinel package
        docstring). ``x_start`` is the phase-B start (staged origins,
        or the pre-move committed positions in continue mode) and
        ``s_b`` the phase-B ray coordinates — together they let the
        retry CONTINUE the exact original parametrization, which is
        what makes recovered flux bitwise. All arrays are the facade's
        padded caller-order views. Returns the found-all verdict the
        protocol check consumes."""
        pol = self.config.sentinel
        n_unf, mask = self._sentinel.audit(
            x_start, self.x, fly, w, done, self.flux
        )
        recovered = lost = 0
        ok = done
        if n_unf and pol.straggler_retry:
            from pumiumtally_tpu.sentinel.straggler import run_ladder

            unfinished = np.asarray(~done & (fly == 1))
            sc = None
            if self._scoring is not None:
                # The retry must CONTINUE the scoring lanes too: same
                # bins/factors the interrupted move staged.
                sbin, sfac = self._last_score_ops
                sc = (self._scoring.spec.kinds, self._score_bank,
                      sbin, sfac)
            x2, e2, flux2, rec_idx, lost_idx, bank2 = run_ladder(
                self.mesh, self.x, self.elem, dests, fly, w, self.flux,
                unfinished,
                tol=self._tol, base_iters=self._max_iters,
                retry_factor=pol.retry_iters_factor,
                walk_kw=self._walk_kw,
                two_tier=(self._table_dtype == "bfloat16"),
                x_start=x_start, s_init=s_b, scoring=sc,
            )
            self.x, self.elem, self.flux = x2, e2, flux2
            if sc is not None:
                self._score_bank = bank2
            recovered, lost = int(rec_idx.size), int(lost_idx.size)
            if lost:
                self._lost_total += lost
                self._quarantine_lost(lost_idx, x_start, dests, w)
            # The ladder tallied after the audit snapshotted the flux
            # sum — re-baseline so the next conservation delta is
            # clean.
            self._sentinel.resync(self.flux)
            ok = lost == 0
        self._sentinel.note_outcome(
            mask, n_unf, recovered, lost, self.iter_count
        )
        return ok

    def _sentinel_post_localize(self, dest, done):
        """Non-tallying localization ladder: a localization walk that
        exhausts ``max_iters`` would seed the whole campaign from
        partial positions — re-walk the residue with the escalated
        budget and ZERO weights (flux is untouched bitwise; the retry
        program is the same ``straggler_retry`` entry point). Returns
        the updated done mask."""
        if self._sentinel is None or not (
            self.config.sentinel.straggler_retry
        ):
            return done
        unfinished = np.asarray(~done)
        if not unfinished.any():
            return done
        from pumiumtally_tpu.sentinel.straggler import run_ladder

        pol = self.config.sentinel
        fly = jnp.ones((self._cap,), jnp.int8)
        w0 = jnp.zeros((self._cap,), self.dtype)
        x2, e2, _flux, rec_idx, lost_idx, _bank = run_ladder(
            self.mesh, self.x, self.elem, dest, fly, w0, self.flux,
            unfinished,
            tol=self._tol, base_iters=self._max_iters,
            retry_factor=pol.retry_iters_factor, walk_kw=self._walk_kw,
            two_tier=(self._table_dtype == "bfloat16"),
        )
        # flux is deliberately NOT reassigned: zero-weight retries add
        # exact zeros, so the returned array is bitwise-equal anyway.
        self.x, self.elem = x2, e2
        self._sentinel.note_localization(rec_idx.size, lost_idx.size)
        dn = np.asarray(done).copy()
        dn[rec_idx] = True
        return jnp.asarray(dn)

    def _quarantine_lost(self, idx: np.ndarray, x_start, dests, w,
                         reason: str = "iteration_budget") -> None:
        """Append one quarantine record per unrecoverable particle
        (pid, origin, dest, element, weight, move) — the postmortem
        payload for re-injection; no-op file-wise without a
        ``quarantine_dir`` (the health report still counts them)."""
        from pumiumtally_tpu.sentinel.quarantine import (
            append_quarantine,
            build_records,
        )

        sel = jnp.asarray(idx)
        append_quarantine(
            self.config.sentinel.quarantine_dir,
            build_records(
                idx, np.asarray(x_start[sel]), np.asarray(dests[sel]),
                np.asarray(self.elem[sel]), np.asarray(w[sel]),
                self.iter_count, reason=reason,
            ),
        )

    # -- leakage accounting ----------------------------------------------
    def _current_lost(self) -> int:
        """Particles currently excluded from transport (source in no
        mesh element). Non-partitioned engines clamp out-of-hull
        sources to the boundary instead of dropping them, so only the
        partitioned facades override this."""
        return 0

    def _roll_lost(self) -> None:
        """Fold the closing batch's still-lost particles into the
        cumulative counter (called at each re-sourcing, BEFORE the new
        localization resets the engine's lost flags; revived particles
        rejoined transport and are correctly not counted)."""
        self._lost_total += self._current_lost()

    @property
    def lost_particles(self) -> int:
        """Cumulative count of particles dropped from transport over
        the whole campaign (every facade; written into the VTK output's
        field data so campaigns can account for leakage). Monolithic /
        sharded / plain-streaming engines clamp out-of-domain sources
        rather than dropping them, so this is nonzero only for the
        partitioned engines' lost-particle path (api/streaming.py
        warn-and-drop)."""
        return self._lost_total + self._current_lost()

    # -- batch statistics (TallyConfig.batch_stats) ----------------------
    def _stats_roll_batch(self) -> None:
        """Batch boundary hook: every ``CopyInitialPosition`` closes
        the open source batch (if any moves landed in it) and opens a
        new one at the current flux. No-op with stats disabled."""
        if self._stats is not None:
            self._stats.close(self.flux, reopen=True)
            self._score_stats_close(reopen=True)

    def _stats_note_move(self) -> None:
        if self._stats is not None:
            self._stats.note_move()
        if self._score_stats is not None:
            self._score_stats.note_move()

    def _require_stats(self):
        if self._stats is None:
            raise RuntimeError(
                "batch statistics are disabled; construct the tally "
                "with TallyConfig(batch_stats=True)"
            )
        return self._stats

    def _stats_elapsed(self) -> Optional[float]:
        """Transport seconds for the figure of merit (TallyTimes'
        fenced accumulation); None before any move completes."""
        t = self.tally_times.total_time_to_tally
        return t if t > 0.0 else None

    def close_batch(self, trigger=None):
        """Close the open source batch into the statistics lanes and
        open the next one (one jitted [E] lane update, no host sync).

        When a ``stats.TriggerSpec`` is passed — or
        ``TallyConfig.batch_stats_trigger`` is set — the trigger is
        evaluated right after the close (one jitted reduction + a
        single scalar D2H) and its ``TriggerResult`` returned: the
        stop decision for a driver loop
        (``if result.converged: break``), plus a 1/sqrt(N)-law
        estimate of the batches remaining. Returns None when no
        trigger spec is available. A batch with zero moves closes as
        a no-op (an empty batch is not a sample)."""
        stats = self._require_stats()
        stats.close(self.flux, reopen=True)
        self._score_stats_close(reopen=True)
        self._resilience_roll_batch()  # explicit close = batch close
        spec = (
            trigger if trigger is not None
            else self.config.batch_stats_trigger
        )
        if spec is None:
            return None
        from pumiumtally_tpu.stats.triggers import evaluate_trigger

        return evaluate_trigger(stats, spec)

    def finalize(self):
        """Close the open batch WITHOUT opening another and return the
        final ``BatchStatistics``. Moves after ``finalize()`` are not
        attributed to any batch until the next ``CopyInitialPosition``
        (or ``close_batch``) opens one."""
        stats = self._require_stats()
        stats.close(self.flux, reopen=False)
        self._score_stats_close(reopen=False)
        self._resilience_roll_batch()  # final close = batch close
        return self.batch_statistics()

    def batch_statistics(self):
        """Current ``stats.BatchStatistics`` view (closed batches
        only — an open batch contributes nothing until it closes).
        Needs >= 1 closed batch for ``mean`` and >= 2 for the
        variance-derived fields."""
        from pumiumtally_tpu.stats import BatchStatistics

        stats = self._require_stats()
        return BatchStatistics(
            flux_sum=stats.flux_sum,
            flux_sq_sum=stats.flux_sq_sum,
            num_batches=stats.num_batches,
            elapsed_seconds=self._stats_elapsed(),
        )

    # -- filtered scoring (TallyConfig.scoring, round 10) -----------------
    def _arm_scoring(self, bank_size: Optional[int] = None) -> None:
        """Build the ScoringRuntime once the facade's bank geometry is
        known (``bank_size`` = the padded lane-bank length for the
        partitioned facades; None = the canonical ``E·B·S``). Also
        arms the optional scoring statistics lanes — with
        ``batch_stats=True`` the scoring bank gets its own per-batch
        (sum, sum-of-squares) accumulator, exactly like the flux lane
        ("stats accumulators gain scoring lanes")."""
        if self.config.scoring is None:
            return
        from pumiumtally_tpu.scoring.binding import ScoringRuntime

        self._scoring = ScoringRuntime(
            self.config.scoring, self.mesh.nelems, self.dtype,
            bank_size=bank_size,
        )
        if self.config.batch_stats:
            from pumiumtally_tpu.stats import BatchAccumulator

            self._score_stats = BatchAccumulator(
                self.mesh.nelems * self._scoring.stride, self.dtype
            )

    def _require_scoring(self):
        if self._scoring is None:
            raise RuntimeError(
                "filtered scoring is disabled; construct the tally "
                "with TallyConfig(scoring=scoring.ScoringSpec(...))"
            )
        return self._scoring

    @property
    def score_bank(self) -> jnp.ndarray:
        """The accumulated scoring lanes, CANONICAL flattened
        ``[E·B·S]`` layout in original element order (partitioned /
        streaming facades override the assembly)."""
        self._require_scoring()
        return self._score_bank

    def score_array(self) -> jnp.ndarray:
        """The scoring lanes as ``[E, n_bins, n_scores]`` — bin-major,
        score-minor; ``spec.scores`` names the last axis."""
        rt = self._require_scoring()
        return self.score_bank.reshape(
            self.mesh.nelems, rt.spec.n_bins, rt.spec.n_scores
        )

    def score_statistics(self):
        """Per-batch ``BatchStatistics`` over the FLATTENED scoring
        lanes (mean/std dev/rel err per lane) — needs both
        ``batch_stats=True`` and a scoring spec."""
        from pumiumtally_tpu.stats import BatchStatistics

        self._require_scoring()
        self._require_stats()
        return BatchStatistics(
            flux_sum=self._score_stats.flux_sum,
            flux_sq_sum=self._score_stats.flux_sq_sum,
            num_batches=self._score_stats.num_batches,
            elapsed_seconds=self._stats_elapsed(),
        )

    def _score_args_check(self, energy, time_) -> None:
        """Refuse mismatched energy=/time= combinations with errors
        that NAME the argument (narrow prevalidation — the alternative
        is an opaque trace failure deep in the move)."""
        if self._scoring is None:
            if energy is not None or time_ is not None:
                raise ValueError(
                    "energy=/time= require TallyConfig(scoring="
                    "scoring.ScoringSpec(...)); this tally has no "
                    "scoring lanes to bin them into"
                )
            return
        spec = self._scoring.spec
        if spec.needs_energy and energy is None:
            raise ValueError(
                "this ScoringSpec bins (or scales) by energy: pass "
                "energy= (one value per particle) to MoveToNextLocation"
            )
        if spec.needs_time and time_ is None:
            raise ValueError(
                "this ScoringSpec bins by time: pass time= (one value "
                "per particle) to MoveToNextLocation"
            )
        if energy is not None and not spec.needs_energy:
            raise ValueError(
                "energy= passed but this ScoringSpec has no "
                "EnergyFilter and no energy-scaled score"
            )
        if time_ is not None and not spec.needs_time:
            raise ValueError(
                "time= passed but this ScoringSpec has no TimeFilter"
            )

    def _stage_move_attr(self, buf, what: str) -> Optional[jnp.ndarray]:
        """Validate + stage one per-particle move attribute ([n],
        working dtype): shape errors name the argument
        (host_scalar_field) and the finite check runs AFTER the
        working-dtype cast, like every other staged buffer."""
        if buf is None:
            return None
        a = host_scalar_field(buf, self.num_particles, what)
        cast = np.asarray(a, dtype=np.dtype(self.dtype))
        if self.config.validate_inputs:
            check_finite(cast, what)
        return jnp.asarray(self._owned(cast))

    def _resolve_move_scoring(self, energy, time_):
        """Per-move scoring operands: validate, stage, resolve bins +
        factor rows (jitted ``score_bins``), pad to capacity. Returns
        (sbin, sfac) or (None, None) with scoring off."""
        self._score_args_check(energy, time_)
        if self._scoring is None:
            return None, None
        e_dev = self._stage_move_attr(energy, "energy")
        t_dev = self._stage_move_attr(time_, "time")
        # Unpadded [n] rows: _dispatch_move pads to engine capacity
        # where the other staged inputs do (the partitioned facades
        # size their engines to n and consume these as-is).
        return self._scoring.resolve(e_dev, t_dev, self.num_particles)

    def _score_stats_close(self, reopen: bool) -> None:
        """Scoring arm of every batch-close hook (no-op unless both
        stats and scoring are armed)."""
        if self._score_stats is not None:
            self._score_stats.close(self.score_bank, reopen=reopen)

    # -- the three-call protocol ----------------------------------------
    def CopyInitialPosition(self, init_particle_positions, size: Optional[int] = None):
        """Localize particles to the host app's sampled source points
        (reference PumiTally.h:66-67; non-tallying initial search,
        PumiTallyImpl.cpp:54-64)."""
        self._check_poisoned()
        t0 = time.perf_counter()
        self._stats_roll_batch()  # each sourcing opens a new batch
        self._resilience_roll_batch()  # autosave/drain at batch close
        self._roll_lost()  # fold the closed batch's leakage
        self._last_dests_host = None  # localization rewrites the state
        self._last_dests_dev = None
        self._echo_misses = 0  # new batch: re-arm the echo detector
        self._xpoint_stash = None  # xpoints reset to the new positions
        dest = self._as_positions(init_particle_positions, size)
        found_all, n_exited = self._dispatch_localize(dest)
        if self.config.check_found_all:
            if not bool(found_all):
                print(
                    "ERROR: Not all particles are found. May need more loops "
                    "in search"
                )
            nex = int(n_exited)
            if nex:
                # The straight walk from element 0's centroid left the
                # domain before reaching the source point — happens only on
                # non-convex geometry, which the reference also requires to
                # be convex (reference README.md:112-113).
                print(
                    f"WARNING: {nex} particles exited the domain during "
                    "localization (non-convex mesh?); they were clamped to "
                    "the boundary"
                )
        self.is_initialized = True
        if self.config.fenced_timing:
            jax.block_until_ready(self.x)
        self.tally_times.initialization_time += time.perf_counter() - t0

    def _dispatch_localize(self, dest: jnp.ndarray):
        """Run the non-tallying localization on [n]-shaped staged
        destinations. Returns (found_all, n_exited) — lazily evaluated
        scalars (only fetched when check_found_all is on)."""
        dest = self._pad_particles(dest, self.x)
        if self.device_mesh is not None:
            from pumiumtally_tpu.parallel.sharded import (
                sharded_locate,
                sharded_localize_step,
            )

            x, elem = self.x, self.elem
            if self.config.localization == "locate":
                # Same pre-pass as _localize_by_planes, with the points
                # sharded over dp and the tables replicated.
                x, elem = adopt_located(
                    x, elem, dest,
                    sharded_locate(
                        self.device_mesh, self.mesh, dest, tol=self._tol
                    ),
                )
            self.x, self.elem, done, exited = sharded_localize_step(
                self.device_mesh, self.mesh, x, elem, dest,
                tol=self._tol, max_iters=self._max_iters,
                walk_kw=self._walk_kw,
            )
            done = self._sentinel_post_localize(dest, done)
            return jnp.all(done), jnp.sum(exited)
        if self.config.localization == "locate":
            return self._localize_by_planes(dest)
        self.x, self.elem, done, exited = _localize_step(
            self.mesh, self.x, self.elem, dest,
            tol=self._tol, max_iters=self._max_iters,
            walk_kw=self._walk_kw,
        )
        done = self._sentinel_post_localize(dest, done)
        return jnp.all(done), jnp.sum(exited)

    def _localize_by_planes(self, dest: jnp.ndarray):
        """TallyConfig.localization="locate": direct MXU point location
        (one half-space matmul pass instead of an O(mesh-diameter)
        walk). Points located in no element keep walking from the
        CURRENT committed state exactly as "walk" mode would (clamping
        at the hull); located particles enter that walk already at
        their destination, so it retires them on its first iteration
        group. No host sync, no branch — the masked walk is dispatched
        unconditionally and is near-free when everything was located."""
        x, elem = locate_or_committed(
            self.mesh, self.x, self.elem, dest, tol=self._tol
        )
        self.x, self.elem, done, exited = _localize_step(
            self.mesh, x, elem, dest,
            tol=self._tol, max_iters=self._max_iters,
            walk_kw=self._walk_kw,
        )
        done = self._sentinel_post_localize(dest, done)
        return jnp.all(done), jnp.sum(exited)

    def MoveToNextLocation(
        self, particle_origin, particle_destinations, flying=None, weights=None,
        size: Optional[int] = None, energy=None, time=None,
    ):
        """Two-phase tracked move (reference PumiTally.h:87-89).

        ``flying`` is zeroed in place after staging, matching the
        reference's host-side side effect (PumiTallyImpl.cpp:169-172).

        TPU-native extensions beyond the reference protocol (each skips
        host→device staging, the scarce resource when the physics app
        drives the tally from a remote host):

        - ``particle_origin=None``: continue from the committed
          positions — valid whenever no particle was resampled since the
          last move (then the reference's phase A walks zero distance,
          PumiTallyImpl.cpp:88-109); phase A is skipped entirely.
        - ``flying=None``: every particle is in flight; no host-side
          zeroing side effect is performed (there is no buffer to zero).
        - ``weights=None``: unit weights.
        - ``energy=`` / ``time=`` (round 10): per-particle attribute
          arrays ([n] values) for a ``TallyConfig.scoring`` spec's
          filters and energy-scaled scores — validated with errors
          that name the argument, refused when no scoring is armed.
        """
        # Poisoned check FIRST: a corrupt engine must refuse with the
        # resume-from-checkpoint error whatever else is wrong.
        self._check_poisoned()
        if not self.is_initialized:
            raise RuntimeError(
                "CopyInitialPosition must be called before MoveToNextLocation "
                "(reference invariant, PumiTallyImpl.cpp:437-438)"
            )
        t0 = _perf_counter()
        dests_host = self._as_positions_host(particle_destinations, size,
                                             what="destinations")
        # Convert the origins buffer at most once (a list / non-f64
        # input would otherwise convert in the echo probe AND again on
        # the miss-path cast).
        origins_h = (
            None
            if particle_origin is None
            else host_positions(particle_origin, size, self.num_particles)
        )
        origins: Optional[jnp.ndarray]
        if self._origins_echo_raw(origins_h, size):
            # The staged origins echo the previous destinations in the
            # working dtype — substitute the device array that staged
            # them last move instead of uploading the same bytes again.
            # Bit-exact: phase A still runs on device (against values
            # identical to the caller's origins), and the device-side
            # trivial check skips its walk whenever every particle
            # committed its destination. See TallyConfig.auto_continue.
            origins = self._last_dests_dev
        elif origins_h is None:
            origins = None
        else:
            origins = jnp.asarray(
                self._owned(self._as_positions_cast(origins_h, size,
                                                    what="origins"))
            )
        dests = jnp.asarray(dests_host)
        n = self.num_particles
        if flying is None:
            fly = self._cached_ones("fly")
        else:
            flying_np = np.asarray(flying)
            if flying_np.size < n:
                raise ValueError(
                    f"flying buffer has {flying_np.size} values, need {n}"
                )
            fly_cast = flying_np.reshape(-1)[:n].astype(np.int8, copy=False)
            if self.config.auto_continue and np.all(fly_cast == 1):
                # All in flight — the common physics batch; reuse the
                # cached device ones instead of uploading n bytes.
                fly = self._cached_ones("fly")
            else:
                # Copy BEFORE staging: jnp.asarray on the CPU backend
                # may alias the caller's buffer zero-copy, and we are
                # about to zero that buffer in place below — without
                # the copy the staged flags would be zeroed too and no
                # particle would fly.
                fly = jnp.asarray(self._owned(fly_cast))
        if weights is None:
            w = self._cached_ones("w")
        else:
            weights_np = np.asarray(weights, dtype=np.float64).reshape(-1)
            if weights_np.size < n:
                raise ValueError(
                    f"weights buffer has {weights_np.size} values, need {n}"
                )
            # numpy pre-cast before transfer — see _as_positions_cast.
            w_cast = np.asarray(weights_np[:n], dtype=np.dtype(self.dtype))
            if self.config.validate_inputs:
                check_finite(w_cast, "weights")
            if (
                self.config.auto_continue
                and self._last_weights_host is not None
                and np.array_equal(w_cast, self._last_weights_host)
            ):
                # Unchanged statistical weights (echo of the previous
                # batch): reuse the device array already holding them.
                # Pure input caching — needs no engine-state proof.
                w = self._last_weights_dev
            else:
                w_cast = self._owned(w_cast)
                w = jnp.asarray(w_cast)
                if self.config.auto_continue:
                    self._last_weights_host = w_cast
                    self._last_weights_dev = w
        # Scoring validation/staging BEFORE the flying-zeroing side
        # effect: a refused move (missing/invalid energy=/time=) must
        # leave the caller's buffers untouched — zeroing first would
        # make the caller's corrected retry silently transport nothing
        # (the streaming facade validates before any staging for the
        # same reason).
        sbin, sfac = self._resolve_move_scoring(energy, time)
        zero_flying_side_effect(flying, n)

        found_all = self._dispatch_move(origins, dests, fly, w, sbin, sfac)
        if origins_h is not None and self._retain_echo_snapshots():
            # _as_positions_host returned OWNED memory, so these
            # snapshots cannot alias a caller buffer that gets recycled
            # next call. Only retained for origin-passing drivers (the
            # ones that can echo, and have not proven themselves
            # never-echoing) — a continue-mode driver would pin an
            # extra [n,3] on device and host for nothing. A stale
            # snapshot is value-correct by construction: the echo
            # substitutes bytes equal to whatever the caller passed.
            self._last_dests_host = dests_host
            self._last_dests_dev = dests
        self.iter_count += 1
        self._stats_note_move()
        # found_all may be a per-particle mask (round 9) or an
        # engine-reduced verdict — jnp.all covers both.
        if self.config.check_found_all and not bool(jnp.all(found_all)):
            print("ERROR: Not all particles are found. May need more loops in search")
        if self.config.fenced_timing:
            jax.block_until_ready(self.flux)
        self.tally_times.total_time_to_tally += _perf_counter() - t0
        self._resilience_note_move()  # drain/timer-cadence safe point

    def _dispatch_move(self, origins, dests, fly, w, sbin=None, sfac=None):
        """Run one tallied move from [n]-shaped staged inputs
        (origins may be None: continue mode; ``sbin``/``sfac`` are the
        capacity-padded scoring operands, None with scoring off).
        Returns found_all (lazy)."""
        dests = self._pad_particles(dests, self.x)
        fly = self._pad_particles(fly, jnp.zeros((self._cap,), jnp.int8))
        w = self._pad_particles(w, jnp.zeros((self._cap,), self.dtype))
        if origins is not None:
            origins = self._pad_particles(origins, self.x)
        if self.config.record_xpoints:
            # Pre-move committed state + staged inputs: everything
            # intersection_points() needs to replay this move.
            self._xpoint_stash = (self.x, self.elem, origins, dests, fly)
        score_kw = {}
        if self._scoring is not None:
            sbin = self._pad_particles(
                sbin, jnp.zeros((self._cap,), jnp.int32)
            )
            sfac = self._pad_particles(
                sfac,
                jnp.zeros(
                    (self._cap, self._scoring.spec.n_scores), self.dtype
                ),
            )
            self._last_score_ops = (sbin, sfac)  # the ladder's operands
            score_kw = {
                "score_kinds": self._scoring.spec.kinds,
                "score_ops": (self._score_bank, sbin, sfac),
            }
        if self.device_mesh is not None:
            from pumiumtally_tpu.parallel.sharded import (
                sharded_move_step,
                sharded_move_step_continue,
            )

            if origins is None:
                step = partial(
                    sharded_move_step_continue, self.device_mesh, self.mesh,
                    self.x, self.elem, dests,
                )
            else:
                step = partial(
                    sharded_move_step, self.device_mesh, self.mesh,
                    self.x, self.elem, origins, dests,
                )
        elif origins is None:
            step = partial(
                _move_step_continue, self.mesh, self.x, self.elem, dests
            )
        else:
            step = partial(
                _move_step, self.mesh, self.x, self.elem, origins, dests
            )
        x_prev = self.x  # phase-B start in continue mode (sentinel)
        res = step(
            fly, w, self.flux, tol=self._tol, max_iters=self._max_iters,
            walk_kw=self._walk_kw, **score_kw,
        )
        self.x, self.elem, self.flux, done, s_b = res[:5]
        if self._scoring is not None:
            self._score_bank = res[5]
        if self._sentinel is None:
            return done
        return self._sentinel_post_move(
            x_prev if origins is None else origins, dests, fly, w, done,
            s_b,
        )

    # -- cross-session fusion surface (round 12, service/fusion.py) ------
    def _fusion_key(self):
        """The co-fusability identity of this facade's moves, or None
        when its moves must never share a fused launch.

        Two sessions may pack one padded slab and run ONE walk iff
        their moves already lower through the same program family:
        same mesh (the fused walk gathers from ONE table set — object
        identity, since value comparison would cost an [E]-sized scan
        per pick), same working dtype, and the same static walk
        configuration (tolerance, iteration budget, walk_kw, table
        tier). A scoring spec joins through its STATIC key only — edge
        values are per-session operands, exactly as in a solo move.
        Host-side subsystems (sentinel, stats, resilience, timing,
        validation) run per-session after the shared launch and do not
        key. Conservative by construction: subclasses (streaming,
        partitioned — their moves are chunked/multi-launch, and the
        chunk-major scatter order that defines their bitwise contract
        cannot survive coalescing), sharded facades, and xpoint
        recorders never fuse."""
        if type(self) is not PumiTally:
            return None
        if self.device_mesh is not None or self.config.record_xpoints:
            return None
        spec = self.config.scoring
        return (
            "mono",
            id(self.mesh),
            str(np.dtype(self.dtype)),
            self._tol,
            self._max_iters,
            self._walk_kw,
            self._table_dtype,
            None if spec is None else spec.static_key(),
        )

    def _fused_move_stage(self, op) -> "FusedMoveStage":
        """The host half of one move, for a fused group: cast the
        PREVALIDATED staged op's buffers to the working dtype and
        resolve the scoring operands, mutating NO facade state — a
        later pack/launch failure can fall back to the solo path (or
        land on exactly this session's future) with the campaign
        untouched. ``op`` is a service ``StagedOp`` whose buffers
        already passed submit-time validation (service/staging.py), so
        no finite/shape checks re-run here; the protocol-order checks
        that gate a solo move (poisoned latch, initialization) DO
        re-run, with the same errors."""
        self._check_poisoned()
        if not self.is_initialized:
            raise RuntimeError(
                "CopyInitialPosition must be called before "
                "MoveToNextLocation (reference invariant, "
                "PumiTallyImpl.cpp:437-438)"
            )
        n = self.num_particles
        wd = np.dtype(self.dtype)
        sbin, sfac = self._resolve_move_scoring(op.energy, op.time)
        return FusedMoveStage(
            dests=np.asarray(op.dests.reshape(n, 3), dtype=wd),
            origins=(
                None if op.origins is None
                else np.asarray(op.origins.reshape(n, 3), dtype=wd)
            ),
            fly=op.flying,
            w=(
                None if op.weights is None
                else np.asarray(op.weights, dtype=wd)
            ),
            sbin=sbin,
            sfac=sfac,
            x_prev=self.x,
        )

    def _fused_move_commit(self, res, stage: "FusedMoveStage", t0: float,
                           sentinel_ops=None) -> None:
        """The state half of one fused move: adopt this session's
        slice of the shared launch and run the solo move's post-walk
        sequence in the solo order (scoring bank + ladder operands,
        sentinel audit/ladder, iter/stats counters, found-all check,
        fence, timing, resilience move hook). ``res`` is
        ``(x, elem, flux, done, s, bank-or-None)``; ``sentinel_ops``
        — ``(x_start, dests, fly, w)`` device views — is required iff
        a sentinel is armed. ``t0`` is the GROUP's staging start, so
        every co-fused session's TallyTimes carries the wall time its
        move actually took (the shared launch is each move's launch).
        The auto-continue echo snapshots are left as they were: the
        fused pack stages from host slabs, so there is no upload to
        skip, and a stale snapshot is value-correct by construction
        (the echo substitutes bytes equal to whatever the caller
        passed)."""
        x2, elem2, flux2, done, s_b, bank2 = res
        self.x, self.elem, self.flux = x2, elem2, flux2
        if self._scoring is not None:
            self._score_bank = bank2
            self._last_score_ops = (stage.sbin, stage.sfac)
        found_all = done
        if self._sentinel is not None:
            x_start, dests_dev, fly_dev, w_dev = sentinel_ops
            found_all = self._sentinel_post_move(
                x_start, dests_dev, fly_dev, w_dev, done, s_b
            )
        self.iter_count += 1
        self._stats_note_move()
        if self.config.check_found_all and not bool(jnp.all(found_all)):
            print(
                "ERROR: Not all particles are found. May need more loops "
                "in search"
            )
        if self.config.fenced_timing:
            jax.block_until_ready(self.flux)
        self.tally_times.total_time_to_tally += _perf_counter() - t0
        self._resilience_note_move()  # drain/timer-cadence safe point

    def _stats_vtk_cell_data(self) -> dict:
        """Optional flux_mean/rel_err cell arrays for the VTK payload
        (io.vtk.stats_cell_data): empty with stats disabled or no
        closed batch, so the default file matches the reference's
        flux+volume layout exactly."""
        from pumiumtally_tpu.io.vtk import stats_cell_data

        if self._stats is None or self._stats.num_batches < 1:
            return {}
        return stats_cell_data(
            self.batch_statistics(), np.asarray(self.mesh.volumes)
        )

    def _scoring_vtk_cell_data(self) -> dict:
        """Optional ``<score>_bin<k>`` cell arrays (round 10): every
        lane volume-normalized like flux, empty with scoring off so the
        default payload stays byte-identical."""
        if self._scoring is None:
            return {}
        from pumiumtally_tpu.scoring.binding import score_cell_data

        return score_cell_data(
            self._scoring.spec, np.asarray(self.score_bank),
            np.asarray(self.mesh.volumes),
        )

    def WriteTallyResults(self, filename: Optional[str] = None) -> None:
        """Normalize flux by element volume and write VTK
        (reference PumiTallyImpl.cpp:151-157, 382-416). With batch
        statistics enabled and >= 1 closed batch, ``flux_mean`` and
        (from 2 batches) ``rel_err`` cell arrays ride beside the
        reference's flux+volume payload."""
        self._check_poisoned()
        t0 = time.perf_counter()
        out = filename or self.config.output_filename
        normalized = self.normalized_flux()
        from pumiumtally_tpu.io.vtk import merge_cell_data

        write_vtk(
            out,
            np.asarray(self.mesh.coords),
            np.asarray(self.mesh.tet2vert),
            cell_data=merge_cell_data(
                {
                    "flux": np.asarray(normalized),
                    "volume": np.asarray(self.mesh.volumes),
                },
                self._stats_vtk_cell_data(),
                self._scoring_vtk_cell_data(),
            ),
            field_data=self._vtk_field_data(),
        )
        self.tally_times.vtk_file_write_time += time.perf_counter() - t0
        self.tally_times.print_times()

    def _vtk_field_data(self) -> dict:
        """Campaign-level (non-per-cell) payload for the VTK writers:
        the cumulative lost-particle counter, so a result file accounts
        for its own leakage — plus, with a sentinel armed, the health
        report (audited moves, anomaly mask, worst conservation
        residual, ladder outcomes), so a result file carries its own
        health record."""
        out = {
            "lost_particles": np.asarray(
                [float(self.lost_particles)], np.float64
            ),
        }
        if self._sentinel is not None:
            from pumiumtally_tpu.io.vtk import health_field_data

            out.update(health_field_data(self.health_report()))
        return out

    # -- inspection (white-box surface used by the parity suite) ---------
    def normalized_flux(self) -> jnp.ndarray:
        """flux / element volume (reference NormalizeFlux,
        PumiTallyImpl.cpp:382-409 — deliberately NOT divided by total
        weight, matching the code rather than the README claim)."""
        return self.flux / self.mesh.volumes

    @property
    def elem_ids(self) -> np.ndarray:
        """Current element of each particle (reference
        ``ParticleTracer::getElementIds``, test:154)."""
        return np.asarray(self.elem)[: self.num_particles]

    @property
    def positions(self) -> np.ndarray:
        """Committed particle positions (reference particle origin
        segment get<0>, post-search)."""
        return np.asarray(self.x)[: self.num_particles]

    def intersection_points(self) -> np.ndarray:
        """Each particle's last face-intersection point — the
        reference's ``getIntersectionPoints()`` white-box debug surface
        (PumiTallyImpl.h:177-178; test:464-467). Requires
        ``TallyConfig.record_xpoints=True``.

        Before any move (or for particles that crossed no face in the
        last move) this is the particle's starting position, matching
        the reference's ``UpdatePreviousXPoints(ptcls)`` initialization.
        The production walk's s-parametrization discards per-crossing
        positions, so this accessor REPLAYS the last move's transport
        with an uncompacted recording walk (ops/walk.py walk_xpoints) —
        an inspection path, not a hot path.
        """
        if not self.config.record_xpoints:
            raise RuntimeError(
                "intersection_points() needs TallyConfig.record_xpoints="
                "True (the facade does not retain move inputs otherwise)"
            )
        if not self.is_initialized:
            raise RuntimeError(
                "CopyInitialPosition must be called before "
                "intersection_points()"
            )
        from pumiumtally_tpu.ops.walk import walk_xpoints

        if type(self)._dispatch_move is not PumiTally._dispatch_move or (
            type(self).MoveToNextLocation is not PumiTally.MoveToNextLocation
        ):
            # A subclass routing moves through its own engine never
            # populates the stash — returning start positions as
            # "intersection points" would be silently wrong data.
            raise NotImplementedError(
                f"intersection_points() is implemented for the "
                f"monolithic/sharded PumiTally facade only, not "
                f"{type(self).__name__}"
            )
        if self.device_mesh is not None:
            # The stash holds device arrays sharded over the particle
            # axis, but the replay below would run walk_xpoints
            # monolithically — an untested mixing of layouts (ADVICE
            # r5). Refuse loudly until a sharded replay exists.
            raise NotImplementedError(
                "intersection_points() replay does not support a "
                "device_mesh yet: the sharded replay path is untested. "
                "Drop device_mesh (or record_xpoints) to use this "
                "debug surface"
            )
        stash = getattr(self, "_xpoint_stash", None)
        if stash is None:
            return self.positions  # no move yet: xpoints = start points
        x0, e0, origins, dests, fly = stash
        if origins is not None:
            # Phase A relocation: recover the phase-B start state —
            # skipped when it would walk zero distance (move_step's own
            # trivial-skip; the common origins-echo case). The replay
            # records only phase-B crossings; in the reference a
            # NON-trivial phase A would also touch inter_points, but
            # phase A normally walks zero distance (origins echo the
            # committed positions), where the two agree exactly.
            dest_a = jnp.where((fly == 1)[:, None], origins, x0)
            if not bool(jnp.all(dest_a == x0)):
                x0, e0, _, _ = _localize_step(
                    self.mesh, x0, e0, dest_a, tol=self._tol,
                    max_iters=self._max_iters, walk_kw=self._walk_kw,
                )
        xp = walk_xpoints(
            self.mesh, x0, e0, dests, fly,
            tol=self._tol, max_iters=self._max_iters,
            table_dtype=self._table_dtype,
        )
        return np.asarray(xp)[: self.num_particles]
