"""Engine factory for the native C ABI.

The C boundary (native/pumiumtally_c.h) keeps the reference's
builtin-typed constructor signature — ``(mesh_filename,
num_particles)``, reference PumiTally.h:50 — so engine selection for a
physics host app happens through the environment, the same way the
reference selects its Kokkos backend at build time:

    PUMIUMTALLY_ENGINE            mono (default) | streaming |
                                  partitioned | streaming_partitioned
    PUMIUMTALLY_DEVICES           device-mesh size (default: all local
                                  devices; implies the sharded
                                  replicated mode for `mono`/`streaming`)
    PUMIUMTALLY_CHUNK_SIZE        streaming chunk size (default 1e6)
    PUMIUMTALLY_CAPACITY_FACTOR   partitioned slot over-provisioning
    PUMIUMTALLY_VMEM_MAX_ELEMS    partitioned engines: per-chip element
                                  bound under which the local walk runs
                                  as the VMEM one-hot MXU Pallas kernel
                                  (TallyConfig.walk_vmem_max_elems)
    PUMIUMTALLY_BLOCK_KERNEL      partitioned engines: vmem (default) |
                                  gather — which kernel runs the
                                  sub-split per-block local walk
                                  (TallyConfig.walk_block_kernel)
    PUMIUMTALLY_ALLOW_CPU_FALLBACK  1 to ACCEPT running on CPU when the
                                  env requests an accelerator whose
                                  PJRT plugin is not registered in this
                                  (embedded) interpreter; default:
                                  refuse with an error
    PUMIUMTALLY_TOLERANCE         walk tolerance override
    PUMIUMTALLY_OUTPUT            default VTK output path
    PUMIUMTALLY_LOCALIZATION      walk (default) | locate — see
                                  TallyConfig.localization
    PUMIUMTALLY_AUTO_CONTINUE     1 (default) | 0 — host staging dedup
    PUMIUMTALLY_FENCED_TIMING     1 (default) | 0 — 0 enables unfenced
                                  pipelined dispatch and implies
                                  CHECK_FOUND_ALL=0 unless that is set
                                  explicitly (the convergence read-back
                                  is itself a per-move sync)
    PUMIUMTALLY_CHECK_FOUND_ALL   1 (default) | 0 — per-move "Not all
                                  particles are found" check
    PUMIUMTALLY_DEVICE_GROUPS     streaming_partitioned only: split the
                                  device mesh into this many groups
                                  (dp × part hybrid — see
                                  TallyConfig.device_groups)
"""

from __future__ import annotations

import os


def _unregistered_platform_error(e: Exception, plat: str) -> bool:
    """Does this jax error mean the named platform never registered?

    Matches jax's KNOWN phrasings of the no-such-backend error across
    the versions this library has run on (0.4.x through 0.9):

    - "Backend 'x' is not in the list of known backends: ..."
      (xla_bridge.backends(), the JAX_PLATFORMS path)
    - "Unknown backend: 'x' requested, but no platforms that are
      instances of x are present." (backend selection by name)
    - "Unknown backend x" (older spelling of the same)

    Anything else naming the platform — in particular "... failed to
    initialize" from a backend that IS registered but could not come up
    (chip busy, driver error) — is a real error to propagate, not a
    registration gap to paper over. The r5 advice tightened this from
    loose "platform <name>" substring matches, which also caught those
    initialization failures."""
    msg = str(e)
    if "failed to initialize" in msg.lower():
        return False
    markers = (
        "not in the list of known backends",
        "Unknown backend",
        "unknown backend",
    )
    return any(m in msg for m in markers)


def _ensure_backend() -> None:
    """Make the accelerator backend usable inside an embedding host —
    or refuse loudly rather than silently compute on CPU.

    An embedding host initializes CPython itself, so interpreter-
    startup hooks that register PJRT *plugin* backends (installed via
    sitecustomize/.pth) may not have run — while JAX_PLATFORMS in the
    inherited environment still names the plugin's platform. jax then
    refuses to initialize any backend at the first device use, deep
    inside the first jit. In order:

    1. Probe. If a backend initializes, done.
    2. Run the deployment's own startup hook (``import sitecustomize``
       — idempotent if site already ran it) and re-probe: this performs
       whatever PJRT plugin registration the deployment installs,
       driven by its own env vars, without this library hardcoding any
       plugin's API.
    3. Fall back to automatic selection (a BOUNDED probe of the
       remaining named platforms then cpu — never jax's unconstrained
       plugin discovery, which can hang; see below) — but if the env
       named an ACCELERATOR platform and the fallback lands on CPU, a
       physics host would silently get CPU numbers while believing the
       accelerator ran (VERDICT r4 weak #6). Refuse with a clear error
       unless PUMIUMTALLY_ALLOW_CPU_FALLBACK=1 opts in (then warn
       loudly).
    """
    import jax

    from pumiumtally_tpu.utils.logging import get_logger

    plat = os.environ.get("JAX_PLATFORMS", "")
    try:
        jax.devices()
        return
    except RuntimeError as e:
        if not (plat and _unregistered_platform_error(e, plat)):
            raise
    # The named platform never registered here: run the deployment's
    # startup hook ourselves, then re-probe.
    try:
        import sitecustomize  # noqa: F401 — side effect is the point
    except Exception as e:  # noqa: BLE001 — hook absent/broken: fall back
        get_logger().debug("sitecustomize import failed: %s", e)
    try:
        jax.devices()
        get_logger().info(
            "backend for JAX_PLATFORMS=%r registered by running the "
            "deployment's sitecustomize hook in-process", plat
        )
        return
    except RuntimeError as e:
        if not _unregistered_platform_error(e, plat):
            raise
        probe_error = e  # survives the except block's scope cleanup
    # Log the ORIGINAL jax error before discarding it for the
    # fallback: when automatic selection lands somewhere surprising,
    # the original message is the only evidence of WHY the named
    # platform was unusable (ADVICE r5).
    get_logger().warning(
        "JAX_PLATFORMS=%r is not a registered backend in this "
        "(embedded) interpreter (jax said: %s); falling back to "
        "automatic backend selection", plat, probe_error
    )
    # "Automatic" here is a BOUNDED probe, not jax's unconstrained
    # discovery (jax_platforms=None): discovery initializes every
    # installed PJRT plugin, and a plugin whose device is unreachable
    # can block forever inside its init (observed: a libtpu install in
    # a CPU-only container spins waiting for the TPU system) — in
    # exactly the broken-registration environments this path serves.
    # Probe only platforms the deployment NAMED after the failed one,
    # then cpu; each probe is the named-backend path, which fails fast
    # when the platform is absent.
    devs = None
    last_err: Exception = probe_error
    for cand in [p for p in plat.split(",")[1:] if p] + ["cpu"]:
        try:
            jax.config.update("jax_platforms", cand)
            devs = jax.devices()
            break
        except RuntimeError as e:
            last_err = e
    if devs is None:  # not even cpu: surface jax's own error
        raise last_err
    wanted_accel = plat.split(",")[0] not in ("", "cpu")
    if wanted_accel and devs and devs[0].platform == "cpu":
        if os.environ.get("PUMIUMTALLY_ALLOW_CPU_FALLBACK") != "1":
            raise RuntimeError(
                f"JAX_PLATFORMS={plat!r} requested an accelerator but "
                "only the CPU backend is available in this embedded "
                "interpreter (PJRT plugin not registered). Refusing to "
                "run the tally silently on CPU — fix the host's plugin "
                "registration, or set PUMIUMTALLY_ALLOW_CPU_FALLBACK=1 "
                "to accept CPU execution."
            )
        get_logger().warning(
            "ACCELERATOR FALLBACK: JAX_PLATFORMS=%r requested an "
            "accelerator but the tally is running on CPU "
            "(PUMIUMTALLY_ALLOW_CPU_FALLBACK=1). Performance numbers "
            "from this run are CPU numbers.", plat
        )


def native_create(mesh_filename: str, num_particles: int):
    """Build the engine the environment asks for (see module doc)."""
    _ensure_backend()
    from pumiumtally_tpu import (
        PartitionedPumiTally,
        PumiTally,
        StreamingPartitionedTally,
        StreamingTally,
        TallyConfig,
    )

    engine = os.environ.get("PUMIUMTALLY_ENGINE", "mono").lower()
    kwargs = {}
    tol = os.environ.get("PUMIUMTALLY_TOLERANCE")
    if tol:
        kwargs["tolerance"] = float(tol)
    capf = os.environ.get("PUMIUMTALLY_CAPACITY_FACTOR")
    if capf:
        kwargs["capacity_factor"] = float(capf)
    out = os.environ.get("PUMIUMTALLY_OUTPUT")
    if out:
        kwargs["output_filename"] = out
    def env_flag(name: str):
        v = os.environ.get(name, "").strip().lower()
        return None if not v else v not in ("0", "false", "off", "no")

    loc = os.environ.get("PUMIUMTALLY_LOCALIZATION")
    if loc:
        kwargs["localization"] = loc.strip().lower()
    auto = env_flag("PUMIUMTALLY_AUTO_CONTINUE")
    if auto is not None:
        kwargs["auto_continue"] = auto
    vmem = os.environ.get("PUMIUMTALLY_VMEM_MAX_ELEMS")
    if vmem:
        if engine not in ("partitioned", "streaming_partitioned"):
            raise ValueError(
                "PUMIUMTALLY_VMEM_MAX_ELEMS applies only to the "
                f"partitioned engines, not PUMIUMTALLY_ENGINE={engine!r}"
            )
        kwargs["walk_vmem_max_elems"] = int(vmem)
    bk = os.environ.get("PUMIUMTALLY_BLOCK_KERNEL")
    if bk:
        if engine not in ("partitioned", "streaming_partitioned"):
            raise ValueError(
                "PUMIUMTALLY_BLOCK_KERNEL applies only to the "
                f"partitioned engines, not PUMIUMTALLY_ENGINE={engine!r}"
            )
        kwargs["walk_block_kernel"] = bk.strip().lower()
    fenced = env_flag("PUMIUMTALLY_FENCED_TIMING")
    check = env_flag("PUMIUMTALLY_CHECK_FOUND_ALL")
    if fenced is not None:
        kwargs["fenced_timing"] = fenced
        if not fenced and check is None:
            # Unfenced dispatch only pipelines without the per-move
            # convergence read-back; imply it off unless asked for.
            check = False
    if check is not None:
        kwargs["check_found_all"] = check
    groups = os.environ.get("PUMIUMTALLY_DEVICE_GROUPS")
    if groups:
        if engine != "streaming_partitioned":
            raise ValueError(
                "PUMIUMTALLY_DEVICE_GROUPS applies only to "
                f"PUMIUMTALLY_ENGINE=streaming_partitioned, not {engine!r}"
            )
        kwargs["device_groups"] = int(groups)
    ndev = os.environ.get("PUMIUMTALLY_DEVICES")
    partitioned = engine in ("partitioned", "streaming_partitioned")
    if ndev or partitioned:
        from pumiumtally_tpu.parallel import make_device_mesh

        kwargs["device_mesh"] = make_device_mesh(
            int(ndev) if ndev else None
        )
    cfg = TallyConfig(**kwargs)
    chunk = int(os.environ.get("PUMIUMTALLY_CHUNK_SIZE", "1000000"))
    if engine == "mono":
        return PumiTally(mesh_filename, num_particles, cfg)
    if engine == "streaming":
        return StreamingTally(mesh_filename, num_particles, chunk, cfg)
    if engine == "partitioned":
        return PartitionedPumiTally(mesh_filename, num_particles, cfg)
    if engine == "streaming_partitioned":
        return StreamingPartitionedTally(
            mesh_filename, num_particles, chunk, cfg
        )
    raise ValueError(
        f"PUMIUMTALLY_ENGINE={engine!r}: expected mono, streaming, "
        "partitioned, or streaming_partitioned"
    )
