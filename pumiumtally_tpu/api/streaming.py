"""Streaming front-end: arbitrarily large particle batches in chunks.

The reference sizes its device buffers once at ``num_particles``
(PumiTallyImpl.cpp:36-41) and stages the whole batch per call; BASELINE
config 5 asks for "10M-particle/batch streaming … double-buffered
pipeline". ``StreamingTally`` provides that: the same three-call
protocol, but the batch is processed in fixed-size chunks whose
host→device staging is dispatched ahead of the walk that consumes it —
on an asynchronously-executing backend the transfer of chunk k+1
overlaps the walk of chunk k (the dispatch order IS the double
buffering; no explicit buffer juggling is needed under XLA's async
runtime).

Design points:

- Per-chunk persistent state (positions + element ids) lives on device
  between moves, exactly like the monolithic engine.
- Each chunk accumulates into its OWN flux array; they are summed only
  when the flux is read. A single shared flux would chain every chunk's
  walk through a data dependency and serialize the pipeline.
- The flying-zeroing host side effect (reference PumiTallyImpl.cpp:
  169-172) applies to the whole caller buffer, preserved bit-for-bit
  with the monolithic path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu.api.tally import (
    PumiTally,
    TallyConfig,
    _localize_step,
    _move_step,
    _move_step_continue,
    _perf_counter,
    adopt_located,
    check_finite,
    host_positions,
    host_scalar_field,
    locate_or_committed,
    zero_flying_side_effect,
)
from pumiumtally_tpu.mesh.tetmesh import TetMesh


@dataclass
class FusedStreamStage:
    """One streaming session's share of a fused CHUNK-WISE launch
    (round 20): the host half of a move, chunk-major — produced by
    ``StreamingTally._fused_move_stage`` and consumed by
    ``service/fusion.py``'s per-chunk pack loop. Every list holds one
    entry per chunk, padded to ``chunk_size`` with the solo staging
    rules (positions repeat the last row; pad slots never fly; unit
    weights include pad rows, staged weights pad 0.0), so each packed
    slab segment carries byte-identical rows to the solo chunk
    staging. The scoring operands are the per-chunk device arrays a
    solo streaming move would resolve (``None`` with scoring off)."""

    dests: List[np.ndarray]  # per-chunk [chunk,3] working dtype, host
    origins: Optional[List[np.ndarray]]  # None = continue mode
    fly: List[np.ndarray]  # per-chunk [chunk] int8 host, pads grounded
    w: List[np.ndarray]  # per-chunk [chunk] working dtype, host
    sbin: Optional[List[jnp.ndarray]]  # per-chunk device (scoring only)
    sfac: Optional[List[jnp.ndarray]]  # per-chunk device (scoring only)


class StreamingTally(PumiTally):
    """Three-call tally over batches far larger than one staging buffer.

    Args:
      mesh: TetMesh or mesh file path.
      num_particles: TOTAL batch size (e.g. 10_000_000).
      chunk_size: particles staged/walked per pipeline step.
      config: engine knobs. With ``config.device_mesh`` set, every
        chunk's walk is the replicated-mesh sharded step
        (``parallel.sharded``): the chunk is sharded over the ``dp``
        axis and its flux delta psum'd over ICI — BASELINE configs 3+5
        (multi-chip × 10M-particle streaming) compose.
    """

    def __init__(
        self,
        mesh: Union[TetMesh, str],
        num_particles: int,
        chunk_size: int = 1_000_000,
        config: Optional[TallyConfig] = None,
    ):
        t0 = time.perf_counter()
        mesh = self._init_common(mesh, num_particles, config)
        self.chunk_size = int(min(chunk_size, self.num_particles))
        if self.device_mesh is not None:
            from pumiumtally_tpu.parallel.sharded import axis_name

            axis_name(self.device_mesh)  # fail fast: must be 1-D
            ndev = self.device_mesh.devices.size
            # Chunks shard evenly over the mesh; pad slots never fly.
            self.chunk_size = -(-self.chunk_size // ndev) * ndev
        self.nchunks = -(-self.num_particles // self.chunk_size)
        self._alloc_chunks(mesh)
        self.tally_times.initialization_time += time.perf_counter() - t0

    def _alloc_chunks(self, mesh: TetMesh) -> None:
        """Per-chunk device state (overridden by the partitioned
        composition below)."""
        c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0).astype(self.dtype)
        self._x = [
            jnp.broadcast_to(c0, (self.chunk_size, 3))
            for _ in range(self.nchunks)
        ]
        self._elem = [
            jnp.zeros((self.chunk_size,), jnp.int32)
            for _ in range(self.nchunks)
        ]
        self._flux = [
            jnp.zeros((mesh.nelems,), self.dtype) for _ in range(self.nchunks)
        ]
        # Scoring (round 10): each chunk accumulates into its OWN lane
        # bank, exactly like the per-chunk flux (a shared bank would
        # chain the chunk walks through a data dependency and
        # serialize the pipeline); banks sum on read.
        self._arm_scoring()
        if self._scoring is not None:
            self._score = [
                self._scoring.zero_bank() for _ in range(self.nchunks)
            ]
        jax.block_until_ready(self._x[0])

    # -- chunk staging ----------------------------------------------------
    def _chunk_bounds(self, k: int):
        lo = k * self.chunk_size
        return lo, min(lo + self.chunk_size, self.num_particles)

    def _stage_chunk_positions(
        self, host: np.ndarray, k: int, retain: bool = False,
        what: Optional[str] = None,
    ) -> jnp.ndarray:
        """host is the caller's [3n] buffer (f64); returns [chunk,3] on
        device, padded by repeating the last row (pad slots never fly).

        ``retain=True`` for chunks kept past this call (the origin-echo
        dest cache): in f64 mode the cast is a view of the caller's
        buffer and the CPU backend's jnp.asarray can alias it
        zero-copy, so a retained chunk must own its memory. Chunks
        consumed within the call skip the copy ONLY when the facade
        fences before returning (fenced_timing=True); an unfenced call
        returns with walks still in flight, so a recycled caller
        buffer could otherwise mutate data a queued walk reads."""
        lo, hi = self._chunk_bounds(k)
        a = host[3 * lo : 3 * hi].reshape(hi - lo, 3)
        a = np.asarray(a, dtype=np.dtype(self.dtype))  # host pre-cast
        if (what is not None and self.config.validate_inputs
                and np.dtype(self.dtype) != np.float64):
            # AFTER the working-dtype cast (an f64 value that overflows
            # f32 to inf must be caught too — same rule as the
            # monolithic facade), per chunk so the streaming design's
            # no-full-batch-copies property holds. Skipped in f64 mode:
            # the cast is an identity there and the raw batch was
            # already checked at entry.
            check_finite(a, what, offset=3 * lo)
        if hi - lo < self.chunk_size:
            a = np.concatenate(
                [a, np.repeat(a[-1:], self.chunk_size - (hi - lo), axis=0)]
            )
        elif retain or not self.config.fenced_timing:
            a = self._owned(a)
        return jnp.asarray(a)

    def _prevalidate_narrow(self, dests_h, origins_h, w_h, e_h=None,
                            t_h=None) -> None:
        """Pre-dispatch working-dtype finite check for MoveToNextLocation
        (see the call site): chunk-at-a-time casts, discarded after the
        check, so a non-finite value anywhere in the batch raises before
        ANY chunk dispatches — error messages name the argument
        (``energy``/``time`` included, round 10). No-op in f64 mode
        (cast is identity; the raw batch was checked at entry) or with
        validation off."""
        if (not self.config.validate_inputs
                or np.dtype(self.dtype) == np.float64):
            return
        dt = np.dtype(self.dtype)
        for k in range(self.nchunks):
            lo, hi = self._chunk_bounds(k)
            check_finite(np.asarray(dests_h[3 * lo : 3 * hi], dtype=dt),
                         "destinations", offset=3 * lo)
            if origins_h is not None:
                check_finite(np.asarray(origins_h[3 * lo : 3 * hi], dtype=dt),
                             "origins", offset=3 * lo)
            if w_h is not None:
                check_finite(np.asarray(w_h[lo:hi], dtype=dt),
                             "weights", offset=lo)
            if e_h is not None:
                check_finite(np.asarray(e_h[lo:hi], dtype=dt),
                             "energy", offset=lo)
            if t_h is not None:
                check_finite(np.asarray(t_h[lo:hi], dtype=dt),
                             "time", offset=lo)

    def _stage_chunk_vec(self, host, k: int, dtype, fill,
                         what: Optional[str] = None) -> jnp.ndarray:
        lo, hi = self._chunk_bounds(k)
        # copy=True: jnp.asarray may alias a same-dtype numpy buffer
        # zero-copy on the CPU backend, and the flying buffer is zeroed
        # in place after staging (see tally.zero_flying_side_effect).
        a = np.array(host[lo:hi], dtype=dtype, copy=True)
        if (what is not None and self.config.validate_inputs
                and np.dtype(dtype) != np.float64):
            check_finite(a, what, offset=lo)  # see _stage_chunk_positions
        if hi - lo < self.chunk_size:
            a = np.concatenate(
                [a, np.full(self.chunk_size - (hi - lo), fill, dtype=dtype)]
            )
        return jnp.asarray(a)

    # -- the three-call protocol -----------------------------------------
    def CopyInitialPosition(self, init_particle_positions, size: Optional[int] = None):
        self._check_poisoned()
        t0 = time.perf_counter()
        self._stats_roll_batch()  # each sourcing opens a new batch
        self._resilience_roll_batch()  # autosave/drain at batch close
        self._roll_lost()  # fold the closed batch's leakage
        self._last_dests_host = None  # localization rewrites the state
        self._last_dests_dev = None
        self._echo_misses = 0  # new batch: re-arm the echo detector
        host = host_positions(init_particle_positions, size, self.num_particles)
        if self.config.validate_inputs:
            check_finite(host, "positions")
        # Dispatch every chunk first (staging of chunk k+1 overlaps the
        # walk of chunk k); evaluate the convergence flags only after.
        dones = []
        for k in range(self.nchunks):
            dest = self._stage_chunk_positions(host, k, what="positions")
            dones.append(self._chunk_localize(k, dest))
        self._after_chunk_dispatch()
        if self.config.check_found_all and not all(
            bool(jnp.all(d)) for d in dones
        ):
            print("ERROR: Not all particles are found. May need more loops in search")
        self.is_initialized = True
        if self.config.fenced_timing:
            jax.block_until_ready(self._x)
        self.tally_times.initialization_time += time.perf_counter() - t0

    def MoveToNextLocation(
        self, particle_origin, particle_destinations, flying=None, weights=None,
        size: Optional[int] = None, energy=None, time=None,
    ):
        # Poisoned check FIRST (same order as the base facade): a
        # corrupt engine must refuse whatever else is wrong.
        self._check_poisoned()
        if not self.is_initialized:
            raise RuntimeError(
                "CopyInitialPosition must be called before MoveToNextLocation"
            )
        t0 = _perf_counter()
        n = self.num_particles
        # Scoring-attribute validation BEFORE any staging: shape/
        # combination errors name the argument (round 10).
        self._score_args_check(energy, time)
        e_h = (
            None if energy is None
            else host_scalar_field(energy, n, "energy")
        )
        t_h = (
            None if time is None
            else host_scalar_field(time, n, "time")
        )
        dests_h = host_positions(particle_destinations, size, n)
        origins_h = (
            None
            if particle_origin is None
            else host_positions(particle_origin, size, n)
        )
        if self.config.validate_inputs:
            check_finite(dests_h, "destinations")
            if origins_h is not None:
                check_finite(origins_h, "origins")
            if e_h is not None:
                check_finite(e_h, "energy")
            if t_h is not None:
                check_finite(t_h, "time")
        # Origin-echo dedup (TallyConfig.auto_continue), chunk-wise: when
        # the caller's origins equal the previous move's destinations
        # bit-for-bit in the working dtype (same rule as the monolithic
        # facade — _origins_echo_raw), reuse the device chunks that
        # staged them instead of re-uploading the whole batch (here
        # _last_dests_dev is the LIST of per-chunk device arrays). The
        # raw-buffer probe compares a strided sample before any
        # full-batch cast, so never-echoing drivers pay ~nothing here.
        # Pass the already-converted flat buffer, not the raw one — a
        # list/non-f64 input would otherwise convert twice per move.
        echo = self._origins_echo_raw(origins_h, size)
        fly_h = None if flying is None else np.asarray(flying).reshape(-1)
        w_h = (
            None
            if weights is None
            else np.asarray(weights, np.float64).reshape(-1)
        )
        if self.config.validate_inputs and w_h is not None:
            check_finite(w_h[: self.num_particles], "weights")

        # Sentinel stash: the per-chunk staged views the post-move
        # audit/ladder needs (phase-B start, dest, fly, w + the ray
        # coordinates _chunk_move records into _move_s), retained only
        # while a sentinel is armed — the sentinel-off path keeps its
        # no-extra-references contract.
        stash = [] if self._sentinel is not None else None
        self._move_s = {}
        # Pre-dispatch finite check in the working dtype (ADVICE r4):
        # the narrow-dtype overflow corner (f64 input finite, f32 cast
        # inf) used to raise from a mid-loop chunk stage AFTER earlier
        # chunks had dispatched and tallied — a refused move left flux
        # partially committed. Cast+check every chunk (discarding the
        # cast) BEFORE any dispatch, so refusal is atomic like the
        # monolithic facade's; the staging loop below then skips its
        # per-chunk re-check (what=None). Costs one extra cast pass,
        # only in validate+narrow mode, still chunk-at-a-time (the
        # no-full-batch-copies property holds).
        self._prevalidate_narrow(dests_h, None if echo else origins_h, w_h,
                                 e_h, t_h)
        retain = origins_h is not None and self._retain_echo_snapshots()
        oks = []
        dest_chunks = []
        for k in range(self.nchunks):
            # Stage chunk k, dispatch its walk, move on: dispatches are
            # async, so chunk k+1's staging overlaps chunk k's walk.
            dest = self._stage_chunk_positions(dests_h, k, retain=retain)
            dest_chunks.append(dest)
            fly = (
                jnp.ones((self.chunk_size,), jnp.int8)
                if fly_h is None
                else self._stage_chunk_vec(fly_h, k, np.int8, 0)
            )
            w = (
                jnp.ones((self.chunk_size,), self.dtype)
                if w_h is None
                else self._stage_chunk_vec(w_h, k, np.dtype(self.dtype), 0.0)
            )
            lo, hi = self._chunk_bounds(k)
            if hi - lo < self.chunk_size:  # pad slots never fly
                mask = np.zeros(self.chunk_size, np.int8)
                mask[: hi - lo] = 1
                fly = fly * jnp.asarray(mask)
            if origins_h is None:
                orig = None
            elif echo:
                orig = self._last_dests_dev[k]
            else:
                orig = self._stage_chunk_positions(origins_h, k)
            sbin = sfac = None
            if self._scoring is not None:
                # Chunk-local bin/factor resolution (pad slots never
                # fly, so their fill value never scores); what=None —
                # the batch was validated at entry and per chunk by
                # _prevalidate_narrow.
                e_c = (
                    None if e_h is None else self._stage_chunk_vec(
                        e_h, k, np.dtype(self.dtype), 0.0
                    )
                )
                t_c = (
                    None if t_h is None else self._stage_chunk_vec(
                        t_h, k, np.dtype(self.dtype), 0.0
                    )
                )
                sbin, sfac = self._scoring.resolve(
                    e_c, t_c, self.chunk_size
                )
            if stash is not None:
                stash.append(
                    (k, self._chunk_phase_b_start(k, orig), dest, fly, w,
                     sbin, sfac)
                )
            oks.append(self._chunk_move(k, orig, dest, fly, w, sbin, sfac))
        zero_flying_side_effect(flying, n)
        if retain:
            # Snapshot in the working dtype (the compare representation
            # _origins_echo_raw uses), owned so a recycled caller buffer
            # cannot fool the next compare. Reuse the already-converted
            # flat buffer — a list/non-f64 input must not convert twice.
            # Only retained for origin-passing drivers (see tally.py).
            # what=None: dests_h was validated at entry (and per
            # chunk for the narrow-dtype corner) — skip a third
            # full-batch pass.
            self._last_dests_host = self._as_positions_host(
                dests_h, size, what=None)
            self._last_dests_dev = dest_chunks
        self.iter_count += 1
        self._stats_note_move()
        self._after_chunk_dispatch()
        oks = self._correct_verdicts(oks)
        if stash is not None:
            oks = self._sentinel_chunks_post_move(stash, oks)
        # Per-chunk verdicts may be masks (round 9) or engine booleans.
        if self.config.check_found_all and not all(
            bool(jnp.all(o)) for o in oks
        ):
            print("ERROR: Not all particles are found. May need more loops in search")
        if self.config.fenced_timing:
            jax.block_until_ready(self._flux)
        self.tally_times.total_time_to_tally += _perf_counter() - t0
        self._resilience_note_move()  # drain/timer-cadence safe point

    def _after_chunk_dispatch(self) -> None:
        """Hook: deferred per-chunk error checks (partitioned mode)."""

    def _correct_verdicts(self, oks):
        """Hook: re-derive per-chunk found-all verdicts after a
        deferred overflow recovery invalidated the lazily collected
        ones (partitioned mode overrides)."""
        return oks

    # -- runtime sentinels (chunked arms) --------------------------------
    def _chunk_phase_b_start(self, k: int, orig):
        """Chunk k's phase-B start positions for the sentinel audit:
        the staged origins, or the committed pre-move chunk state."""
        return self._x[k] if orig is None else orig

    def _sentinel_chunks_post_move(self, stash, oks):
        """Streaming arm of the sentinel protocol, at the batch sync
        point (per-chunk syncs would serialize the pipeline): ONE
        concatenated audit over every chunk's caller-order view, then
        the straggler ladder chunk-by-chunk over whatever residue the
        done masks show."""
        from pumiumtally_tpu.sentinel.straggler import run_ladder

        pol = self.config.sentinel
        x0 = jnp.concatenate([s[1] for s in stash], axis=0)
        x1 = jnp.concatenate(self._x, axis=0)
        fly = jnp.concatenate([s[3] for s in stash])
        w = jnp.concatenate([s[4] for s in stash])
        done = jnp.concatenate(oks)
        n_unf, mask = self._sentinel.audit(
            x0, x1, fly, w, done, self.flux
        )
        recovered = lost = 0
        if n_unf and pol.straggler_retry:
            new_oks = []
            for (k, _x0k, dest, fly_k, w_k, sbin_k, sfac_k), done_k in zip(
                stash, oks
            ):
                unfinished = np.asarray(~done_k & (fly_k == 1))
                if not unfinished.any():
                    new_oks.append(done_k)
                    continue
                sc = None
                if self._scoring is not None:
                    sc = (self._scoring.spec.kinds, self._score[k],
                          sbin_k, sfac_k)
                x2, e2, flux2, rec_idx, lost_idx, bank2 = run_ladder(
                    self.mesh, self._x[k], self._elem[k], dest, fly_k,
                    w_k, self._flux[k], unfinished,
                    tol=self._tol, base_iters=self._max_iters,
                    retry_factor=pol.retry_iters_factor,
                    walk_kw=self._walk_kw,
                    two_tier=(self._table_dtype == "bfloat16"),
                    x_start=_x0k, s_init=self._move_s.get(k),
                    scoring=sc,
                )
                self._x[k], self._elem[k], self._flux[k] = x2, e2, flux2
                if sc is not None:
                    self._score[k] = bank2
                recovered += int(rec_idx.size)
                lost += int(lost_idx.size)
                if lost_idx.size:
                    self._lost_total += int(lost_idx.size)
                    self._quarantine_streaming(
                        k, lost_idx, _x0k, dest, w_k
                    )
                new_oks.append(lost_idx.size == 0)
            oks = new_oks
            self._sentinel.resync(self.flux)
        self._sentinel.note_outcome(
            mask, n_unf, recovered, lost, self.iter_count - 1
        )
        return oks

    def _quarantine_streaming(self, k: int, idx, x0, dest, w) -> None:
        """Quarantine records for chunk k's unrecoverable residue —
        pids in GLOBAL (caller) numbering via the chunk offset."""
        from pumiumtally_tpu.sentinel.quarantine import (
            append_quarantine,
            build_records,
        )

        lo, _hi = self._chunk_bounds(k)
        sel = jnp.asarray(idx)
        append_quarantine(
            self.config.sentinel.quarantine_dir,
            build_records(
                idx, np.asarray(x0[sel]), np.asarray(dest[sel]),
                np.asarray(self._elem[k][sel]), np.asarray(w[sel]),
                self.iter_count - 1, pid_offset=lo,
            ),
        )

    # -- per-chunk dispatch (overridden by StreamingPartitionedTally) ----
    def _chunk_localize(self, k: int, dest: jnp.ndarray):
        """Localize chunk k to staged [chunk,3] destinations; returns
        the chunk's done flags (lazy)."""
        x, elem = self._x[k], self._elem[k]
        if self.device_mesh is not None:
            from pumiumtally_tpu.parallel.sharded import (
                sharded_locate,
                sharded_localize_step,
            )

            if self.config.localization == "locate":
                x, elem = adopt_located(
                    x, elem, dest,
                    sharded_locate(
                        self.device_mesh, self.mesh, dest, tol=self._tol
                    ),
                )
            self._x[k], self._elem[k], done, _ = sharded_localize_step(
                self.device_mesh, self.mesh, x, elem,
                dest, tol=self._tol, max_iters=self._max_iters,
                walk_kw=self._walk_kw,
            )
            return self._sentinel_chunk_post_localize(k, dest, done)
        if self.config.localization == "locate":
            # MXU point location per chunk; unlocated points keep
            # walking from the committed state (shared pre-pass with
            # PumiTally._localize_by_planes).
            x, elem = locate_or_committed(
                self.mesh, x, elem, dest, tol=self._tol
            )
        self._x[k], self._elem[k], done, _ = _localize_step(
            self.mesh, x, elem, dest,
            tol=self._tol, max_iters=self._max_iters,
            walk_kw=self._walk_kw,
        )
        return self._sentinel_chunk_post_localize(k, dest, done)

    def _sentinel_chunk_post_localize(self, k: int, dest, done):
        """Chunk arm of the non-tallying localization ladder (see
        PumiTally._sentinel_post_localize)."""
        if self._sentinel is None or not (
            self.config.sentinel.straggler_retry
        ):
            return done
        unfinished = np.asarray(~done)
        if not unfinished.any():
            return done
        from pumiumtally_tpu.sentinel.straggler import run_ladder

        pol = self.config.sentinel
        fly = jnp.ones((self.chunk_size,), jnp.int8)
        w0 = jnp.zeros((self.chunk_size,), self.dtype)
        x2, e2, _flux, rec_idx, lost_idx, _bank = run_ladder(
            self.mesh, self._x[k], self._elem[k], dest, fly, w0,
            self._flux[k], unfinished,
            tol=self._tol, base_iters=self._max_iters,
            retry_factor=pol.retry_iters_factor, walk_kw=self._walk_kw,
            two_tier=(self._table_dtype == "bfloat16"),
        )
        self._x[k], self._elem[k] = x2, e2
        self._sentinel.note_localization(rec_idx.size, lost_idx.size)
        dn = np.asarray(done).copy()
        dn[rec_idx] = True
        return jnp.asarray(dn)

    def _chunk_move(self, k: int, orig, dest, fly, w, sbin=None,
                    sfac=None):
        """One tallied move of chunk k (orig None = continue mode);
        returns the chunk's done mask (lazy). The phase-B ray
        coordinates are stashed for the sentinel ladder when one is
        armed (``_move_s``). ``sbin``/``sfac`` (scoring armed) are the
        chunk's resolved bin offsets / factor rows; the chunk's OWN
        lane bank accumulates like its flux."""
        score_kw = {}
        if self._scoring is not None:
            score_kw = {
                "score_kinds": self._scoring.spec.kinds,
                "score_ops": (self._score[k], sbin, sfac),
            }
        if self.device_mesh is not None:
            from pumiumtally_tpu.parallel.sharded import (
                sharded_move_step,
                sharded_move_step_continue,
            )

            if orig is None:
                res = sharded_move_step_continue(
                    self.device_mesh, self.mesh, self._x[k],
                    self._elem[k], dest, fly, w, self._flux[k],
                    tol=self._tol, max_iters=self._max_iters,
                    walk_kw=self._walk_kw, **score_kw,
                )
            else:
                res = sharded_move_step(
                    self.device_mesh, self.mesh, self._x[k],
                    self._elem[k], orig, dest, fly, w, self._flux[k],
                    tol=self._tol, max_iters=self._max_iters,
                    walk_kw=self._walk_kw, **score_kw,
                )
        elif orig is None:
            res = _move_step_continue(
                self.mesh, self._x[k], self._elem[k], dest, fly, w,
                self._flux[k], tol=self._tol, max_iters=self._max_iters,
                walk_kw=self._walk_kw, **score_kw,
            )
        else:
            res = _move_step(
                self.mesh, self._x[k], self._elem[k], orig, dest, fly, w,
                self._flux[k], tol=self._tol, max_iters=self._max_iters,
                walk_kw=self._walk_kw, **score_kw,
            )
        self._x[k], self._elem[k], self._flux[k], ok, s_b = res[:5]
        if self._scoring is not None:
            self._score[k] = res[5]
        if self._sentinel is not None:
            self._move_s[k] = s_b
        return ok

    # -- cross-session chunk-wise fusion (round 20, service/fusion.py) ---
    def _fusion_key(self):
        """Streaming arm of the co-fusability identity (see
        ``PumiTally._fusion_key``): compatible streaming sessions fuse
        CHUNK-WISE — chunk j of every session packs one slab, one
        shared launch per chunk index. The key leads with the facade
        kind, so a group can never mix monolithic and streaming heads
        (their launch geometry differs — the scheduler's ``group_key``
        comparison refuses the mix by construction), and pins
        ``num_particles`` + ``chunk_size``: an equal chunk grid makes
        every fused launch one static (spans, pad) composition, one
        trace key per group size like the monolithic path. Subclasses
        (partitioned streaming — engine-owned state), sharded facades,
        and xpoint recorders never fuse."""
        if type(self) is not StreamingTally:
            return None
        if self.device_mesh is not None or self.config.record_xpoints:
            return None
        spec = self.config.scoring
        return (
            "stream",
            id(self.mesh),
            str(np.dtype(self.dtype)),
            self._tol,
            self._max_iters,
            self._walk_kw,
            self._table_dtype,
            None if spec is None else spec.static_key(),
            self.num_particles,
            self.chunk_size,
        )

    def _fused_chunk_positions(self, host: np.ndarray,
                               k: int) -> np.ndarray:
        """Host-side twin of ``_stage_chunk_positions`` for the fused
        pack: byte-identical values (working-dtype cast, last-row
        repeat padding), left on the HOST so the pack step pays one
        upload per operand per chunk however many sessions share it.
        No re-validation — the op prevalidated at submit, like the
        monolithic stage."""
        lo, hi = self._chunk_bounds(k)
        a = np.asarray(
            host[3 * lo : 3 * hi].reshape(hi - lo, 3),
            dtype=np.dtype(self.dtype),
        )
        if hi - lo < self.chunk_size:
            a = np.concatenate(
                [a, np.repeat(a[-1:], self.chunk_size - (hi - lo), axis=0)]
            )
        return a

    def _fused_chunk_vec(self, host, k: int, dtype, fill) -> np.ndarray:
        """Host-side twin of ``_stage_chunk_vec`` (same values, no
        upload — the pack's slab concatenation owns the bytes)."""
        lo, hi = self._chunk_bounds(k)
        a = np.asarray(host[lo:hi], dtype=dtype)
        if hi - lo < self.chunk_size:
            a = np.concatenate(
                [a, np.full(self.chunk_size - (hi - lo), fill, dtype=dtype)]
            )
        return a

    def _fused_move_stage(self, op) -> FusedStreamStage:
        """The host half of one streaming move for a fused group (same
        contract as ``PumiTally._fused_move_stage``: the protocol-order
        checks re-run with the same errors, NO facade state mutates —
        a later pack/launch failure falls back to the solo path with
        the campaign untouched). Chunk-major: every operand stages per
        chunk under the solo path's padding rules, and the scoring
        operands resolve per chunk exactly as a solo streaming move
        would."""
        self._check_poisoned()
        if not self.is_initialized:
            raise RuntimeError(
                "CopyInitialPosition must be called before "
                "MoveToNextLocation (reference invariant, "
                "PumiTallyImpl.cpp:437-438)"
            )
        self._score_args_check(op.energy, op.time)
        wd = np.dtype(self.dtype)
        dests: List[np.ndarray] = []
        origins = None if op.origins is None else []
        fly: List[np.ndarray] = []
        w: List[np.ndarray] = []
        scoring = self._scoring is not None
        sbin = [] if scoring else None
        sfac = [] if scoring else None
        for k in range(self.nchunks):
            lo, hi = self._chunk_bounds(k)
            dests.append(self._fused_chunk_positions(op.dests, k))
            if origins is not None:
                origins.append(self._fused_chunk_positions(op.origins, k))
            if op.flying is None:
                f = np.ones(self.chunk_size, np.int8)
                f[hi - lo :] = 0  # pad slots never fly
            else:
                # Staged fill is already 0, matching the solo path's
                # pad mask.
                f = self._fused_chunk_vec(op.flying, k, np.int8, 0)
            fly.append(f)
            w.append(
                np.ones(self.chunk_size, wd) if op.weights is None
                else self._fused_chunk_vec(op.weights, k, wd, 0.0)
            )
            if scoring:
                e_c = (
                    None if op.energy is None
                    else self._stage_chunk_vec(op.energy, k, wd, 0.0)
                )
                t_c = (
                    None if op.time is None
                    else self._stage_chunk_vec(op.time, k, wd, 0.0)
                )
                sb, sf = self._scoring.resolve(e_c, t_c, self.chunk_size)
                sbin.append(sb)
                sfac.append(sf)
        return FusedStreamStage(dests=dests, origins=origins, fly=fly,
                                w=w, sbin=sbin, sfac=sfac)

    def _fused_move_commit(self, res, stage: FusedStreamStage, t0: float,
                           sentinel_ops=None) -> None:
        """The state half of one fused streaming move: adopt every
        chunk's slice of the shared per-chunk launches, then run the
        solo streaming move's post-dispatch sequence in the solo order
        (per-chunk adopt + ray stash, counters, deferred-check hook,
        verdict correction, the sentinel audit/ladder at the batch
        sync point, found-all check, fence, timing, resilience move
        hook). ``res`` is a list over chunks of this session's
        ``(x, elem, flux, done, s, bank-or-None)`` slices;
        ``sentinel_ops`` — one ``(origins, dests, fly, w)`` device
        slice tuple per chunk (``origins`` None in continue mode) — is
        required iff a sentinel is armed. The auto-continue echo
        snapshots are left as they were, exactly like the monolithic
        commit (a stale snapshot is value-correct by construction)."""
        stash = [] if self._sentinel is not None else None
        self._move_s = {}
        oks = []
        for k, (x2, elem2, flux2, done, s_b, bank2) in enumerate(res):
            if stash is not None:
                org, dest, fly_k, w_k = sentinel_ops[k]
                # Phase-B start BEFORE the adopt below — the committed
                # pre-move chunk state, as _chunk_phase_b_start reads.
                x0 = self._x[k] if org is None else org
                stash.append((
                    k, x0, dest, fly_k, w_k,
                    None if stage.sbin is None else stage.sbin[k],
                    None if stage.sfac is None else stage.sfac[k],
                ))
            self._x[k], self._elem[k], self._flux[k] = x2, elem2, flux2
            if self._scoring is not None:
                self._score[k] = bank2
            if self._sentinel is not None:
                self._move_s[k] = s_b
            oks.append(done)
        self.iter_count += 1
        self._stats_note_move()
        self._after_chunk_dispatch()
        oks = self._correct_verdicts(oks)
        if stash is not None:
            oks = self._sentinel_chunks_post_move(stash, oks)
        if self.config.check_found_all and not all(
            bool(jnp.all(o)) for o in oks
        ):
            print("ERROR: Not all particles are found. May need more loops in search")
        if self.config.fenced_timing:
            jax.block_until_ready(self._flux)
        self.tally_times.total_time_to_tally += _perf_counter() - t0
        self._resilience_note_move()  # drain/timer-cadence safe point

    # -- state views ------------------------------------------------------
    @property
    def x(self):
        return jnp.concatenate(self._x, axis=0)[: self.num_particles]

    @property
    def elem(self):
        return jnp.concatenate(self._elem, axis=0)[: self.num_particles]

    @property
    def flux(self) -> jnp.ndarray:
        total = self._flux[0]
        for f in self._flux[1:]:
            total = total + f
        return total

    @property
    def score_bank(self) -> jnp.ndarray:
        """Scoring lanes summed over the per-chunk banks (same
        read-path assembly as ``flux``)."""
        self._require_scoring()
        total = self._score[0]
        for b in self._score[1:]:
            total = total + b
        return total

    @property
    def positions(self) -> np.ndarray:
        return np.asarray(self.x)

    @property
    def elem_ids(self) -> np.ndarray:
        return np.asarray(self.elem)


class StreamingPartitionedTally(StreamingTally):
    """Streaming chunks through the PARTITIONED engine: the mesh too
    large to replicate per chip AND the batch too large for one slot
    array (BASELINE configs 2 + 5 composed). Each chunk owns a
    ``PartitionedEngine`` slot state; all chunks share one mesh
    partition and one set of compiled locate/phase programs, and owned
    flux accumulates across chunks.
    """

    # Per-chip tiered tables come from build_partition, not the
    # replicated mesh — see PumiTally._replicated_mesh_walk.
    _replicated_mesh_walk = False

    def __init__(
        self,
        mesh: Union[TetMesh, str],
        num_particles: int,
        chunk_size: int = 1_000_000,
        config: Optional[TallyConfig] = None,
    ):
        if config is None or config.device_mesh is None:
            raise ValueError(
                "StreamingPartitionedTally requires TallyConfig.device_mesh"
            )
        if config.sentinel is not None and int(config.device_groups) > 1:
            # The audit concatenates caller-order device views across
            # chunk engines; with disjoint device groups those live on
            # different device sets (the same reason the flux property
            # assembles on the host there).
            raise ValueError(
                "TallyConfig.sentinel with device_groups > 1 is not "
                "supported: the audit needs one device set across "
                "chunk engines"
            )
        super().__init__(mesh, num_particles, chunk_size, config)

    def _alloc_chunks(self, mesh: TetMesh) -> None:
        from jax.sharding import Mesh

        from pumiumtally_tpu.parallel.partition import (
            PartitionedEngine,
            build_partition,
            derive_blocks_per_chip,
        )

        # Device groups: dp × part hybrid. The flat device list splits
        # into G disjoint sub-meshes; chunks round-robin across them, so
        # G chunks walk CONCURRENTLY (different devices) while each
        # group still shards the mesh over its own chips — particle
        # data parallelism across groups × mesh partitioning within a
        # group. G=1 (default) is the original single-group pipeline.
        ngroups = int(self.config.device_groups)  # >=1, validated by config
        devs = np.asarray(self.device_mesh.devices).reshape(-1)
        if len(devs) % ngroups:
            raise ValueError(
                f"device_groups={ngroups} does not divide the "
                f"{len(devs)}-device mesh"
            )
        if ngroups > self.nchunks:
            # Round-robin can only reach nchunks groups — trailing
            # groups (and their chips) would silently idle.
            raise ValueError(
                f"device_groups={ngroups} exceeds the {self.nchunks} "
                "chunk(s) of this batch; lower it or shrink chunk_size"
            )
        per = len(devs) // ngroups
        ax = self.device_mesh.axis_names[0]
        group_meshes = [
            Mesh(devs[g * per : (g + 1) * per], (ax,))
            for g in range(ngroups)
        ]
        # The partition depends only on (mesh, parts-per-group): build
        # it once; every group shares the tables. Compiled programs bake
        # the device mesh, so each group keeps its own jit cache. The
        # VMEM sub-split (walk_vmem_max_elems) multiplies the part
        # count so each BLOCK fits the bound; the engines derive their
        # blocks_per_chip back from the part's shape. Clamp the bound
        # through the same helper the engines use, or a prebuilt part
        # could carry blocks the kernel cannot compile on hardware.
        from pumiumtally_tpu.ops.vmem_walk import effective_vmem_bound
        from pumiumtally_tpu.parallel.partition import (
            block_elems_bound,
            resolve_block_kernel,
        )

        # The Mosaic scoped-VMEM clamp applies to the vmem block kernel
        # and to the pallas streaming kernel (whose resident per-block
        # operands obey the same scoped-stack law at the bf16 2x
        # ceiling); the gather block kernel has no such ceiling. A bf16
        # two-tier config with walk_kernel='vmem' routes blocked walks
        # through the gather kernel (same resolution the engines
        # apply), with the block element bound at 2x — the half-width
        # select tier keeps resident bytes constant.
        block_kernel = resolve_block_kernel(
            self.config.resolved_walk_kernel(), self._table_dtype
        )
        if block_kernel == "vmem":
            vmem_bound = effective_vmem_bound(self.config.walk_vmem_max_elems)
        elif block_kernel == "pallas":
            vmem_bound = effective_vmem_bound(
                self.config.walk_vmem_max_elems, "bfloat16"
            )
        else:
            vmem_bound = self.config.walk_vmem_max_elems
        bpc = derive_blocks_per_chip(
            mesh.nelems, per,
            block_elems_bound(vmem_bound, self._table_dtype),
        )
        # The chunk engines share ONE prebuilt partition, so the
        # placement knob shapes it HERE (engines refuse to re-derive a
        # placement for a part= they did not build). Host chip counts
        # apply per GROUP mesh — every group has ``per`` devices.
        if self.config.placement == "pod_rcb":
            if self.config.placement_hosts is not None:
                host_chips = tuple(
                    int(h) for h in self.config.placement_hosts
                )
            else:
                from pumiumtally_tpu.parallel.distributed import (
                    derive_host_counts,
                )

                host_chips = derive_host_counts(group_meshes[0])
            hosts = [h * bpc for h in host_chips]
        else:
            hosts = None
        part = build_partition(
            mesh,
            per * bpc,
            table_dtype=self._table_dtype,
            placement=self.config.placement,
            hosts=hosts,
        )
        caches = [dict() for _ in range(ngroups)]
        # Each engine is sized to its chunk's REAL particle count (a
        # padded slot would otherwise be a live particle piling onto
        # whatever chip owns the repeated pad point).
        self.engines = []
        for k in range(self.nchunks):
            lo, hi = self._chunk_bounds(k)
            g = k % ngroups
            self.engines.append(PartitionedEngine(
                mesh, group_meshes[g], hi - lo,
                capacity_factor=self.config.capacity_factor,
                tol=self._tol, max_iters=self._max_iters,
                max_rounds=self.config.max_migration_rounds,
                check_found_all=self.config.check_found_all,
                part=part, shared_jit_cache=caches[g],
                cond_every=self.config.resolved_cond_every(),
                min_window=self.config.resolved_min_window(),
                vmem_walk_max_elems=vmem_bound,
                block_kernel=self.config.resolved_walk_kernel(),
                partition_method=self.config.resolved_partition_method(),
                cap_frontier=self.config.cap_frontier,
                scoring=self.config.scoring,
                migrate_collective=self.config.migrate_collective,
                placement=self.config.placement,
                placement_hosts=self.config.placement_hosts,
            ))
        # Scoring runtime AFTER the engines: the DROP sentinel needs
        # the shared partition's PADDED lane-bank size (every chunk
        # engine shares one partition, hence one bank geometry).
        self._arm_scoring(
            bank_size=None if self.config.scoring is None else (
                self.engines[0].nparts * self.engines[0].part.L
                * self.engines[0].score_stride
            )
        )
        for eng in self.engines:
            # Recovery-ladder wiring (round 9): recoveries report into
            # the sentinel record; a ladder exhaustion safety-saves
            # before the poisoned raise.
            eng.on_overflow_recovered = self._note_overflow_recovered
            eng.on_poisoned = self._overflow_safety_save
        # Base-class sync/view lists are unused in this mode.
        self._x = []
        self._elem = []
        self._flux = []
        self._pending_overflows = []
        self._dispatched_localize = False
        self._recovered_this_call = False
        jax.block_until_ready(part.table)

    # -- per-chunk dispatch via the partitioned engines ------------------
    # defer_sync everywhere: a per-chunk host sync would serialize the
    # chunk pipeline; overflow flags are collected and checked once per
    # protocol call in _after_chunk_dispatch.
    def _chunk_localize(self, k: int, dest: jnp.ndarray):
        self._dispatched_localize = True
        n = self.engines[k].n  # strip staging pads: engines hold only
        found_all, ovf = self.engines[k].localize(  # real slots
            dest[:n], defer_sync=True
        )
        self._pending_overflows.append((self.engines[k], "localize", ovf))
        return found_all

    def _chunk_move(self, k: int, orig, dest, fly, w, sbin=None,
                    sfac=None):
        n = self.engines[k].n
        skw = {}
        if self._scoring is not None:
            skw = {"sbin_n": sbin[:n], "sfac_n": sfac[:n]}
        ok, ovf = self.engines[k].move(
            None if orig is None else orig[:n], dest[:n], fly[:n], w[:n],
            defer_sync=True, **skw,
        )
        self._pending_overflows.append((self.engines[k], "move", ovf))
        return ok

    def _engine_poisoned(self) -> bool:
        return self._poisoned or any(e.poisoned for e in self.engines)

    def _note_overflow_recovered(self, escalated: bool) -> None:
        if self._sentinel is not None:
            self._sentinel.note_overflow_recovery(escalated)

    def _overflow_safety_save(self) -> None:
        if self._resilience is not None:
            self._resilience.save(self, reason="overflow_safety")

    def _recover_deferred_overflow(self, eng, kind: str) -> None:
        """One engine's deferred overflow, at the batch sync point.
        The overflow-safe migrate kept its pre-migrate snapshot, so
        localization and single-phase (continue-mode) moves resume
        through the engine ladder. A two-phase move whose PHASE A
        overflowed is the unrecoverable corner: phase B already walked
        (and tallied) from the incomplete relocation before the
        deferred flag was read — poison rather than compute on."""
        self._recovered_this_call = True
        if kind == "localize":
            eng._recover_localize_overflow()
            return
        ovf_a, _ovf_b = eng._last_defer_flags or (None, None)
        if ovf_a is not None and bool(ovf_a):
            eng.poisoned = True
            self._overflow_safety_save()
            from pumiumtally_tpu.sentinel.policy import POISONED_MESSAGE

            raise RuntimeError(
                "partitioned-mode capacity overflow in a deferred "
                "two-phase relocation: the transport phase already ran "
                "over the incomplete placement — " + POISONED_MESSAGE
            )
        eng._recover_overflow(eng._last_phase_tally)

    def _after_chunk_dispatch(self) -> None:
        pending, self._pending_overflows = self._pending_overflows, []
        # Per-flag host reads: this IS the batch sync point, and with
        # device_groups > 1 the flags live on disjoint device sets (a
        # device-side stack across groups is invalid).
        for eng, kind, ovf in pending:
            if bool(ovf):
                self._recover_deferred_overflow(eng, kind)
        # Resolve every engine's lost count at this batch sync point:
        # the two-phase revival check in move() then reads a cached int
        # instead of forcing a mid-pipeline device fetch.
        n_lost = sum(e._n_lost for e in self.engines)
        was_localize, self._dispatched_localize = (
            self._dispatched_localize, False
        )
        if n_lost and was_localize and self.config.check_found_all:
            # Surface the specific diagnostic the per-chunk deferred
            # localize skipped (on EVERY re-sourcing, like the
            # non-streaming partitioned engine).
            print(
                f"[WARNING] {n_lost} source points lie in no mesh "
                "element; their particles are excluded from transport"
            )

    # -- state views (numpy-side: engine accessors already fetched) ------
    @property
    def x(self):
        return np.concatenate(
            [e.positions() for e in self.engines], axis=0
        )[: self.num_particles]

    @property
    def elem(self):
        return np.concatenate(
            [e.elem_ids() for e in self.engines]
        )[: self.num_particles]

    @property
    def positions(self) -> np.ndarray:
        return self.x

    @property
    def elem_ids(self) -> np.ndarray:
        return self.elem

    def _current_lost(self) -> int:
        """Still-lost particles across the chunk engines (each count is
        an int cached at the batch sync point, _after_chunk_dispatch —
        no extra device fetch here)."""
        return sum(e._n_lost for e in self.engines)

    def _correct_verdicts(self, oks):
        """A deferred overflow recovery re-ran part of a phase AFTER
        the lazy verdicts were collected — re-derive found-all from
        the engines' committed done flags (we are past the batch sync
        point, so these fetches add no new pipeline stall)."""
        if not self._recovered_this_call:
            return oks
        self._recovered_this_call = False
        return [jnp.all(e.state["done"]) for e in self.engines]

    # -- runtime sentinels (partitioned-chunk arm) ------------------------
    def _chunk_phase_b_start(self, k: int, orig):
        n = self.engines[k].n
        if orig is not None:
            return orig[:n]
        return self.engines[k].caller_order_view(("x",))["x"]

    def _sentinel_chunks_post_move(self, stash, oks):
        """Partitioned-chunk arm: one concatenated audit over the
        engines' caller-order views (single device group — enforced at
        construction), then the ENGINE-level straggler ladder per
        chunk (resume-phase retry → declare lost + quarantine; lost
        particles land in the engines' ``lost`` flags, so
        ``lost_particles`` counts them without a facade-side bump)."""
        pol = self.config.sentinel
        views = [
            e.caller_order_view(("x", "done")) for e in self.engines
        ]
        x0 = jnp.concatenate([s[1] for s in stash], axis=0)
        x1 = jnp.concatenate([v["x"] for v in views], axis=0)
        fly = jnp.concatenate(
            [s[3][: self.engines[s[0]].n] for s in stash]
        )
        w = jnp.concatenate(
            [s[4][: self.engines[s[0]].n] for s in stash]
        )
        done = jnp.concatenate([v["done"] for v in views])
        n_unf, mask = self._sentinel.audit(
            x0, x1, fly, w, done, self.flux
        )
        recovered = lost = 0
        if n_unf and pol.straggler_retry:
            new_oks = []
            for (k, x0k, dest, fly_k, w_k, _sb, _sf), ok in zip(
                stash, oks
            ):
                eng = self.engines[k]
                done_k = np.asarray(views[k]["done"])
                unf = ~done_k & (np.asarray(fly_k)[: eng.n] == 1)
                if not unf.any():
                    new_oks.append(ok)
                    continue
                ok_r = eng.retry_stragglers(pol.retry_iters_factor)
                lost_k = 0
                if not ok_r:
                    self._quarantine_partitioned_chunk(
                        k, eng, x0k, dest, w_k
                    )
                    lost_k = eng.declare_lost_stragglers()
                lost += lost_k
                recovered += int(unf.sum()) - lost_k
                new_oks.append(lost_k == 0)
            oks = new_oks
            self._sentinel.resync(self.flux)
        self._sentinel.note_outcome(
            mask, n_unf, recovered, lost, self.iter_count - 1
        )
        return oks

    def _quarantine_partitioned_chunk(self, k, eng, x0, dest, w) -> None:
        from pumiumtally_tpu.sentinel.quarantine import (
            append_quarantine,
            build_records,
        )

        lo, _hi = self._chunk_bounds(k)
        view = eng.caller_order_view(("done", "elem_orig"))
        done = np.asarray(view["done"])
        idx = np.flatnonzero(~done)
        if idx.size == 0:
            return
        sel = jnp.asarray(idx)
        append_quarantine(
            self.config.sentinel.quarantine_dir,
            build_records(
                idx, np.asarray(x0[sel]), np.asarray(dest[sel]),
                np.asarray(view["elem_orig"])[idx], np.asarray(w[sel]),
                self.iter_count - 1, pid_offset=lo,
            ),
        )

    @property
    def flux(self) -> jnp.ndarray:
        if self.config.device_groups > 1:
            # Engines live on DISJOINT device groups; device-side adds
            # across committed arrays on different devices are invalid,
            # so assemble on the host (this is the output path).
            total = np.zeros(self.mesh.nelems, np.float64)
            for e in self.engines:
                total += np.asarray(e.flux_original(), np.float64)
            return jnp.asarray(total, self.dtype)
        total = self.engines[0].flux_original()
        for e in self.engines[1:]:
            total = total + e.flux_original()
        return total

    @property
    def score_bank(self) -> jnp.ndarray:
        """Scoring lanes summed over the chunk engines' canonical
        views (same assembly rules as ``flux``, device-groups
        included)."""
        self._require_scoring()
        if self.config.device_groups > 1:
            stride = self.engines[0].score_stride
            total = np.zeros(self.mesh.nelems * stride, np.float64)
            for e in self.engines:
                total += np.asarray(e.score_original(), np.float64)
            return jnp.asarray(total, self.dtype)
        total = self.engines[0].score_original()
        for e in self.engines[1:]:
            total = total + e.score_original()
        return total
