"""Atomic generational checkpoint store.

One checkpoint file is not fault tolerance: a crash mid-save corrupts
the only copy, and storage damage (a truncated write on a preempted VM,
a flipped bit) turns "resume" into a crash at the worst moment. The
store keeps the last K GENERATIONS, each written atomically and sealed
with an integrity digest, and on load walks backward past any damaged
generation with a warning instead of dying:

- **Atomic writes**: payload → temp file in the store directory →
  flush → fsync → ``os.replace`` → directory fsync. A crash at ANY
  instant leaves every previously committed generation untouched.
- **Integrity header**: each file opens with one ASCII line ::

      PUMIUMTALLY-CKPT1 gen=<n> sha256=<hex> bytes=<n> meta=<b64 json>

  followed by the raw ``.npz`` payload. Load recomputes the sha256
  over the payload; any mismatch (truncation, bit flip, foreign file)
  is ``CorruptCheckpointError`` — detected BEFORE the tally is
  touched, never a half-restored engine.
- **Generational fallback**: ``load_latest`` tries the newest
  generation first and falls back generation-by-generation past
  corrupt files (one warning each); only when EVERY generation is
  damaged does it raise. Header mismatches (wrong mesh / particle
  count) are configuration errors, not damage — those raise
  immediately.
- **Payload validation**: a digest-clean payload carrying non-finite
  flux/positions (e.g. a NaN that poisoned the engine before the save)
  is treated as corrupt too — resuming it would relive the poisoning.
"""

from __future__ import annotations

import base64
import hashlib
import io
import json
import os
import re
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pumiumtally_tpu.resilience import faults
from pumiumtally_tpu.utils.checkpoint import (
    CorruptCheckpointError,
    apply_tally_state,
    atomic_write,
    collect_tally_state,
    read_checkpoint_arrays,
)

_MAGIC = "PUMIUMTALLY-CKPT1"
_NAME_RE = re.compile(r"^gen-(\d{8})\.ckpt$")
# Header fields are bounded: a damaged file must not make the reader
# slurp gigabytes hunting for a newline.
_MAX_HEADER = 64 * 1024


@dataclass(frozen=True)
class ResumeInfo:
    """What ``load_latest``/``resume_latest`` restored: which
    generation, from which file, with the saver's metadata (at least
    ``iter_count`` and ``batches_closed`` for autosaved generations)."""

    generation: int
    path: str
    meta: Dict[str, Any] = field(default_factory=dict)


class GenerationStore:
    """Atomic, digest-sealed, keep-last-K checkpoint directory."""

    def __init__(self, directory: str, keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep!r}")
        self.directory = directory
        self.keep = int(keep)
        os.makedirs(directory, exist_ok=True)
        # A hard kill between the temp-file fsync and the rename (the
        # kill@save fault; a real preemption SIGKILL) orphans one
        # checkpoint-sized .tmp file. Stores are single-writer, so at
        # startup any temp file is a dead writer's — sweep them rather
        # than leak one per preemption across a long campaign.
        for name in os.listdir(directory):
            if name.startswith(".tmp-gen-"):
                try:
                    os.unlink(os.path.join(directory, name))
                except OSError:
                    pass

    # -- enumeration ----------------------------------------------------
    def generations(self) -> List[Tuple[int, str]]:
        """(generation, path) pairs, ascending. Temp files and foreign
        names are ignored."""
        out = []
        for name in os.listdir(self.directory):
            m = _NAME_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.directory, name)))
        out.sort()
        return out

    def _path(self, generation: int) -> str:
        return os.path.join(self.directory, f"gen-{generation:08d}.ckpt")

    # -- save -----------------------------------------------------------
    def save(self, tally, meta: Optional[Dict[str, Any]] = None
             ) -> Tuple[int, str]:
        """Write the next generation atomically; returns (gen, path).
        Fault-injection hooks (resilience.faults) fire at their
        documented points when PUMIUMTALLY_FAULT is armed."""
        gens = self.generations()
        generation = gens[-1][0] + 1 if gens else 1
        arrays = collect_tally_state(tally)
        faults.corrupt_payload_arrays(arrays, generation)
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        payload = buf.getvalue()
        digest = hashlib.sha256(payload).hexdigest()
        meta_b64 = base64.urlsafe_b64encode(
            json.dumps(meta or {}, sort_keys=True, default=str).encode()
        ).decode("ascii")
        header = (
            f"{_MAGIC} gen={generation} sha256={digest} "
            f"bytes={len(payload)} meta={meta_b64}\n"
        ).encode("ascii")
        final = self._path(generation)

        def write_payload(f):
            f.write(header)
            f.write(payload)

        atomic_write(
            final, write_payload,
            tmp_path=os.path.join(
                self.directory, f".tmp-gen-{generation:08d}-{os.getpid()}"
            ),
            pre_replace=lambda: faults.maybe_kill_mid_save(generation),
        )
        faults.damage_after_save(final, generation)
        self.prune()
        return generation, final

    def prune(self) -> None:
        """Drop the oldest generations beyond ``keep`` (never the
        newest — the fallback chain shrinks from the tail)."""
        gens = self.generations()
        for _, path in gens[: max(0, len(gens) - self.keep)]:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- load -----------------------------------------------------------
    def read_generation(self, path: str) -> Tuple[bytes, int, Dict[str, Any]]:
        """Verify one generation file end-to-end; returns
        (payload bytes, generation, meta). ANY damage — bad magic,
        unparseable header, short payload, digest mismatch — raises
        ``CorruptCheckpointError``."""
        try:
            with open(path, "rb") as f:
                head = f.readline(_MAX_HEADER)
                payload = f.read()
        except OSError as e:
            raise CorruptCheckpointError(
                f"unreadable checkpoint {path!r}: {e}"
            ) from e
        try:
            text = head.decode("ascii").rstrip("\n")
            if not text.startswith(_MAGIC + " "):
                raise ValueError("bad magic")
            fields = dict(
                kv.split("=", 1) for kv in text.split(" ")[1:]
            )
            generation = int(fields["gen"])
            digest = fields["sha256"]
            nbytes = int(fields["bytes"])
            meta = json.loads(
                base64.urlsafe_b64decode(fields["meta"].encode("ascii"))
            )
        # json/base64/int errors are all ValueError subclasses.
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            raise CorruptCheckpointError(
                f"corrupt checkpoint {path!r}: unparseable header ({e})"
            ) from e
        if len(payload) != nbytes:
            raise CorruptCheckpointError(
                f"corrupt checkpoint {path!r}: payload is {len(payload)} "
                f"bytes, header promises {nbytes} (truncated write?)"
            )
        if hashlib.sha256(payload).hexdigest() != digest:
            raise CorruptCheckpointError(
                f"corrupt checkpoint {path!r}: sha256 digest mismatch "
                "(bit flip or partial overwrite)"
            )
        return payload, generation, meta

    def load_latest(self, tally) -> Optional[ResumeInfo]:
        """Restore the newest loadable generation into ``tally``.

        Falls back generation-by-generation past corrupt files, each
        with a warning; returns None when the store holds no
        generations at all; raises ``CorruptCheckpointError`` when
        every generation present is damaged, and plain ValueError when
        a VALID generation does not fit the target (config error —
        older generations would not fit either)."""
        gens = self.generations()
        if not gens:
            return None
        for generation, path in reversed(gens):
            try:
                payload, g, meta = self.read_generation(path)
                z = read_checkpoint_arrays(io.BytesIO(payload))
                _validate_payload(z, path)
                apply_tally_state(tally, z)
            except CorruptCheckpointError as e:
                warnings.warn(
                    f"checkpoint generation {generation} is corrupt and "
                    f"was skipped ({e}); falling back to the previous "
                    "generation"
                )
                continue
            return ResumeInfo(generation=g, path=path, meta=meta)
        raise CorruptCheckpointError(
            f"every checkpoint generation in {self.directory!r} is "
            f"corrupt ({len(gens)} tried); nothing to resume from"
        )


def _validate_payload(z: dict, path: str) -> None:
    """Digest-clean but non-physical payloads are corruption too: a
    NaN/Inf flux or position would silently poison every tally after
    the resume (the same failure TallyConfig.validate_inputs refuses
    at staging time)."""
    for key in ("flux", "x"):
        if key in z and not np.isfinite(np.asarray(z[key])).all():
            raise CorruptCheckpointError(
                f"corrupt checkpoint {path!r}: non-finite values in "
                f"{key!r} payload"
            )


def resume_latest(tally, directory: Optional[str] = None
                  ) -> Optional[ResumeInfo]:
    """Discovery-and-restore for a restarted campaign: find the newest
    loadable generation under ``directory`` (default: the tally's
    ``TallyConfig.checkpoint.dir``) and restore it into ``tally``.

    Returns the ``ResumeInfo`` (its ``meta`` carries the saver's
    ``iter_count``/``batches_closed``) or None when no checkpoint
    exists yet — the idempotent start-of-campaign pattern::

        tally = PumiTally(mesh, n, TallyConfig(checkpoint=policy))
        info = resume_latest(tally)
        start = tally.iter_count if info else 0

    When the tally runs an autosave policy, its runner's batch/cadence
    counters are re-synced from the restored metadata so generation
    numbering and cadence continue seamlessly."""
    if directory is None:
        policy = getattr(tally.config, "checkpoint", None)
        if policy is None:
            raise ValueError(
                "resume_latest needs a directory (or a tally built "
                "with TallyConfig(checkpoint=CheckpointPolicy(...)))"
            )
        directory = policy.dir
        keep = policy.keep
    else:
        keep = 3
    store = GenerationStore(directory, keep=keep)
    info = store.load_latest(tally)
    runner = getattr(tally, "_resilience", None)
    if info is not None and runner is not None:
        runner.sync_from_resume(info)
    return info
