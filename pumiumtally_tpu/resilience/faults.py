"""Deterministic fault injection for the resilience layer.

A fault-tolerance subsystem that is only ever exercised by real crashes
is untested by construction. This module injects the failure modes the
checkpoint layer claims to survive, at DETERMINISTIC points keyed to
checkpoint generation / batch ordinals (cross-process stable, so a
kill-and-resume test reproduces exactly), driven by one environment
variable:

    PUMIUMTALLY_FAULT=<action>@<site>:<ordinal>[:<arg>]

Grammar (docs/DESIGN.md "Fault tolerance" holds the contract each
fault is meant to violate):

- ``kill@save:N``      SIGKILL this process in the middle of writing
                       checkpoint generation N — after the temp file is
                       flushed and fsync'd, BEFORE the atomic
                       ``os.replace``. The atomicity contract says the
                       store must be left with generation N-1 intact
                       and no generation N.
- ``sigterm@batch:N``  deliver SIGTERM to this process at the Nth
                       batch-close hook (before any cadence save) —
                       exercises the graceful-drain handler: finish the
                       hook, save, exit 0.
- ``truncate@gen:N[:B]``  after generation N is fully written, cut B
                       bytes (default 64) off the end of the file —
                       the digest check must catch it on load.
- ``bitflip@gen:N[:OFF]`` after generation N is fully written, XOR one
                       byte at offset OFF (default: middle of the
                       payload) — the digest check must catch it.
- ``nan@gen:N``        poison the flux array with NaN BEFORE the
                       payload is serialized and digested — the file
                       verifies clean, so this exercises the loader's
                       payload validation, not the digest.

All hooks are no-ops when the variable is unset; a malformed spec
raises immediately (a typo'd fault that silently never fires would be
a green test proving nothing).
"""

from __future__ import annotations

import os
import signal
from dataclasses import dataclass
from typing import Optional

import numpy as np

FAULT_ENV = "PUMIUMTALLY_FAULT"

_VALID = {
    ("kill", "save"),
    ("sigterm", "batch"),
    ("truncate", "gen"),
    ("bitflip", "gen"),
    ("nan", "gen"),
}
_GRAMMAR = (
    "expected <action>@<site>:<ordinal>[:<arg>] with (action, site) one "
    "of kill@save, sigterm@batch, truncate@gen, bitflip@gen, nan@gen"
)


@dataclass(frozen=True)
class FaultSpec:
    action: str
    site: str
    ordinal: int
    arg: Optional[int] = None

    def matches(self, site: str, ordinal: int) -> bool:
        return self.site == site and self.ordinal == int(ordinal)


def parse_fault(spec: str) -> FaultSpec:
    """Parse one fault spec; raises ValueError with the grammar on any
    malformation."""
    try:
        action, rest = spec.split("@", 1)
        parts = rest.split(":")
        site = parts[0]
        ordinal = int(parts[1])
        arg = int(parts[2]) if len(parts) > 2 else None
        if len(parts) > 3:
            raise ValueError("too many ':' fields")
    except (ValueError, IndexError) as e:
        raise ValueError(
            f"bad {FAULT_ENV} spec {spec!r}: {_GRAMMAR}"
        ) from e
    if (action, site) not in _VALID:
        raise ValueError(
            f"bad {FAULT_ENV} spec {spec!r}: unknown fault "
            f"{action}@{site}; {_GRAMMAR}"
        )
    if ordinal < 1:
        raise ValueError(
            f"bad {FAULT_ENV} spec {spec!r}: ordinal must be >= 1 "
            "(generations and batch closes count from 1)"
        )
    return FaultSpec(action=action, site=site, ordinal=ordinal, arg=arg)


def active_fault() -> Optional[FaultSpec]:
    """The process's injected fault, or None. Read from the environment
    on every call (cheap) so tests can arm/disarm without reloads."""
    spec = os.environ.get(FAULT_ENV)
    if not spec:
        return None
    return parse_fault(spec)


# -- hooks (called by the generation store / autosave runner) -----------

def corrupt_payload_arrays(arrays: dict, generation: int) -> None:
    """``nan@gen:N``: poison the flux BEFORE serialization, so the
    written file carries a VALID digest around non-physical data."""
    f = active_fault()
    if f is not None and f.action == "nan" and f.matches("gen", generation):
        arrays["flux"] = np.full_like(
            np.asarray(arrays["flux"], np.float64), np.nan
        )


def maybe_kill_mid_save(generation: int) -> None:
    """``kill@save:N``: SIGKILL between the temp-file fsync and the
    atomic rename — the hardest point for a non-atomic writer."""
    f = active_fault()
    if f is not None and f.action == "kill" and f.matches("save", generation):
        os.kill(os.getpid(), signal.SIGKILL)


def damage_after_save(path: str, generation: int) -> None:
    """``truncate@gen:N`` / ``bitflip@gen:N``: storage-level damage to
    a fully written generation file."""
    f = active_fault()
    if f is None or not f.matches("gen", generation):
        return
    if f.action == "truncate":
        cut = f.arg if f.arg is not None else 64
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(0, size - cut))
    elif f.action == "bitflip":
        size = os.path.getsize(path)
        off = f.arg if f.arg is not None else size // 2
        off = min(max(0, off), size - 1)
        with open(path, "r+b") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))


def maybe_sigterm_at_batch(batches_closed: int) -> None:
    """``sigterm@batch:N``: deliver a real SIGTERM to this process at
    the Nth batch-close hook (the handler runs synchronously in the
    main thread, so the drain flag is set before the hook continues)."""
    f = active_fault()
    if f is not None and f.action == "sigterm" and f.matches(
        "batch", batches_closed
    ):
        os.kill(os.getpid(), signal.SIGTERM)
