"""Fault-tolerant campaigns (round 8, docs/DESIGN.md "Fault tolerance").

Production TPU fleets get preempted; a long Monte Carlo campaign must
survive that bitwise. This package layers three mechanisms over the
checkpoint format (utils/checkpoint.py):

- ``GenerationStore`` / ``resume_latest`` — atomic, sha256-sealed,
  keep-last-K checkpoint generations with corruption fallback
  (generations.py);
- ``CheckpointPolicy`` / ``AutosaveRunner`` — autosave cadence hooked
  into every engine facade at batch close, plus the SIGTERM/SIGINT
  graceful-drain handler (policy.py);
- ``faults`` — the deterministic fault-injection harness
  (``PUMIUMTALLY_FAULT``) that proves the first two under process
  kill, truncation, bit flips, and NaN payloads (faults.py).

Everything here is host-side Python over numpy buffers — no jitted
code, no new trace entry points (config.RETRACE_BUDGETS unchanged).
"""

from pumiumtally_tpu.resilience.faults import FAULT_ENV, FaultSpec, parse_fault
from pumiumtally_tpu.resilience.generations import (
    GenerationStore,
    ResumeInfo,
    resume_latest,
)
from pumiumtally_tpu.resilience.policy import (
    AutosaveRunner,
    CheckpointPolicy,
    install_drain_owner,
    release_drain_owner,
)
from pumiumtally_tpu.utils.checkpoint import CorruptCheckpointError

__all__ = [
    "AutosaveRunner",
    "CheckpointPolicy",
    "CorruptCheckpointError",
    "FAULT_ENV",
    "FaultSpec",
    "GenerationStore",
    "ResumeInfo",
    "install_drain_owner",
    "parse_fault",
    "release_drain_owner",
    "resume_latest",
]
