"""Autosave policy + preemption-safe drain.

The checkpoint layer (PR 5/6) fixed the FORMAT; saving was still
entirely manual, and nothing handled the signal a preempted TPU VM
actually receives (SIGTERM, with a short grace window). This module
closes both holes:

- ``CheckpointPolicy`` — declarative autosave cadence, carried on
  ``TallyConfig.checkpoint``. The facades call the runner's hooks at
  batch close (every ``CopyInitialPosition`` that closes a non-empty
  source batch, plus ``close_batch``/``finalize``) and at the end of
  each move; saves happen OFF the critical path — only when the
  cadence fires, never per call.
- ``AutosaveRunner`` — the per-tally engine behind the policy: owns
  the ``GenerationStore``, tracks batch/move counters, and implements
  graceful drain. First SIGTERM/SIGINT sets a flag; the in-flight
  particle batch finishes (signals never interrupt device work
  mid-move), the next hook saves a final generation and exits 0. A
  SECOND signal restores the previous handler and re-delivers — an
  operator's double ctrl-C still kills immediately.

Cadence semantics: ``every_n_batches`` counts CLOSED source batches
(an empty batch is not a sample, mirroring the statistics layer);
``every_seconds`` is wall time since the last save, checked at every
hook (so a single long source batch still checkpoints). Either may be
None; with both None only drain/manual saves happen.
"""

from __future__ import annotations

import dataclasses
import signal
import threading
import time
import warnings
from typing import Any, Dict, Optional, Tuple

from pumiumtally_tpu.resilience import faults
from pumiumtally_tpu.resilience.generations import GenerationStore, ResumeInfo


@dataclasses.dataclass
class CheckpointPolicy:
    """Declarative autosave for a campaign (TallyConfig.checkpoint).

    Attributes:
      dir: generation-store directory (created on first use).
      every_n_batches: save after this many closed source batches
        (None disables the batch cadence).
      every_seconds: save when this much wall time passed since the
        last save, checked at every batch close and move end (None
        disables the timer cadence).
      keep: how many generations the store retains (older ones are
        pruned; the on-load fallback chain is at most this long).
      handle_signals: install the SIGTERM/SIGINT graceful-drain
        handler (main thread only; silently skipped elsewhere).
    """

    dir: str
    every_n_batches: Optional[int] = 1
    every_seconds: Optional[float] = None
    keep: int = 3
    handle_signals: bool = True

    def __post_init__(self) -> None:
        if not self.dir:
            raise ValueError("CheckpointPolicy.dir must be a directory path")
        if self.keep < 1:
            raise ValueError(f"keep must be >= 1, got {self.keep!r}")
        if self.every_n_batches is not None and int(self.every_n_batches) < 1:
            raise ValueError(
                f"every_n_batches must be >= 1 or None, "
                f"got {self.every_n_batches!r}"
            )
        if self.every_seconds is not None and float(self.every_seconds) <= 0:
            raise ValueError(
                f"every_seconds must be > 0 or None, "
                f"got {self.every_seconds!r}"
            )


# Process-wide signal state: ONE dispatcher owns SIGTERM/SIGINT no
# matter how many checkpoint-armed tallies exist, and the second-signal
# escalation always restores the ORIGINAL (pre-any-runner) disposition
# — stacking per-runner handlers would make a second ctrl-C land in a
# stale runner's handler and merely set a dead drain flag.
_signal_originals: Dict[int, Any] = {}
_active_runner: Optional["AutosaveRunner"] = None


def _signal_dispatch(signum, frame) -> None:
    runner = _active_runner
    if runner is None or runner._drain:
        # Second signal (or no live runner): the operator means it.
        # Restore the original dispositions and re-deliver immediately.
        _restore_signal_originals()
        signal.raise_signal(signum)
        return
    runner._drain = True


def _install_signal_dispatch(runner: "AutosaveRunner") -> None:
    global _active_runner
    if threading.current_thread() is not threading.main_thread():
        warnings.warn(
            "CheckpointPolicy(handle_signals=True) outside the main "
            "thread: Python only delivers signals to the main "
            "thread, so the graceful-drain handler was not installed"
        )
        return
    if not _signal_originals:
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                _signal_originals[sig] = signal.signal(
                    sig, _signal_dispatch
                )
            except (ValueError, OSError):  # embedded/exotic runtimes
                _signal_originals.pop(sig, None)
    _active_runner = runner


def _restore_signal_originals() -> None:
    global _active_runner
    _active_runner = None
    for sig, prev in list(_signal_originals.items()):
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError):
            pass
    _signal_originals.clear()


def install_drain_owner(owner: Any) -> None:
    """Hand the process-wide SIGTERM/SIGINT drain dispatch to ``owner``
    — any object with a writable ``_drain`` flag (duck-typed: an
    ``AutosaveRunner``, or the multi-session service, which drains
    EVERY open session when its flag trips). Newest owner wins, the
    second-signal escalation still restores the original dispositions
    and re-delivers, and the originals are captured exactly once —
    the same single-dispatcher invariant the runners rely on.
    Idempotent: re-installing the current owner is a no-op (no
    duplicate capture, no spurious warnings)."""
    if _active_runner is owner:
        return
    _install_signal_dispatch(owner)


def release_drain_owner(owner: Any) -> None:
    """Detach ``owner`` from the drain dispatch (restores the original
    signal dispositions iff ``owner`` is the current owner; a stale
    release after a newer owner took over is a no-op)."""
    if _active_runner is owner:
        _restore_signal_originals()


class AutosaveRunner:
    """Per-tally autosave engine (built by the facades from
    ``TallyConfig.checkpoint``; one per tally instance). The newest
    runner with ``handle_signals`` owns the process's drain handler."""

    def __init__(self, policy: CheckpointPolicy):
        self.policy = policy
        self.store = GenerationStore(policy.dir, keep=policy.keep)
        self.batches_closed = 0
        self.moves_since_close = 0
        self._drain = False
        self._last_save_monotonic = time.monotonic()
        self._last_saved_iter: Optional[int] = None
        if policy.handle_signals:
            _install_signal_dispatch(self)

    # -- signals ---------------------------------------------------------
    @property
    def drain_requested(self) -> bool:
        return self._drain

    def _restore_handlers(self) -> None:
        if _active_runner is self:
            _restore_signal_originals()

    def close(self) -> None:
        """Detach from the process (restore the original signal
        dispositions when this runner owns them). Called by tests; a
        draining exit restores them itself."""
        self._restore_handlers()

    # -- hooks (called by the facades) ------------------------------------
    def on_move(self, tally) -> None:
        """End of one MoveToNextLocation: a state-exact point (device
        work for the particle batch is complete). A pending drain
        writes a SAFETY generation here — if the preemption grace
        window expires before the source batch closes, at most one
        move is lost — but the clean exit waits for the batch close,
        so the newest generation a drained process leaves behind is
        batch-aligned (the resume recipe drivers actually use)."""
        self.moves_since_close += 1
        if self._drain:
            if self._last_saved_iter != int(tally.iter_count):
                self.save(tally, reason="drain_safety")
        elif self._timer_due():
            self.save(tally, reason="every_seconds")

    def on_batch_close(self, tally) -> None:
        """A source batch closed (CopyInitialPosition over a non-empty
        batch, close_batch, finalize). The primary autosave point."""
        if self.moves_since_close == 0:
            # Empty batch (back-to-back re-sourcing): not a sample,
            # not a cadence tick — but a pending drain still exits.
            if self._drain:
                self._drain_exit(tally)
            return
        self.batches_closed += 1
        self.moves_since_close = 0
        faults.maybe_sigterm_at_batch(self.batches_closed)
        if self._drain:
            self._drain_exit(tally)
        n = self.policy.every_n_batches
        if (n is not None and self.batches_closed % int(n) == 0) or (
            self._timer_due()
        ):
            self.save(tally, reason="batch_close")

    def _timer_due(self) -> bool:
        s = self.policy.every_seconds
        return s is not None and (
            time.monotonic() - self._last_save_monotonic >= float(s)
        )

    # -- saving ------------------------------------------------------------
    def save(self, tally, reason: str = "manual",
             meta: Optional[Dict[str, Any]] = None) -> Tuple[int, str]:
        m = dict(meta) if meta else {}
        # Reserved keys win over caller extras: sync_from_resume reads
        # them back into the cadence counters, so a checkpoint_now
        # kwarg shadowing iter_count would desynchronize every resume.
        m.update(
            reason=reason,
            iter_count=int(tally.iter_count),
            batches_closed=int(self.batches_closed),
        )
        gen, path = self.store.save(tally, meta=m)
        self._last_save_monotonic = time.monotonic()
        self._last_saved_iter = int(tally.iter_count)
        return gen, path

    def _drain_exit(self, tally) -> None:
        """Graceful drain at a batch close: the in-flight source batch
        just finished, so save — unless this exact state was just
        saved — restore the signal handlers, and exit cleanly."""
        if self._last_saved_iter != int(tally.iter_count):
            self.save(tally, reason="drain")
        self._restore_handlers()
        raise SystemExit(0)

    def sync_from_resume(self, info: ResumeInfo) -> None:
        """Continue counters from a restored generation so cadence and
        metadata stay monotone across the restart."""
        self.batches_closed = int(info.meta.get("batches_closed", 0))
        self.moves_since_close = 0
        self._last_save_monotonic = time.monotonic()
        self._last_saved_iter = int(info.meta.get("iter_count", -1))
