"""Runtime sentinels & graceful degradation (round 9, docs/DESIGN.md
"Failure taxonomy").

PR 8 made campaigns survive *crashes*; this package makes them survive
the engine's own failure modes, in flight:

- **Audit lanes** (audit.py) — opt-in per-move on-device diagnostics:
  unfinished-particle count after the walk loop, the tallied-length vs
  straight-line-length conservation residual (the bench-only gate
  moved on-device), and a non-finite-flux probe, packed into ONE
  scalar fetch per move.
- **Straggler escalation** (straggler.py) — particles that exhaust
  ``max_iters`` are no longer silently truncated: a bounded retry
  ladder (2× budget on the compacted residue → exact-f32 retry for
  bf16 tiers → quarantine + ``lost_particles``).
- **Quarantine** (quarantine.py) — an append-safe JSONL record of
  every particle nothing could recover, for postmortem re-injection.
- **Policy/report** (policy.py, runner.py) — ``SentinelPolicy`` on
  ``TallyConfig.sentinel`` arms all of it; ``tally.health_report()``
  returns the cumulative ``HealthReport`` (also written as VTK FIELD
  data). The partitioned overflow-recovery ladder
  (parallel/partition.py) reports its events through the same runner.

Sentinel-off (the default) constructs nothing anywhere: every engine
is bitwise-identical and allocation-free vs a sentinel-less build —
the same contract as stats-off and checkpoint-off, pinned by
tests/test_sentinel.py and the bench A/B parity gate
(tools/exp_sentinel_ab.py).
"""

from pumiumtally_tpu.sentinel.policy import (
    ANOMALY_CONSERVATION,
    ANOMALY_NONFINITE,
    ANOMALY_UNFINISHED,
    EnginePoisonedError,
    HealthReport,
    POISONED_MESSAGE,
    SentinelAnomalyError,
    SentinelPolicy,
    describe_mask,
)
from pumiumtally_tpu.sentinel.quarantine import (
    append_quarantine,
    quarantine_path,
    read_quarantine,
)
from pumiumtally_tpu.sentinel.runner import SentinelRunner, build_runner

__all__ = [
    "ANOMALY_CONSERVATION",
    "ANOMALY_NONFINITE",
    "ANOMALY_UNFINISHED",
    "EnginePoisonedError",
    "HealthReport",
    "POISONED_MESSAGE",
    "SentinelAnomalyError",
    "SentinelPolicy",
    "SentinelRunner",
    "append_quarantine",
    "build_runner",
    "describe_mask",
    "quarantine_path",
    "read_quarantine",
]
