"""Sentinel policy + health report (round 9, docs/DESIGN.md "Failure
taxonomy").

The engine's existing gates (input validation, conservation in bench,
the found-all ERROR print) either run off-device, run only in the
bench, or merely *print*: an in-flight anomaly — a walk that exhausts
``max_iters``, a flux delta that stopped matching the straight-line
track length, a non-finite accumulator — used to corrupt the campaign
silently. ``SentinelPolicy`` arms the runtime health subsystem on a
tally (``TallyConfig.sentinel``): cheap on-device per-move audit lanes
packed into ONE scalar fetch, a bounded straggler-escalation ladder in
place of silent truncation, and quarantine accounting for particles
nothing could recover. Sentinel-off (the default) constructs nothing:
every engine stays bitwise-identical and allocation-free, the same
contract as stats-off / checkpoint-off.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Anomaly bitmask (low 3 bits of the packed audit scalar; the
# remaining bits carry the unfinished-particle count — see
# audit.pack_audit / split_packed).
ANOMALY_UNFINISHED = 1  # particles not done when the walk loop exited
ANOMALY_CONSERVATION = 2  # tallied-vs-straight-line residual over rtol
ANOMALY_NONFINITE = 4  # non-finite flux delta (poisoned accumulator)
_ANOMALY_BITS = 3  # bit width of the mask inside the packed scalar

ANOMALY_NAMES = {
    ANOMALY_UNFINISHED: "unfinished",
    ANOMALY_CONSERVATION: "conservation",
    ANOMALY_NONFINITE: "nonfinite_flux",
}


def describe_mask(mask: int) -> str:
    """Human-readable anomaly mask, for warnings and reports."""
    names = [n for bit, n in ANOMALY_NAMES.items() if mask & bit]
    return "+".join(names) if names else "none"


@dataclasses.dataclass(frozen=True)
class SentinelPolicy:
    """Runtime health knobs (TallyConfig.sentinel).

    Attributes:
      audit: per-move on-device audit lanes — unfinished-particle
        count, tallied-length vs straight-line-length conservation
        residual (the bench-only ``check_conservation`` gate moved
        on-device), and a non-finite-flux probe — packed into one
        scalar fetched per move. Under the default fenced timing this
        adds no sync point (the facade already blocks on the flux);
        an unfenced pipeline pays one scalar sync per move for the
        audit, which is why it is a policy knob and not always-on.
      conservation_rtol: relative residual above which the
        conservation bit fires. ``None`` → 1e-9 in f64, 1e-3
        otherwise (the residual of a healthy move is pure
        accumulation rounding, ~ulp·sqrt(n) — the f32 default leaves
        headroom for million-particle batches). Two-phase moves whose
        phase-A relocation clamps at the hull legitimately travel less
        than ``|x1 − origin|`` — the audit measures phase B against the
        staged origins — so non-convex relocation workloads should
        widen this or read the report instead of raising.
      straggler_retry: arm the escalation ladder — particles still
        unfinished when the walk loop exits are no longer silently
        truncated mid-flight; they are compacted and re-dispatched
        with ``retry_iters_factor``× the iteration budget, bf16
        two-tier engines additionally retry against the exact
        f32/hi-tier tables, and only then is a particle declared lost
        (folded into ``lost_particles`` + a quarantine record).
      retry_iters_factor: iteration-budget multiplier for the retry
        rungs (the partitioned retry also multiplies the round
        budget).
      quarantine_dir: directory for ``quarantine.jsonl`` — one record
        per unrecoverable particle (pid, origin, dest, element,
        weight, move) for postmortem re-injection. ``None`` keeps
        quarantine accounting in the health report only.
      on_anomaly: what a non-zero audit mask does beyond counting:
        ``"warn"`` prints one warning per anomalous move, ``"raise"``
        raises ``SentinelAnomalyError`` (the move's state is already
        committed — the raise is a tripwire, not a rollback),
        ``"record"`` only accumulates into the health report.
    """

    audit: bool = True
    conservation_rtol: Optional[float] = None
    straggler_retry: bool = True
    retry_iters_factor: int = 2
    quarantine_dir: Optional[str] = None
    on_anomaly: str = "warn"

    def __post_init__(self) -> None:
        if self.on_anomaly not in ("warn", "raise", "record"):
            raise ValueError(
                "on_anomaly must be 'warn', 'raise' or 'record', "
                f"got {self.on_anomaly!r}"
            )
        if int(self.retry_iters_factor) < 1:
            raise ValueError(
                f"retry_iters_factor must be >= 1, "
                f"got {self.retry_iters_factor!r}"
            )
        if self.conservation_rtol is not None and (
            float(self.conservation_rtol) <= 0
        ):
            raise ValueError(
                f"conservation_rtol must be > 0 or None, "
                f"got {self.conservation_rtol!r}"
            )

    def resolved_rtol(self, dtype) -> float:
        import numpy as np

        if self.conservation_rtol is not None:
            return float(self.conservation_rtol)
        return 1e-9 if np.dtype(dtype) == np.float64 else 1e-3


class SentinelAnomalyError(RuntimeError):
    """An audited move tripped the anomaly mask under
    ``on_anomaly="raise"``. The move's state is committed (the audit
    runs after the walk); the campaign should checkpoint/abort rather
    than keep accumulating."""


class EnginePoisonedError(RuntimeError):
    """The engine state is known-corrupt (a partitioned capacity
    overflow exhausted the recovery ladder, or an unrecoverable
    mid-pipeline overflow); every further protocol call refuses until
    the tally is restored from a checkpoint."""


POISONED_MESSAGE = (
    "engine state corrupt — a capacity overflow exhausted the recovery "
    "ladder; resume from checkpoint (resilience.resume_latest) or "
    "rebuild the tally with a larger TallyConfig.capacity_factor"
)


@dataclasses.dataclass
class HealthReport:
    """Cumulative campaign health (``tally.health_report()``); also
    written as VTK FIELD data so a result file carries its own health
    record (io.vtk.health_field_data).

    ``moves_audited``/``anomaly_moves`` count audited moves and the
    subset with a non-zero anomaly mask; ``anomaly_mask_union`` ORs
    every move's mask (``describe_mask`` renders it).
    ``unfinished_total`` counts particle-moves that hit the iteration
    cap BEFORE the ladder ran; ``stragglers_recovered``/
    ``stragglers_lost`` split them by ladder outcome (recovered +
    lost == unfinished_total when the ladder is armed).
    ``max_conservation_residual`` is the worst relative residual seen.
    ``overflow_recoveries``/``capacity_escalations`` count partitioned
    overflow events the recovery ladder absorbed and the host-side
    capacity rebuilds among them.
    """

    moves_audited: int = 0
    anomaly_moves: int = 0
    anomaly_mask_union: int = 0
    max_conservation_residual: float = 0.0
    unfinished_total: int = 0
    stragglers_recovered: int = 0
    stragglers_lost: int = 0
    overflow_recoveries: int = 0
    capacity_escalations: int = 0

    def as_dict(self) -> dict:
        """Plain JSON-serializable summary (builtin ints/floats only)
        — what the service layer embeds in drain-checkpoint metadata
        and returns over the NDJSON socket's ``health`` op, and what
        the A/B tools report. ``dataclasses.asdict`` would work too;
        this pins the field set as API."""
        import dataclasses

        return {
            k: (float(v) if isinstance(v, float) else int(v))
            for k, v in dataclasses.asdict(self).items()
        }

    def as_field_data(self) -> dict:
        """Scalar FIELD arrays for the VTK writers (float64 — legacy
        VTK field blocks are typed, and every writer already emits
        float fields for lost_particles)."""
        import numpy as np

        return {
            "sentinel_moves_audited": np.asarray(
                [float(self.moves_audited)], np.float64
            ),
            "sentinel_anomaly_moves": np.asarray(
                [float(self.anomaly_moves)], np.float64
            ),
            "sentinel_anomaly_mask": np.asarray(
                [float(self.anomaly_mask_union)], np.float64
            ),
            "sentinel_max_conservation_residual": np.asarray(
                [float(self.max_conservation_residual)], np.float64
            ),
            "sentinel_stragglers_recovered": np.asarray(
                [float(self.stragglers_recovered)], np.float64
            ),
            "sentinel_stragglers_lost": np.asarray(
                [float(self.stragglers_lost)], np.float64
            ),
            "sentinel_overflow_recoveries": np.asarray(
                [float(self.overflow_recoveries)], np.float64
            ),
        }
