"""Straggler escalation for the replicated-mesh facades.

A particle still unfinished when the walk loop exits used to be
truncated mid-flight with zero signal: its partial track was tallied
(the s-telescoping commits exactly the traveled prefix) and the rest
silently dropped. The ladder re-dispatches the residue instead:

1. compact the stragglers into a small padded batch and re-walk them
   from their committed partial positions toward their original
   destinations with ``retry_iters_factor``× the iteration budget —
   the common cure (a forced-tiny ``max_iters``, an adversarial mesh
   corridor). Because the committed position IS the tallied position,
   the retry's tally continues the telescoped sum exactly: a recovered
   particle's flux/position/element match an unconstrained run.
2. two-tier (bf16 select) engines retry once more against the exact
   full-precision tables (``table_dtype="float32"`` walks the
   hi-tier planes the lowp mesh retains) — the cure for the
   documented tie-class dead ends of the select tier.
3. whatever remains is declared lost: the caller folds it into
   ``lost_particles`` and appends quarantine records
   (sentinel.quarantine).

The compacted batch is padded to the next power of two (floor 8) so
the retry program compiles O(log n) distinct shapes, not one per
straggler count; pad slots carry ``fly=0, dest=x`` and retire on the
first iteration with zero contribution (the walk's own contract).
Entry point ``straggler_retry`` (config.RETRACE_BUDGETS).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu.ops.walk import walk
from pumiumtally_tpu.utils.profiling import register_entry_point


def padded_size(k: int, floor: int = 8) -> int:
    """Next power of two >= k (>= floor) — the shape-quantization that
    bounds the retry's jit keys."""
    m = max(int(floor), 1)
    while m < k:
        m *= 2
    return m


@partial(jax.jit, static_argnames=("tol", "max_iters", "walk_kw",
                                   "score_kinds"))
def _retry_step(mesh, x, elem, dest, fly, w, flux, k, s_init=None,
                score_ops=None, *, tol, max_iters, walk_kw=(),
                score_kinds=()):
    """Tallied retry walk over one compacted straggler batch. ``k``
    (traced) marks the real rows; pad rows are forced inert
    (``fly=0, dest=x`` — the walk's hold contract) so duplicated pad
    indices can never double-tally. ``s_init`` (with ``x`` = the
    ORIGINAL phase start) continues the interrupted parametrization —
    see ops.walk.WalkResult.s. ``score_ops`` (round 10) continues the
    interrupted move's SCORING lanes the same way: the compacted
    rows' bin offsets / factor rows plus the facade's bank — the
    retry's remaining crossings score into the same lanes an
    uninterrupted walk would have."""
    valid = (jnp.cumsum(jnp.ones_like(elem)) - 1) < k
    fly_v = jnp.where(valid, fly, 0).astype(jnp.int8)
    dest_v = jnp.where((fly_v == 1)[:, None], dest, x)
    sc = None
    if score_ops is not None:
        from pumiumtally_tpu.scoring.binding import ScoreOps

        sc = ScoreOps(score_kinds, *score_ops)
    r = walk(
        mesh, x, elem, dest_v, fly_v, w, flux,
        tally=True, tol=tol, max_iters=max_iters, s_init=s_init,
        scoring=sc, **dict(walk_kw),
    )
    return r.x, r.elem, r.done, r.flux, r.s, r.score_bank


_retry_step = register_entry_point("straggler_retry", _retry_step)


def _f32_walk_kw(walk_kw: tuple) -> tuple:
    """The rung-2 key: the same tuned knobs with the table tier forced
    to the exact full-precision path (the lowp mesh's hi-tier rows back
    the f32 gather through the face_* views)."""
    kw = dict(walk_kw)
    kw["table_dtype"] = "float32"
    return tuple(sorted(kw.items()))


def run_ladder(
    mesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    dests: jnp.ndarray,
    fly: jnp.ndarray,
    w: jnp.ndarray,
    flux: jnp.ndarray,
    unfinished: np.ndarray,
    *,
    tol: float,
    base_iters: int,
    retry_factor: int,
    walk_kw: tuple = (),
    two_tier: bool = False,
    x_start: jnp.ndarray = None,
    s_init: jnp.ndarray = None,
    scoring=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, np.ndarray, np.ndarray,
           jnp.ndarray]:
    """Run the escalation ladder over the ``unfinished`` host mask.

    Arrays are the facade's committed caller-order state ([cap]-shaped,
    any padding already inert). With ``x_start``/``s_init`` (the
    phase's start positions and the walk's final ray coordinates) the
    retry CONTINUES the exact original parametrization — every
    remaining crossing computes bit-identically to an uninterrupted
    walk, so recovered flux is bitwise; without them (the non-tallying
    localization ladder) rungs restart from the committed partial
    positions. ``scoring = (kinds, bank, sbin, sfac)`` (round 10, the
    interrupted move's staged operands) continues the scoring lanes the
    same way. Returns ``(x, elem, flux, recovered_idx, lost_idx,
    bank)`` — ``bank`` None without scoring — with the straggler rows
    updated in place (scattered back) and the index sets as host int
    arrays. The caller must only invoke this when
    ``unfinished.any()``.
    """
    idx = np.flatnonzero(unfinished)
    k = idx.size
    m = padded_size(k)
    idx_pad = np.concatenate([idx, np.full(m - k, idx[0], idx.dtype)])
    idx_dev = jnp.asarray(idx_pad)
    continuing = x_start is not None and s_init is not None
    xs = (x_start if continuing else x)[idx_dev]
    es = elem[idx_dev]
    ss = s_init[idx_dev] if continuing else None
    ds, fs, ws = dests[idx_dev], fly[idx_dev], w[idx_dev]
    k_dev = jnp.asarray(k, jnp.int32)
    s_kinds: tuple = ()
    bank = sb_r = sf_r = None
    if scoring is not None:
        s_kinds, bank, sbin, sfac = scoring
        sb_r, sf_r = sbin[idx_dev], sfac[idx_dev]

    # The retry budget: retry_factor x the engine budget, floored at
    # the mesh-derived safe bound (config.resolved_max_iters'
    # heuristic) — a deliberately tiny engine max_iters (the truncation
    # scenario this ladder exists for) must not also starve its own
    # cure, and the walk's while_loop exits early anyway, so a
    # generous bound costs nothing at runtime.
    retry_iters = max(
        int(base_iters) * int(retry_factor), 64 + int(mesh.nelems)
    )
    rungs = [(retry_iters, walk_kw)]
    if two_tier:
        rungs.append((retry_iters, _f32_walk_kw(walk_kw)))
    # Committed outputs accumulate rung by rung: a particle's
    # (x, elem) are captured by the rung that FINISHES it and never
    # touched again (a later rung's zero-length re-walk of a finished
    # particle would not round-trip its position bitwise).
    x_out, e_out = xs, es
    done_acc = None
    for max_iters, kw in rungs:
        xr, er, done_r, flux, sr, bank = _retry_step(
            mesh, xs, es, ds, fs, ws, flux, k_dev, ss,
            None if scoring is None else (bank, sb_r, sf_r),
            tol=tol, max_iters=max_iters, walk_kw=kw,
            score_kinds=s_kinds,
        )
        if done_acc is None:
            x_out, e_out, done_acc = xr, er, done_r
        else:
            newly = done_r & ~done_acc
            x_out = jnp.where(newly[:, None], xr, x_out)
            e_out = jnp.where(newly, er, e_out)
            done_acc = done_acc | done_r
        if bool(jnp.all(done_acc[:k])):
            break
        # Later rungs re-dispatch ONLY the still-unfinished rows
        # (finished ones are masked inert: fly=0 -> hold) and continue
        # from the rung's committed progress: element from the rung,
        # ray coordinate chained in continuation mode, position
        # restarted from the rung's partial commit otherwise.
        fs = jnp.where(done_acc, 0, fs).astype(jnp.int8)
        es = er
        if continuing:
            ss = sr  # xs stays the ORIGINAL start: same ray
        else:
            xs = xr

    x = x.at[idx_dev[:k]].set(x_out[:k])
    elem = elem.at[idx_dev[:k]].set(e_out[:k])
    done_h = np.asarray(done_acc)[:k]
    return x, elem, flux, idx[done_h], idx[~done_h], bank
