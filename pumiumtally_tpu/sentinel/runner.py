"""Per-tally sentinel engine: audit bookkeeping + anomaly dispatch.

One ``SentinelRunner`` per armed tally (built by the facades from
``TallyConfig.sentinel``, exactly like the stats accumulator and the
autosave runner). It owns the carried device scalars — the running
flux sum the conservation delta diffs against and the running worst
residual — and the cumulative ``HealthReport``. The per-move protocol:

    n_unf, mask = runner.audit(x0, x1, fly, w, done, flux)
    ... facade runs the straggler ladder if n_unf ...
    runner.note_outcome(mask, n_unf, recovered, lost, move)

``audit`` performs the move's ONE scalar fetch (the packed audit
word); every other scalar stays on device and is fetched lazily by
``health_report``. ``note_outcome`` applies the policy's anomaly
disposition AFTER the ladder ran, so a fully recovered straggler move
does not warn about a condition the sentinel just cured (it still
counts in ``unfinished_total`` — recovery is not silence).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp

from pumiumtally_tpu.sentinel.audit import audit_pack, split_packed
from pumiumtally_tpu.sentinel.policy import (
    ANOMALY_UNFINISHED,
    HealthReport,
    SentinelAnomalyError,
    SentinelPolicy,
    describe_mask,
)


class SentinelRunner:
    def __init__(self, policy: SentinelPolicy, dtype):
        from pumiumtally_tpu.sentinel.audit import wide_dtype

        self.policy = policy
        self.report = HealthReport()
        wd = wide_dtype()
        self._rtol = jnp.asarray(policy.resolved_rtol(dtype), wd)
        self._flux_sum_prev = jnp.asarray(0.0, wd)
        self._max_resid_dev = jnp.asarray(0.0, wd)

    # -- audit -----------------------------------------------------------
    def resync(self, flux) -> None:
        """Re-baseline the conservation delta (checkpoint restore, or
        any path that rewrites flux outside a move)."""
        from pumiumtally_tpu.sentinel.audit import wide_dtype

        self._flux_sum_prev = jnp.sum(
            jnp.asarray(flux).astype(wide_dtype())
        )

    def audit(self, x0, x1, fly, w, done, flux) -> Tuple[int, int]:
        """Run the one-program audit over a move's caller-order view;
        returns the host ``(n_unfinished, anomaly_mask)`` pair (the
        move's single scalar fetch)."""
        packed, self._flux_sum_prev, self._max_resid_dev, _resid = (
            audit_pack(
                x0, x1, fly, w, done, flux,
                self._flux_sum_prev, self._max_resid_dev, self._rtol,
            )
        )
        return split_packed(int(packed))

    # -- outcome dispatch -------------------------------------------------
    def note_outcome(self, mask: int, n_unf: int, recovered: int,
                     lost: int, move: int) -> None:
        """Fold one audited move into the report and apply the
        ``on_anomaly`` disposition. ``recovered``/``lost`` are the
        ladder's split of ``n_unf`` (0/0 when the ladder is disarmed
        or nothing straggled)."""
        self.report.moves_audited += 1
        self.report.unfinished_total += int(n_unf)
        self.report.stragglers_recovered += int(recovered)
        self.report.stragglers_lost += int(lost)
        effective = mask
        if (mask & ANOMALY_UNFINISHED) and n_unf and lost == 0 and (
            recovered == n_unf
        ):
            # The ladder recovered every straggler: the unfinished
            # condition no longer holds on the committed state.
            effective = mask & ~ANOMALY_UNFINISHED
        if effective == 0:
            return
        self.report.anomaly_moves += 1
        self.report.anomaly_mask_union |= effective
        msg = (
            f"[SENTINEL] move {move}: anomaly "
            f"{describe_mask(effective)} (mask {effective}); "
            f"{n_unf} unfinished, {recovered} recovered, {lost} lost"
        )
        if self.policy.on_anomaly == "raise":
            raise SentinelAnomalyError(msg)
        if self.policy.on_anomaly == "warn":
            print(msg)

    def note_localization(self, recovered: int, lost: int) -> None:
        """Localization-walk stragglers (the non-tallying ladder):
        straggler accounting only — localization is not an audited
        move, so no anomaly-mask bookkeeping happens here."""
        self.report.unfinished_total += int(recovered) + int(lost)
        self.report.stragglers_recovered += int(recovered)
        self.report.stragglers_lost += int(lost)

    def note_overflow_recovery(self, escalated: bool) -> None:
        """A partitioned capacity overflow the recovery ladder
        absorbed (``escalated`` = it needed the host-side capacity
        rebuild, not just the full-migrate retry)."""
        self.report.overflow_recoveries += 1
        if escalated:
            self.report.capacity_escalations += 1

    # -- report -----------------------------------------------------------
    def health_report(self) -> HealthReport:
        """The cumulative report with the lazily carried device maximum
        folded in (this is the fetch point for the residual)."""
        return dataclasses.replace(
            self.report,
            max_conservation_residual=float(self._max_resid_dev),
        )


def build_runner(policy: Optional[SentinelPolicy], dtype):
    """Facade hook: a runner when a policy is armed, else None (the
    sentinel-off path constructs NOTHING — same contract as stats-off)."""
    return None if policy is None else SentinelRunner(policy, dtype)
