"""On-device audit lanes: one jitted program, one packed scalar.

Per audited move the facade hands this module the caller-order view of
the move — phase-B start positions, committed end positions, flying
flags, weights, the per-particle done mask, and the current flux — and
gets back ONE packed int32 scalar plus two carried device scalars (the
running flux sum and the running worst residual). Everything reduces
inside a single jitted program (entry point ``audit_pack``,
config.RETRACE_BUDGETS), so the audit costs one dispatch and, when the
facade fetches the packed scalar, one scalar D2H per move — under the
default fenced timing that fetch piggybacks on the fence the facade
already pays.

The conservation lane is the bench-only ``check_conservation`` gate
moved on-device: a track-length tally over segments that stay inside
the mesh must satisfy ``Σ flux == Σ fly·w·|x_end − x_start|`` exactly
up to accumulation rounding — boundary-clamped AND iteration-truncated
particles both commit exactly the position their partial track was
tallied to (the walk's s-telescoping), so the identity holds for them
too and the two anomaly signals stay independent.

Packing: ``packed = n_unfinished · 8 + anomaly_mask`` (mask in the low
``_ANOMALY_BITS`` bits); ``split_packed`` undoes it on the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from pumiumtally_tpu.sentinel.policy import (
    _ANOMALY_BITS,
    ANOMALY_CONSERVATION,
    ANOMALY_NONFINITE,
    ANOMALY_UNFINISHED,
)
from pumiumtally_tpu.utils.profiling import register_entry_point


def wide_dtype():
    """The audit's accumulation dtype: f64 under x64 (parity suites),
    else f32 — requesting f64 on an x64-less runtime only produces a
    truncation warning per op."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


@jax.jit
def _audit_pack(x0, x1, fly, w, done, flux, prev_sum, prev_max, rtol):
    """One-program audit reduction.

    Returns ``(packed, flux_sum, new_max, residual)`` — all device
    scalars; the caller fetches ``packed`` (the one scalar) and
    carries the rest lazily. ``rtol`` is a traced scalar so the jit
    key never varies with the threshold.
    """
    wd = wide_dtype()
    flying = fly.astype(bool)
    traveled = jnp.linalg.norm(x1.astype(wd) - x0.astype(wd), axis=1)
    expected = jnp.sum(jnp.where(flying, w.astype(wd) * traveled, 0.0))
    flux_sum = jnp.sum(flux.astype(wd))
    delta = flux_sum - prev_sum
    tiny = jnp.asarray(jnp.finfo(wd).tiny, wd)
    residual = jnp.abs(delta - expected) / jnp.maximum(expected, tiny)
    n_unf = jnp.sum(flying & ~done).astype(jnp.int32)
    mask = (
        jnp.where(n_unf > 0, ANOMALY_UNFINISHED, 0)
        | jnp.where(residual > rtol, ANOMALY_CONSERVATION, 0)
        | jnp.where(~jnp.isfinite(delta), ANOMALY_NONFINITE, 0)
    ).astype(jnp.int32)
    packed = n_unf * (1 << _ANOMALY_BITS) + mask
    return packed, flux_sum, jnp.maximum(prev_max, residual), residual


# The counting wrapper (retrace tripwire): audit_pack has ONE cache
# key per particle shape — the threshold and every carried scalar are
# traced, so repeated moves hit the cache.
audit_pack = register_entry_point("audit_pack", _audit_pack)


def split_packed(packed: int):
    """(n_unfinished, anomaly_mask) from the fetched packed scalar."""
    p = int(packed)
    return p >> _ANOMALY_BITS, p & ((1 << _ANOMALY_BITS) - 1)
