"""Quarantine records for particles nothing could recover.

When the straggler-escalation ladder exhausts its rungs (sentinel
module docstring) the particle is declared lost: folded into the
facade's ``lost_particles`` counter AND — when the policy names a
``quarantine_dir`` — appended to ``quarantine.jsonl`` there, one JSON
object per particle, so a postmortem can re-inject or bill exactly the
histories the campaign dropped:

    {"pid": 7, "move": 12, "origin": [...], "dest": [...],
     "elem": 4311, "weight": 1.0, "reason": "iteration_budget"}

Writes go through ``utils.checkpoint.atomic_append`` (the shared
temp+fsync+replace durability sequence, append-safe variant), so a
crash mid-append never tears a record. ``read_quarantine`` skips a
truncated final line — logs written by older code or foreign appenders
may still carry one.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from pumiumtally_tpu.utils.checkpoint import atomic_append

QUARANTINE_FILENAME = "quarantine.jsonl"


def quarantine_path(directory: str) -> str:
    return os.path.join(directory, QUARANTINE_FILENAME)


def build_records(
    idx,
    origins,
    dests,
    elems,
    weights,
    move: int,
    *,
    pid_offset: int = 0,
    reason: str = "iteration_budget",
) -> List[dict]:
    """THE quarantine record schema, in one place (every facade's
    quarantine path builds through here — four independent copies of
    this loop drifted once already during review). ``idx`` are the
    residue's caller-order indices; ``origins``/``dests`` [k,3] and
    ``elems``/``weights`` [k] are aligned with it; ``pid_offset``
    shifts chunk-local indices into global pid numbering."""
    return [
        {
            "pid": int(pid_offset + idx[i]),
            "move": int(move),
            "origin": [float(v) for v in origins[i]],
            "dest": [float(v) for v in dests[i]],
            "elem": int(elems[i]),
            "weight": float(weights[i]),
            "reason": reason,
        }
        for i in range(len(idx))
    ]


def append_quarantine(directory: Optional[str], records: List[dict]) -> None:
    """Append one JSONL line per record, atomically; no-op with no
    directory (report-only quarantine accounting) or no records."""
    if directory is None or not records:
        return
    os.makedirs(directory, exist_ok=True)
    payload = "".join(
        json.dumps(r, sort_keys=True) + "\n" for r in records
    ).encode()
    atomic_append(quarantine_path(directory), payload)


def read_quarantine(path: str) -> List[dict]:
    """Parse a quarantine JSONL file; a torn final line (no newline, or
    unparseable JSON) is skipped rather than raising — everything
    before it is intact by the atomic-append contract. A torn line
    ANYWHERE else is real corruption and raises."""
    records: List[dict] = []
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    # A well-formed file ends with a newline -> last split element is
    # empty; anything else is a torn tail, tolerated (skipped).
    body, tail = lines[:-1], lines[-1]
    for i, line in enumerate(body):
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(body) - 1 and not tail:
                # Torn final line that still got its newline in before
                # the crash cut the payload short.
                break
            raise ValueError(
                f"corrupt quarantine file {path!r}: unparseable record "
                f"at line {i + 1}"
            )
    if tail:
        try:
            records.append(json.loads(tail))
        except json.JSONDecodeError:
            pass  # torn tail: the atomic-append crash window
    return records
