"""Mesh tool CLI — the framework's ``msh2osh`` / ``describe`` / ``scale``.

The reference's mesh pipeline uses Omega_h's command-line tools
(reference README.md:115-125):

    msh2osh input.msh output.osh      # convert Gmsh -> .osh
    describe output.osh               # print coordinate min/max
    scale output.osh scaled.osh 10    # scale coordinates

Here the same three verbs live behind ``python -m pumiumtally_tpu.cli``
(or the ``pumiumtally`` console script), operating on Gmsh ``.msh`` and
this package's ``.osh`` directories (io/osh.py).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def _load(path: str):
    p = path.rstrip("/")
    if p.endswith(".msh"):
        from pumiumtally_tpu.io.gmsh import read_gmsh

        return read_gmsh(p)
    if p.endswith(".osh"):
        from pumiumtally_tpu.io.osh import read_osh

        return read_osh(p)
    raise SystemExit(f"unsupported mesh format: {path!r} (.msh or .osh)")


def _save(path: str, coords, tets, elem_tags=None) -> None:
    """Write by output extension: ``.msh`` → Gmsh 2.2 ASCII, anything
    else → ``.osh`` directory. (Generators previously always wrote
    ``.osh``, silently producing an .osh DIRECTORY at a ``.msh`` path.)"""
    p = path.rstrip("/")
    if p.endswith(".msh"):
        from pumiumtally_tpu.io.gmsh import write_gmsh

        elem_tags = elem_tags or {}
        write_gmsh(p, coords, tets, physical=elem_tags.get("class_id"))
        dropped = sorted(set(elem_tags) - {"class_id"})
        if dropped:
            print(f"note: tags {dropped} not representable in MSH 2.2; "
                  "use an .osh output to keep them")
        return
    from pumiumtally_tpu.io.osh import write_osh

    write_osh(path, coords, tets, elem_tags=elem_tags)


def cmd_msh2osh(args) -> None:
    from pumiumtally_tpu.io.osh import write_osh

    coords, tets = _load(args.input)
    write_osh(args.output, coords, tets)
    print(f"wrote {args.output}: {coords.shape[0]} vertices, "
          f"{tets.shape[0]} tets")


def cmd_describe(args) -> None:
    mesh_path = args.mesh.rstrip("/")
    tags = {}
    if mesh_path.endswith(".osh"):
        from pumiumtally_tpu.io.osh import read_osh

        # One parse serves both the geometry lines and the tag listing
        # (legacy directories return an empty tag dict, no error).
        coords, tets, tags = read_osh(mesh_path, with_tags=True)
    else:
        coords, tets = _load(mesh_path)
    lo, hi = coords.min(axis=0), coords.max(axis=0)
    print(f"vertices : {coords.shape[0]}")
    print(f"tets     : {tets.shape[0]}")
    print(f"x range  : [{lo[0]:.6g}, {hi[0]:.6g}]")
    print(f"y range  : [{lo[1]:.6g}, {hi[1]:.6g}]")
    print(f"z range  : [{lo[2]:.6g}, {hi[2]:.6g}]")
    for name, v in tags.items():
        v = np.asarray(v)
        kinds = np.unique(v).size if v.dtype.kind in "iu" else None
        extra = f", {kinds} distinct" if kinds is not None else ""
        print(f"tag      : {name} [{v.dtype}{extra}]")


def cmd_scale(args) -> None:
    coords, tets = _load(args.input)
    _save(args.output, coords * args.factor, tets)
    print(f"wrote {args.output}: scaled by {args.factor}")


def cmd_box(args) -> None:
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(args.lx, args.ly, args.lz,
                              args.nx, args.ny, args.nz)
    _save(args.output, coords, tets)
    print(f"wrote {args.output}: {coords.shape[0]} vertices, "
          f"{len(tets)} tets")


def cmd_pincell(args) -> None:
    """Generate the pincell benchmark geometry (BASELINE configs[0-1])
    as an .osh directory — the reference obtains this via Gmsh +
    msh2osh (reference README.md:115-125)."""
    from pumiumtally_tpu.mesh.pincell import pincell_arrays

    coords, tets, region = pincell_arrays(
        pitch=args.pitch, fuel_radius=args.fuel_radius, height=args.height,
        n_theta=args.n_theta, n_rings_fuel=args.rings_fuel,
        n_rings_pad=args.rings_pad, nz=args.nz,
    )
    # Material classification rides along as the class_id element tag
    # (the tag name Omega_h meshes carry for geometric classification).
    _save(args.output, coords, tets,
          elem_tags={"class_id": region.astype(np.int32)})
    nf = int((region == 0).sum())
    print(f"wrote {args.output}: {coords.shape[0]} vertices, "
          f"{len(tets)} tets ({nf} fuel / {len(tets) - nf} moderator)")


def cmd_lattice(args) -> None:
    """Generate an nx×ny pincell assembly (BASELINE configs[1-2] scale
    class) as an .osh directory with class_id (material) and cell_id
    element tags."""
    from pumiumtally_tpu.mesh.pincell import lattice_arrays

    coords, tets, region, cell_id = lattice_arrays(
        args.nx, args.ny,
        pitch=args.pitch, fuel_radius=args.fuel_radius, height=args.height,
        n_theta=args.n_theta, n_rings_fuel=args.rings_fuel,
        n_rings_pad=args.rings_pad, nz=args.nz,
    )
    _save(args.output, coords, tets,
          elem_tags={"class_id": region.astype(np.int32),
                     "cell_id": cell_id.astype(np.int32)})
    print(f"wrote {args.output}: {coords.shape[0]} vertices, "
          f"{len(tets)} tets, {args.nx}x{args.ny} cells")


def cmd_autotune(args) -> None:
    """Measure the walk-kernel tuning knobs on the CURRENT backend for
    the given mesh and print the winning TallyConfig settings (see
    utils/autotune.py — the deployment-measures-instead-of-guesses
    counterpart of the reference's hard-coded Kokkos launch params)."""
    from pumiumtally_tpu.mesh.tetmesh import TetMesh
    from pumiumtally_tpu.utils.autotune import autotune_walk

    coords, tets = _load(args.mesh)
    mesh = TetMesh.from_arrays(coords, tets)
    cfg, report = autotune_walk(
        mesh, n_particles=args.particles, moves=args.moves, verbose=True,
    )
    kw = cfg.walk_kwargs()  # normalized: () when the winner == defaults
    settings = (
        ", ".join(f"walk_{k}={v!r}" for k, v in kw)
        if kw else "<defaults — no knob beats them on this backend>"
    )
    # The adopted entry, not report[0]: an approximate-tier candidate
    # (never adopted by default) may top the raw sweep — and an
    # all-approximate sweep adopts nothing, so no rate is paired with
    # the kept defaults.
    adopted = next((r for r in report if r.get("adopted")), None)
    if adopted is None:
        print(f"\nbest: no adoptable candidate (approximate tiers are "
              f"measured but not adopted); keeping TallyConfig({settings})")
    else:
        print(f"\nbest: {adopted['moves_per_sec'] / 1e6:.3f}M moves/s with "
              f"TallyConfig({settings})")


def cmd_serve(args) -> None:
    """Run the multi-session campaign service with the NDJSON socket
    front end (service/server.py): external host codes attach as
    independent sessions over TCP, each with its own facade, flux,
    and checkpoint stream. SIGTERM drains: intake stops, in-flight
    moves finish, every autosave-armed session writes one generation,
    and the process exits 0 (preemption-safe serving)."""
    import json as _json
    import time as _time

    from pumiumtally_tpu.mesh.tetmesh import TetMesh
    from pumiumtally_tpu.service import SocketFrontend, TallyService

    default_mesh = None
    if args.mesh is not None:
        coords, tets = _load(args.mesh)
        default_mesh = TetMesh.from_arrays(coords, tets)
    service = TallyService(handle_signals=True,
                           fuse_sessions=not args.no_fuse,
                           admission_budget=args.admission_budget)
    frontend = SocketFrontend(
        service, host=args.host, port=args.port,
        default_mesh=default_mesh, default_particles=args.particles,
        allow_mesh_paths=args.allow_mesh_paths,
        allow_write=args.allow_write,
    )
    frontend.start()
    # One parseable line so drivers/tests can discover the bound port
    # (--port 0 binds an ephemeral one).
    print(_json.dumps({"serving": {"host": frontend.host,
                                   "port": frontend.port}}), flush=True)
    try:
        while not service.drain_requested:
            _time.sleep(0.1)
        print("serve: drain requested; checkpointing open sessions",
              flush=True)
    finally:
        frontend.stop()
        service.shutdown(drain=True)
    raise SystemExit(0)


def cmd_route(args) -> None:
    """Run the session router over per-host service workers
    (service/server.py SessionRouter, round 13): each worker is a
    ``pumiumtally serve`` process on its own host/devices; the router
    pins every session to a home worker at open (least-loaded, or the
    request's "home" hint) and forwards its NDJSON ops there — the
    horizontal scaling front of the multi-session service. SIGTERM
    (or SIGINT) stops intake, closes the worker links, and exits 0 —
    same preemption-safe contract as ``serve``; the workers' own
    vanished-client handling drain-closes any sessions the router
    still had open."""
    import json as _json
    import signal as _signal
    import time as _time

    from pumiumtally_tpu.service import SessionRouter

    backends = []
    for spec in args.backend:
        host, sep, port = spec.rpartition(":")
        if not sep or not port.isdigit():
            raise SystemExit(
                f"--backend {spec!r} is not host:port"
            )
        backends.append((host, int(port)))
    router = SessionRouter(backends, host=args.host, port=args.port)
    router.start()
    # Same one-parseable-line discovery contract as `serve`.
    print(_json.dumps({"routing": {"host": router.host,
                                   "port": router.port,
                                   "backends": len(backends)}}),
          flush=True)
    stop = {"requested": False}
    prev = _signal.signal(_signal.SIGTERM,
                          lambda _sig, _frm: stop.update(requested=True))
    try:
        while not stop["requested"]:
            _time.sleep(0.1)
        print("route: drain requested; closing worker links", flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        _signal.signal(_signal.SIGTERM, prev)
        router.stop()
    raise SystemExit(0)


def cmd_loadgen(args) -> None:
    """Drive scripted OpenMC-style clients at a running ``serve``
    worker or ``route`` router and print the heavy-traffic report
    (tools/loadgen.py, round 20): served moves/s, p50/p99
    submit→resolve latency, per-lane Jain fairness, refusal counts.
    Pure client side — needs only the repository's tools/ directory
    and numpy, no jax, no device."""
    import importlib.util as _ilu
    import json as _json

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    lg_path = os.path.join(tools, "loadgen.py")
    if not os.path.isfile(lg_path):
        raise SystemExit(
            "loadgen needs the repository's tools/ directory "
            f"(expected {lg_path}); run from a source checkout"
        )
    spec = _ilu.spec_from_file_location("pumiumtally_loadgen", lg_path)
    loadgen = _ilu.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    host, sep, port = args.connect.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(f"--connect {args.connect!r} is not host:port")
    try:
        mix = tuple(float(x) for x in args.priority_mix.split(","))
        if len(mix) != 3:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"--priority-mix {args.priority_mix!r} is not "
            "three comma-separated weights (high,normal,low)"
        ) from None
    report = loadgen.run_load(
        host, int(port), clients=args.clients, rate=args.rate,
        particles=args.particles, batches=args.batches,
        moves=args.moves, facade=args.facade,
        chunk_size=args.chunk_size,
        mesh_box=tuple(args.mesh_box), priority_mix=mix,
        seed=args.seed, timeout=args.timeout,
    )
    if args.json:
        print(_json.dumps(report, default=float))
    else:
        print(loadgen.format_report(report))
    if report["clients_failed"] or report["clients_timed_out"]:
        raise SystemExit(1)


def _subproc_timeout() -> float:
    """Helper-subprocess timeout in seconds (default 1800). Deployments
    with slow toolchains raise it via PUMIUMTALLY_SUBPROC_TIMEOUT; the
    expiry message names the env var so the fix is discoverable from
    the failure itself."""
    raw = os.environ.get("PUMIUMTALLY_SUBPROC_TIMEOUT")
    if raw is None:
        return 1800.0
    try:
        t = float(raw)
        if t <= 0:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"PUMIUMTALLY_SUBPROC_TIMEOUT={raw!r} is not a positive "
            "number of seconds"
        ) from None
    return t


def cmd_aot_check(args) -> None:
    """Certify that the Pallas walk kernel (and optionally the full
    multi-chip programs) compile for a real TPU target WITHOUT a
    device, via the locally installed libtpu (chipless AOT — the
    mechanism that caught three lowering bugs interpret mode cannot
    see; tools/aot_vmem_compile.py holds the lowering-law notes).
    Useful as a cluster pre-flight: a green aot-check means the
    deployment's jax/libtpu pair can build every kernel this package
    ships before any TPU time is booked."""
    import subprocess

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if not os.path.isdir(tools):
        # Installed without the repo checkout: the harnesses live in
        # the source tree, not the wheel.
        raise SystemExit(
            "aot-check needs the repository's tools/ directory "
            "(run from a source checkout)"
        )
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    jobs = [("walk kernel (single chip)",
             [sys.executable, os.path.join(tools, "aot_vmem_compile.py"),
              "2048", "1024", "1024", "4", "1"]),
            # The round-17 one-kernel walk. Its harness carries its own
            # SIGALRM deadlines and reports a structured SKIP (rc 0)
            # where the topology client would hang — shown as green
            # with the skip reason in the tail, never a wedge.
            ("one-kernel pallas walk (single chip)",
             [sys.executable,
              os.path.join(tools, "aot_pallas_walk_compile.py"),
              "--quick"])]
    if args.multichip:
        jobs.append(("multi-chip phase programs",
                     [sys.executable,
                      os.path.join(tools, "aot_multichip_compile.py"),
                      "2048"]))
    rc = 0
    tmo = _subproc_timeout()
    for label, cmd in jobs:
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=tmo, env=env)
            job_rc, text = r.returncode, (r.stdout + r.stderr)
        except subprocess.TimeoutExpired as e:
            # A hung compile is a result too (the harness exists
            # because one hung a remote helper) — report it and move
            # on to the remaining jobs.
            job_rc = 1
            text = "".join(
                s if isinstance(s, str) else s.decode("utf-8", "replace")
                for s in (e.stdout, e.stderr) if s
            ) + (
                f"\n(compile timed out after {tmo:g}s; set "
                "PUMIUMTALLY_SUBPROC_TIMEOUT to extend)"
            )
        lines = text.strip().splitlines()
        # Success: a terse tail. Failure: the whole child output, so
        # the root cause (e.g. a libtpu-missing error above jax's
        # warning chatter) is never truncated away.
        shown = lines[-4:] if job_rc == 0 else lines
        print(f"[{'OK' if job_rc == 0 else 'FAILED'}] {label}\n  "
              + ("\n  ".join(shown) if shown else "(no output)"))
        rc |= 1 if job_rc else 0
    if rc:
        raise SystemExit(1)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="pumiumtally",
        description="mesh tools (Gmsh .msh / pumiumtally .osh)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("msh2osh", help="convert Gmsh .msh to .osh directory")
    c.add_argument("input")
    c.add_argument("output")
    c.set_defaults(fn=cmd_msh2osh)

    c = sub.add_parser("describe", help="print mesh size and coordinate range")
    c.add_argument("mesh")
    c.set_defaults(fn=cmd_describe)

    c = sub.add_parser("scale", help="scale mesh coordinates by a factor")
    c.add_argument("input")
    c.add_argument("output")
    c.add_argument("factor", type=float)
    c.set_defaults(fn=cmd_scale)

    c = sub.add_parser("box", help="generate a structured box tet mesh")
    c.add_argument("output")
    c.add_argument("--lx", type=float, default=1.0)
    c.add_argument("--ly", type=float, default=1.0)
    c.add_argument("--lz", type=float, default=1.0)
    c.add_argument("--nx", type=int, default=10)
    c.add_argument("--ny", type=int, default=10)
    c.add_argument("--nz", type=int, default=10)
    c.set_defaults(fn=cmd_box)

    # Shared pin-geometry options (one definition; pincell and lattice
    # stay in lockstep).
    pin_opts = argparse.ArgumentParser(add_help=False)
    pin_opts.add_argument("--pitch", type=float, default=1.26)
    pin_opts.add_argument("--fuel-radius", type=float, default=0.4095)
    pin_opts.add_argument("--height", type=float, default=1.0)
    pin_opts.add_argument("--n-theta", type=int, default=16)
    pin_opts.add_argument("--rings-fuel", type=int, default=3)
    pin_opts.add_argument("--rings-pad", type=int, default=3)
    pin_opts.add_argument("--nz", type=int, default=4)

    c = sub.add_parser(
        "pincell", help="generate the pincell benchmark mesh (O-grid)",
        parents=[pin_opts],
    )
    c.add_argument("output")
    c.set_defaults(fn=cmd_pincell)

    c = sub.add_parser(
        "lattice", help="generate an nx×ny pincell assembly mesh",
        parents=[pin_opts],
    )
    c.add_argument("output")
    c.add_argument("--nx", type=int, default=17)
    c.add_argument("--ny", type=int, default=17)
    c.set_defaults(fn=cmd_lattice)

    c = sub.add_parser(
        "autotune",
        help="measure walk-kernel knobs on this backend, print the best",
    )
    c.add_argument("mesh")
    c.add_argument("--particles", type=int, default=200_000)
    c.add_argument("--moves", type=int, default=3)
    c.set_defaults(fn=cmd_autotune)

    c = sub.add_parser(
        "serve",
        help="run the multi-session campaign service (NDJSON over TCP)",
    )
    c.add_argument("--mesh", default=None,
                   help="default mesh (.msh/.osh) for open requests "
                        "that pass none")
    c.add_argument("--particles", type=int, default=100_000,
                   help="default num_particles for open requests")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (the bound port is printed as "
                        "one JSON line)")
    c.add_argument("--allow-mesh-paths", action="store_true",
                   help="let open requests load meshes by filesystem "
                        "path")
    c.add_argument("--allow-write", action="store_true",
                   help="let sessions write VTK output files")
    c.add_argument("--no-fuse", action="store_true",
                   help="disable cross-session batch fusion (serve "
                        "every session's ops one launch at a time — "
                        "the pre-round-12 dispatch path)")
    c.add_argument("--admission-budget", type=int, default=None,
                   metavar="COST",
                   help="global cap on queued + in-flight transport "
                        "cost units (particles); beyond it, opens and "
                        "submits refuse with a structured overloaded "
                        "error instead of growing the staging heap "
                        "(default: unbounded)")
    c.set_defaults(fn=cmd_serve)

    c = sub.add_parser(
        "route",
        help="route NDJSON sessions over per-host service workers",
    )
    c.add_argument("--backend", action="append", required=True,
                   metavar="HOST:PORT",
                   help="a worker's serve address (repeat per host)")
    c.add_argument("--host", default="127.0.0.1")
    c.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (the bound port is printed as "
                        "one JSON line)")
    c.set_defaults(fn=cmd_route)

    c = sub.add_parser(
        "loadgen",
        help="drive scripted clients at a serve/route address and "
             "report served throughput, latency, fairness, refusals",
    )
    c.add_argument("--connect", required=True, metavar="HOST:PORT",
                   help="a running serve worker or route router")
    c.add_argument("--clients", type=int, default=100)
    c.add_argument("--rate", type=float, default=200.0,
                   help="Poisson arrival rate, clients/second")
    c.add_argument("--particles", type=int, default=64)
    c.add_argument("--batches", type=int, default=1)
    c.add_argument("--moves", type=int, default=2,
                   help="moves per batch")
    c.add_argument("--facade", choices=("mono", "stream"),
                   default="mono")
    c.add_argument("--chunk-size", type=int, default=None,
                   help="streaming chunk size (facade=stream)")
    c.add_argument("--mesh-box", type=float, nargs=6,
                   default=(1.0, 1.0, 1.0, 3.0, 3.0, 3.0),
                   metavar=("LX", "LY", "LZ", "NX", "NY", "NZ"),
                   help="box mesh every client opens against")
    c.add_argument("--priority-mix", default="0.2,0.6,0.2",
                   metavar="H,N,L",
                   help="lane probabilities high,normal,low")
    c.add_argument("--seed", type=int, default=0,
                   help="schedule seed (arrivals, priorities, "
                        "positions — the work is deterministic)")
    c.add_argument("--timeout", type=float, default=600.0,
                   help="per-client join bound, seconds")
    c.add_argument("--json", action="store_true",
                   help="print the full report as one JSON line")
    c.set_defaults(fn=cmd_loadgen)

    c = sub.add_parser(
        "aot-check",
        help="compile the TPU kernels chipless (local libtpu, no device)",
    )
    c.add_argument("--multichip", action="store_true",
                   help="also compile the 4-chip phase programs")
    c.set_defaults(fn=cmd_aot_check)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main(sys.argv[1:])
