"""jaxlint determinism pass: rules JL501-JL503 (pure stdlib).

The engine's headline contract is bitwise determinism (PAPER.md §0:
atomically-accumulated track-length tallies reproduced exactly, pinned
by every parity test in the suite). The device side earns it with
sorted segmented commits and stable sorts; this pass guards the HOST
seams where Python can silently re-randomize an order the device side
worked to fix:

* JL501 — unordered-set iteration (or ``list(...)``/``tuple(...)``
  materialization of a set) feeding an order-sensitive sink: a device
  op, a wire reply (``json.dumps``/socket send), or accumulating
  ``append``/``extend`` state such as checkpoint key order. Python
  ``set`` iteration order varies with hash seeding and insertion
  history — route through ``sorted(...)`` instead. Dict iteration is
  insertion-ordered and is NOT flagged.
* JL502 — a non-stable ``argsort`` in a function that also performs a
  segmented commit (``.at[...].add``/``.at[...].set`` or a
  ``segment_sum``): the fused-scatter stability proof assumes ties
  keep their lane order, which ``np.argsort``'s default quicksort does
  not guarantee. ``jnp.argsort`` is stable by default and only flagged
  when explicitly made unstable (``stable=False`` or an unstable
  ``kind=``).
* JL503 — host-side float re-accumulation: builtin ``sum()`` over
  device fetches (``jax.device_get(...)`` / ``.tolist()``). Left-fold
  float addition on host re-orders the rounding the device commit
  pinned; parity-gated A/B tools must compare device-reduced scalars.

Same no-false-positive bias as every other pass: JL501 needs BOTH an
unambiguously unordered iterable and a recognized sink in the loop
body; JL502 needs the commit and the sort in the same function;
``sorted(set(...))`` is the endorsed spelling and never flagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from pumiumtally_tpu.analysis.core import Diagnostic, _ModuleIndex

#: Method names whose call inside an unordered-iteration body means
#: the iteration order escapes into durable/ordered state.
_SINK_METHODS = (
    "append",
    "extend",
    "sendall",
    "send",
    "write",
    "writelines",
)

#: Dotted-call prefixes that put iteration order onto the device.
_DEVICE_PREFIXES = ("jax.", "jnp.", "jax_graft.")

#: numpy argsort kinds that guarantee stability.
_STABLE_KINDS = ("stable", "mergesort")


def _is_unordered(node: ast.AST, index: _ModuleIndex) -> bool:
    """True when ``node`` evaluates to a Python set (iteration order
    depends on hashing, not program history)."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        d = index.dotted(node.func)
        if d in ("set", "frozenset"):
            return True
        # set algebra on an already-unordered operand: set(a) | b etc.
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    ):
        return _is_unordered(node.left, index) or _is_unordered(
            node.right, index
        )
    return False


def _is_device_call(call: ast.Call, index: _ModuleIndex) -> bool:
    d = index.dotted(call.func)
    if not d:
        return False
    return any(d.startswith(p) for p in _DEVICE_PREFIXES)


def _body_sink(body: List[ast.stmt], index: _ModuleIndex
               ) -> Optional[str]:
    """The first order-sensitive sink inside a loop body, described,
    or None."""
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if _is_device_call(node, index):
                return index.dotted(node.func) or "a device op"
            d = index.dotted(node.func)
            if d and (d == "json.dumps" or d.endswith(".dumps")):
                return d
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SINK_METHODS
            ):
                return f".{node.func.attr}(...)"
    return None


def _sort_knobs(call: ast.Call):
    """(kind, stable) literal keyword values of a sort call, None for
    each when absent or non-literal."""
    kind = None
    stable = None
    for kw in call.keywords:
        if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
            kind = kw.value.value
        if kw.arg == "stable" and isinstance(kw.value, ast.Constant):
            stable = kw.value.value
    return kind, stable


def _argsort_finding(call: ast.Call, index: _ModuleIndex
                     ) -> Optional[str]:
    d = index.dotted(call.func)
    if not d or not d.endswith("argsort"):
        return None
    kind, stable = _sort_knobs(call)
    is_jax = d.startswith("jax.") or d.startswith("jnp.")
    if is_jax:
        if stable is False:
            return f"{d}(..., stable=False)"
        if kind is not None and kind not in _STABLE_KINDS:
            return f"{d}(..., kind={kind!r})"
        return None
    if d.startswith("numpy.") or d.startswith("np."):
        if stable is True or kind in _STABLE_KINDS:
            return None
        return f"{d} (numpy default quicksort)"
    return None


def _is_commit(node: ast.Call, index: _ModuleIndex) -> bool:
    """``x.at[...].add(...)`` / ``x.at[...].set(...)`` or a
    segment_sum — the segmented-commit shapes the stability proof
    (docs/DESIGN notes, PR 9's fused scatter) covers."""
    d = index.dotted(node.func)
    if d and "segment_sum" in d:
        return True
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ("add", "set", "max", "min")
        and isinstance(f.value, ast.Subscript)
        and isinstance(f.value.value, ast.Attribute)
        and f.value.value.attr == "at"
    )


def _fetch_inside(node: ast.AST, index: _ModuleIndex) -> Optional[str]:
    """A device-fetch expression inside ``node`` (``jax.device_get``
    or ``.tolist()``), described, or None."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        d = index.dotted(n.func)
        if d and d.endswith("device_get"):
            return d
        if isinstance(n.func, ast.Attribute) and n.func.attr == "tolist":
            return ".tolist()"
    return None


def check(tree: ast.Module, index: _ModuleIndex, path: str
          ) -> list[Diagnostic]:
    diags: list[Diagnostic] = []

    # JL501: unordered iteration with an order-sensitive sink, and
    # unordered materialization via list()/tuple().
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if _is_unordered(node.iter, index):
                sink = _body_sink(list(node.body), index)
                if sink is not None:
                    diags.append(Diagnostic(
                        path, node.lineno, "JL501",
                        "iteration over an unordered set feeds an "
                        f"order-sensitive sink ({sink}): set order "
                        "varies run-to-run — iterate "
                        "`sorted(...)` to keep the bitwise contract",
                    ))
        elif isinstance(node, ast.Call):
            d = index.dotted(node.func)
            if (
                d in ("list", "tuple")
                and node.args
                and _is_unordered(node.args[0], index)
            ):
                diags.append(Diagnostic(
                    path, node.lineno, "JL501",
                    f"{d}(...) materializes a set in hash order: the "
                    "result's element order varies run-to-run — use "
                    "`sorted(...)` instead",
                ))

    # A bare set-driven comprehension used for membership stays
    # legal; ordered escapes are covered by the For and
    # list()/tuple() shapes above.

    # JL502: non-stable argsort in a function that also commits.
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        commits = False
        sorts: List[tuple] = []
        for inner in ast.walk(node):
            if not isinstance(inner, ast.Call):
                continue
            if _is_commit(inner, index):
                commits = True
            reason = _argsort_finding(inner, index)
            if reason is not None:
                sorts.append((inner.lineno, reason))
        if commits:
            for line, reason in sorts:
                diags.append(Diagnostic(
                    path, line, "JL502",
                    f"non-stable sort `{reason}` in a function that "
                    "performs a segmented commit: ties may swap lane "
                    "order between runs and break the fused-scatter "
                    "stability proof — use kind='stable' (numpy) or "
                    "leave jnp.argsort at its stable default",
                ))

    # JL503: builtin sum() over device fetches.
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Name) and node.func.id == "sum"
        ):
            continue
        if index.resolve_function("sum") is not None:
            continue  # locally shadowed — not the builtin
        for arg in node.args:
            fetch = _fetch_inside(arg, index)
            if fetch is not None:
                diags.append(Diagnostic(
                    path, node.lineno, "JL503",
                    f"host-side float re-accumulation: builtin sum() "
                    f"over a device fetch ({fetch}) left-folds with "
                    "host rounding order — reduce on device (e.g. "
                    "jnp.sum) and fetch the scalar, or compare the "
                    "device-reduced value directly",
                ))
                break
    return diags
