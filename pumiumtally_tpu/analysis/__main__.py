"""CLI: ``python -m pumiumtally_tpu.analysis [paths...]``.

Exit status: 0 clean, 1 diagnostics found, 2 usage error — the same
contract as ruff, so CI can run them side by side.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from pumiumtally_tpu.analysis.core import lint_paths
from pumiumtally_tpu.analysis.rules import RULES


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pumiumtally_tpu.analysis",
        description="jaxlint: JAX-aware static analyzer (trace safety "
        "JL00x, collective safety JL1xx, Pallas kernels JL2xx, host "
        "concurrency JL3xx, trace-key cardinality JL4xx, determinism "
        "JL5xx; docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument(
        "paths", nargs="*", default=["pumiumtally_tpu"],
        help="files or directories to lint (default: pumiumtally_tpu)",
    )
    ap.add_argument(
        "--explain", metavar="RULE",
        help="print the full doc for one rule id and exit",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="list rule ids and summaries and exit",
    )
    ap.add_argument(
        "--contracts", action="store_true",
        help="audit the five tally facades against the shared hook "
        "surface instead of linting (exit 1 on a missing hook)",
    )
    ap.add_argument(
        "--trace-keys", action="store_true", dest="trace_keys",
        help="audit RETRACE_BUDGETS against every registered jit "
        "entry point instead of linting (exit 1 on a dead budget or "
        "an unbudgeted entry point) and print the static-key "
        "calibration table",
    )
    ap.add_argument(
        "--wire", action="store_true",
        help="audit every NDJSON wire encoder against the "
        "AST-extracted SocketFrontend op/reply schema instead of "
        "linting (exit 1 on an unknown op, missing field, or reply "
        "drift)",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json: stable machine-readable schema)",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  {rule.summary}")
        return 0
    if args.explain:
        rule = RULES.get(args.explain.upper())
        if rule is None:
            print(f"unknown rule {args.explain!r}; known: "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2
        print(f"{rule.id}: {rule.summary}\n\n{rule.doc}")
        return 0
    if args.contracts:
        # Lazy import: the auditor is independent of the lint pipeline.
        from pumiumtally_tpu.analysis.contracts import (
            audit_contracts,
            render_json,
            render_text,
        )

        report, code = audit_contracts()
        render = render_json if args.format == "json" else render_text
        print(render(report))
        return code
    if args.trace_keys:
        from pumiumtally_tpu.analysis.tracekeys import (
            audit_trace_keys,
            render_json,
            render_text,
        )

        report, code = audit_trace_keys()
        render = render_json if args.format == "json" else render_text
        print(render(report))
        return code
    if args.wire:
        from pumiumtally_tpu.analysis.wire import (
            audit_wire,
            render_json,
            render_text,
        )

        report, code = audit_wire()
        render = render_json if args.format == "json" else render_text
        print(render(report))
        return code

    # A typo'd path must not read as "clean" (ruff's contract too):
    # every argument has to resolve to something lintable.
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"jaxlint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    diags = lint_paths(args.paths)
    if args.format == "json":
        # Stable schema, pinned in tests/test_jaxlint.py: a JSON array
        # of {path, line, rule, message} objects, sorted like the text
        # output.  Always an array, even when clean.
        print(json.dumps(
            [
                {"path": d.path, "line": d.line, "rule": d.rule,
                 "message": d.message}
                for d in diags
            ],
            indent=2,
        ))
    else:
        for d in diags:
            print(d.render())
    if diags:
        print(f"jaxlint: {len(diags)} issue(s) found", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
