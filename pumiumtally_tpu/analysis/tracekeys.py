"""jaxlint trace-cardinality prover: rules JL401-JL404 (pure stdlib).

Every jitted hot-loop program in the engine is registered through
``profiling.register_entry_point(name, jitted_fn)`` and bounded by a
compile budget in ``config.RETRACE_BUDGETS`` — the runtime tripwire
(tests/conftest.py) fails any test whose entry point compiles past its
budget. This pass is the STATIC half of that contract, in two parts:

* The per-module lint rules, run by ``Analyzer.run()`` like every
  other pass: JL401 flags a registration whose statically-possible
  trace-key cardinality (the product of the literal value domains
  reaching its static-argument positions across all call sites)
  provably exceeds the declared budget; JL404 flags a per-call-varying
  value — ``len(x)`` or ``x.shape[...]``/``x.size`` of a runtime
  argument — reaching a static key position, the unbounded retrace
  bait JL004's single-function view cannot see.
* The repo-wide audit (``python -m pumiumtally_tpu.analysis
  --trace-keys``, ``audit_trace_keys()``): cross-checks the
  ``RETRACE_BUDGETS`` table against every ``register_entry_point``
  site in the package — a budget with no matching entry point is dead
  (JL402), an entry point with no budget is untripwired (JL403) — and
  prints the per-entry static-key inventory that serves as the live
  calibration table (the way ``--contracts`` is for the facade hook
  surface).

Like the rest of jaxlint, everything here is best-effort STATIC
reasoning with a hard no-false-positive bias: JL401 only fires when
every value reaching every static position of an entry point is
statically enumerable (a literal, or a loop variable ranging over a
literal module-level tuple); one runtime-valued knob makes the
cardinality unknowable and the check skips, never guesses. Budgets are
read by PARSING ``config.py`` (never importing it — the package
``__init__`` imports jax).
"""

from __future__ import annotations

import ast
import json
import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from pumiumtally_tpu.analysis.core import (
    Diagnostic,
    JitSpec,
    _ModuleIndex,
)

#: Budget keys that are guard configuration, not entry-point names
#: (``retrace_guard`` treats "total" as the whole-block compile bound).
EXEMPT_BUDGET_KEYS = ("total",)


def package_root() -> str:
    """The ``pumiumtally_tpu`` package dir, valid from any cwd."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def read_budgets(root: Optional[str] = None) -> Dict[str, int]:
    """``RETRACE_BUDGETS`` parsed out of ``config.py`` as a literal —
    the module itself is never imported (its package imports jax).
    Returns {} when the table cannot be read or is not a literal dict
    (callers then skip budget-dependent checks rather than guess)."""
    root = root or package_root()
    path = os.path.join(root, "config.py")
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return {}
    for node in tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "RETRACE_BUDGETS" not in names:
            continue
        try:
            raw = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            return {}
        if not isinstance(raw, dict):
            return {}
        out: Dict[str, int] = {}
        for k, v in raw.items():
            if isinstance(k, str) and isinstance(v, int):
                out[k] = v
        return out
    return {}


_CACHED_BUDGETS: Optional[Dict[str, int]] = None


def _budgets_cached() -> Dict[str, int]:
    global _CACHED_BUDGETS
    if _CACHED_BUDGETS is None:
        _CACHED_BUDGETS = read_budgets()
    return _CACHED_BUDGETS


# ---------------------------------------------------------------------------
# Registration discovery (shared by the lint pass and the audit)


@dataclass
class _Registration:
    """One ``register_entry_point(name, fn)`` site."""

    name: Optional[str]  # None = non-literal name
    line: int
    call: ast.Call
    target: Optional[str] = None  # assigned local/module name
    spec: Optional[JitSpec] = None
    fn_def: Optional[ast.AST] = None  # wrapped FunctionDef when known
    dynamic_name_expr: Optional[str] = None


def _is_reg_call(index: _ModuleIndex, call: ast.Call) -> bool:
    d = index.dotted(call.func)
    return bool(d) and (
        d == "register_entry_point"
        or d.endswith(".register_entry_point")
    )


def _resolve_spec(
    index: _ModuleIndex, call: ast.Call
) -> Tuple[Optional[JitSpec], Optional[ast.AST]]:
    """(JitSpec, wrapped FunctionDef) of a registration's callable:
    either an inline jit wrapping (``register_entry_point("walk",
    jax.jit(f, ...))`` and the partial form) or a previously-jitted
    named function."""
    found = index._find_jit_wrapping(call)
    fn_expr: Optional[ast.AST] = None
    spec: Optional[JitSpec] = None
    if found is not None:
        spec, fn_expr = found
        if isinstance(fn_expr, ast.Call):
            td = index.dotted(fn_expr.func)
            if td in ("functools.partial", "partial") and fn_expr.args:
                fn_expr = fn_expr.args[0]
    elif len(call.args) > 1:
        fn_expr = call.args[1]
    fn_def = None
    if isinstance(fn_expr, ast.Name):
        fn_def = index.resolve_function(fn_expr.id)
        if spec is None and fn_def is not None:
            spec = index.jit_specs.get(id(fn_def))
    return spec, fn_def


def _registrations(
    tree: ast.Module, index: _ModuleIndex
) -> List[_Registration]:
    regs: List[_Registration] = []
    seen: Set[int] = set()
    for node in ast.walk(tree):
        target = None
        call: Optional[ast.Call] = None
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            call = node.value
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            target = names[0] if names else None
        elif isinstance(node, ast.Call):
            call = node
        if call is None or id(call) in seen:
            continue
        if not _is_reg_call(index, call) or not call.args:
            continue
        seen.add(id(call))
        name_node = call.args[0]
        name = (
            name_node.value
            if isinstance(name_node, ast.Constant)
            and isinstance(name_node.value, str)
            else None
        )
        spec, fn_def = _resolve_spec(index, call)
        regs.append(_Registration(
            name=name,
            line=call.lineno,
            call=call,
            target=target,
            spec=spec,
            fn_def=fn_def,
            dynamic_name_expr=(
                None if name is not None
                else ast.unparse(name_node)
            ),
        ))
    return regs


def _param_names(fn_def: Optional[ast.AST]) -> List[str]:
    if not isinstance(fn_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    a = fn_def.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def _static_params(reg: _Registration) -> Optional[List[str]]:
    """The entry point's static parameter NAMES, or None when they
    cannot be fully resolved (argnums with no visible function def)."""
    if reg.spec is None:
        return None
    names = list(reg.spec.static_argnames)
    if reg.spec.static_argnums:
        params = _param_names(reg.fn_def)
        if not params:
            return None
        for i in reg.spec.static_argnums:
            if i >= len(params):
                return None
            if params[i] not in names:
                names.append(params[i])
    return names


# ---------------------------------------------------------------------------
# Lint pass: JL401 (provable cardinality overflow) + JL404 (per-call
# varying value in a static key position)


def _walk_with_ancestors(root: ast.AST):
    stack: List[Tuple[ast.AST, Tuple[ast.AST, ...]]] = [(root, ())]
    while stack:
        node, anc = stack.pop()
        yield node, anc
        child_anc = anc + (node,)
        stack.extend(
            (c, child_anc) for c in ast.iter_child_nodes(node)
        )


def _enclosing_params(anc: Tuple[ast.AST, ...]) -> Set[str]:
    """Parameter names of the nearest enclosing function def — the
    values that vary per CALL of the surrounding code."""
    for node in reversed(anc):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            params = {
                p.arg
                for p in list(a.posonlyargs) + list(a.args)
                + list(a.kwonlyargs)
            }
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
            params.discard("self")
            params.discard("cls")
            return params
    return set()


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _varying_reason(
    expr: ast.AST, params: Set[str]
) -> Optional[str]:
    """Why ``expr`` is per-call varying, or None. Fires only on the
    unambiguous data-size shapes — ``len(arg)``, ``arg.shape[...]``,
    ``arg.size`` — of a surrounding-function parameter."""
    for n in ast.walk(expr):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id == "len"
            and n.args
        ):
            root = _root_name(n.args[0])
            if root in params:
                return f"len({root})"
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "size"):
            root = _root_name(n.value)
            if root in params:
                return f"{root}.{n.attr}"
    return None


def _literal_elements(
    node: ast.AST, tree: ast.Module
) -> Optional[Set[str]]:
    """repr()s of a literal sequence's elements; follows one level of
    module-level ``KNOBS = (…)`` indirection. None = not literal."""
    if isinstance(node, ast.Name):
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == node.id
                for t in stmt.targets
            ):
                node = stmt.value
                break
        else:
            return None
    if not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return None
    try:
        vals = [ast.literal_eval(e) for e in node.elts]
    except (ValueError, SyntaxError):
        return None
    return {repr(v) for v in vals}


def _value_domain(
    expr: ast.AST, anc: Tuple[ast.AST, ...], tree: ast.Module
) -> Optional[Set[str]]:
    """Statically-possible values of ``expr`` at a call site: a
    literal, or a loop variable ranging over a literal sequence. None
    = not enumerable (the caller must then skip, not guess)."""
    try:
        return {repr(ast.literal_eval(expr))}
    except (ValueError, SyntaxError):
        pass
    if isinstance(expr, ast.Name):
        for node in reversed(anc):
            if (
                isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and node.target.id == expr.id
            ):
                return _literal_elements(node.iter, tree)
    return None


def _static_args_at_call(
    call: ast.Call, reg: _Registration, static_names: List[str]
) -> Optional[List[Tuple[str, ast.AST]]]:
    """(static param name, value expr) pairs at one call site; None
    when the site cannot be mapped (``*args``/``**kwargs``
    forwarding)."""
    out: List[Tuple[str, ast.AST]] = []
    if any(isinstance(a, ast.Starred) for a in call.args):
        return None
    params = _param_names(reg.fn_def)
    for i, a in enumerate(call.args):
        if params and i < len(params) and params[i] in static_names:
            out.append((params[i], a))
    for kw in call.keywords:
        if kw.arg is None:  # **kwargs
            return None
        if kw.arg in static_names:
            out.append((kw.arg, kw.value))
    return out


def check(tree: ast.Module, index: _ModuleIndex, path: str
          ) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    regs = [
        r for r in _registrations(tree, index)
        if r.name is not None and r.target is not None
    ]
    if not regs:
        return diags
    budgets = _budgets_cached()
    nodes = list(_walk_with_ancestors(tree))
    for reg in regs:
        static_names = _static_params(reg)
        if not static_names:
            continue
        sites = [
            (node, anc)
            for node, anc in nodes
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == reg.target
            and node is not reg.call
        ]
        domains: Dict[str, Set[str]] = {}
        enumerable = bool(sites)
        for call, anc in sites:
            pairs = _static_args_at_call(call, reg, static_names)
            if pairs is None:
                enumerable = False
                continue
            params = _enclosing_params(anc)
            for sname, expr in pairs:
                reason = _varying_reason(expr, params)
                if reason is not None:
                    diags.append(Diagnostic(
                        path, call.lineno, "JL404",
                        f"per-call-varying value `{reason}` reaches "
                        f"static key position {sname!r} of entry "
                        f"point {reg.name!r}: every distinct value "
                        "compiles a new program — pass it as a traced "
                        "operand (or a padded/quantized static) "
                        "instead",
                    ))
                    enumerable = False
                    continue
                dom = _value_domain(expr, anc, tree)
                if dom is None:
                    enumerable = False
                    continue
                domains.setdefault(sname, set()).update(dom)
        budget = budgets.get(reg.name)
        if enumerable and domains and budget is not None:
            card = math.prod(
                len(v) for v in domains.values() if v
            )
            if card > budget:
                knobs = ", ".join(
                    f"{k}:{len(v)}"
                    for k, v in sorted(domains.items())
                )
                diags.append(Diagnostic(
                    path, reg.line, "JL401",
                    f"entry point {reg.name!r} has a statically-"
                    f"possible trace-key cardinality of {card} "
                    f"({knobs}) exceeding RETRACE_BUDGETS"
                    f"[{reg.name!r}] = {budget}; shrink the static "
                    "knob domain or raise the budget with a "
                    "justifying comment in config.py",
                ))
    return diags


# ---------------------------------------------------------------------------
# Repo-wide audit: --trace-keys (JL402 dead budget / JL403 unbudgeted
# entry point) + the calibration inventory table


def _iter_package_files(root: str) -> List[str]:
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            dn for dn in dirnames
            if dn not in ("__pycache__", ".git")
            and not dn.startswith(".tmp-")
        )
        for f in sorted(filenames):
            if f.endswith(".py") and not f.startswith(".tmp-"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


@dataclass
class _EntryRow:
    name: str
    module: str
    line: int
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    jit_resolved: bool = False
    budget: Optional[int] = None
    findings: List[str] = field(default_factory=list)


def audit_trace_keys(root: Optional[str] = None) -> Tuple[dict, int]:
    """Cross-check ``config.RETRACE_BUDGETS`` against every
    ``register_entry_point`` site under ``root`` (default: the
    installed package). Returns (report, exit_code): 0 = every
    registered entry point budgeted and every budget live, 1 = any
    JL402 (dead budget), JL403 (unbudgeted entry point), or a
    registration whose name is not a string literal (unauditable)."""
    root = root or package_root()
    budgets = read_budgets(root)
    rows: List[_EntryRow] = []
    findings: List[dict] = []
    for path in _iter_package_files(root):
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        index = _ModuleIndex(tree)
        for reg in _registrations(tree, index):
            if reg.name is None:
                findings.append({
                    "rule": "JL403",
                    "name": reg.dynamic_name_expr,
                    "module": rel,
                    "line": reg.line,
                    "message": (
                        "register_entry_point name is not a string "
                        "literal — the retrace budget table cannot "
                        "be audited against it"
                    ),
                })
                continue
            rows.append(_EntryRow(
                name=reg.name,
                module=rel,
                line=reg.line,
                static_argnums=(
                    reg.spec.static_argnums if reg.spec else ()
                ),
                static_argnames=(
                    reg.spec.static_argnames if reg.spec else ()
                ),
                jit_resolved=reg.spec is not None,
                budget=budgets.get(reg.name),
            ))
    registered = {r.name for r in rows}
    budget_names = {
        k for k in budgets if k not in EXEMPT_BUDGET_KEYS
    }
    for name in sorted(budget_names - registered):
        findings.append({
            "rule": "JL402",
            "name": name,
            "message": (
                f"RETRACE_BUDGETS[{name!r}] = {budgets[name]} is a "
                "dead budget: no register_entry_point site declares "
                "this name — prune it (or restore the registration)"
            ),
        })
    for row in rows:
        if row.budget is None:
            row.findings.append("JL403")
            findings.append({
                "rule": "JL403",
                "name": row.name,
                "module": row.module,
                "line": row.line,
                "message": (
                    f"entry point {row.name!r} "
                    f"({row.module}:{row.line}) has no "
                    "RETRACE_BUDGETS entry: its compiles are "
                    "counted but never bounded — add a budget with "
                    "a justifying comment in config.py"
                ),
            })
    rows.sort(key=lambda r: (r.name, r.module, r.line))
    report = {
        "budgets": dict(sorted(budgets.items())),
        "entry_points": [
            {
                "name": r.name,
                "module": r.module,
                "line": r.line,
                "budget": r.budget,
                "static_argnums": list(r.static_argnums),
                "static_argnames": list(r.static_argnames),
                "jit_resolved": r.jit_resolved,
            }
            for r in rows
        ],
        "findings": findings,
    }
    return report, (1 if findings else 0)


def render_text(report: dict) -> str:
    grid = [["entry point", "budget", "registered at", "static key args"]]
    for row in report["entry_points"]:
        statics = ", ".join(
            [str(i) for i in row["static_argnums"]]
            + list(row["static_argnames"])
        )
        if not row["jit_resolved"]:
            statics = statics or "(jit not statically resolvable)"
        grid.append([
            row["name"],
            "—" if row["budget"] is None else str(row["budget"]),
            f"{row['module']}:{row['line']}",
            statics or "(none)",
        ])
    widths = [max(len(r[i]) for r in grid) for i in range(len(grid[0]))]
    lines = []
    for i, r in enumerate(grid):
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        )
        if i == 0:
            lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    n_entries = len(report["entry_points"])
    n_budgets = len([
        k for k in report["budgets"] if k not in EXEMPT_BUDGET_KEYS
    ])
    lines.append("")
    lines.append(
        f"{n_entries} registered entry point(s), {n_budgets} "
        "budget(s)"
    )
    for f in report["findings"]:
        where = (
            f" ({f['module']}:{f['line']})" if "module" in f else ""
        )
        lines.append(f"{f['rule']}: {f['name']}{where} — {f['message']}")
    if not report["findings"]:
        lines.append(
            "every budget live, every entry point budgeted"
        )
    return "\n".join(lines)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True)
