"""jaxlint rule registry: ids, one-line summaries, and full docs.

Each rule documents the JAX/TPU failure mode it guards, with a bad and
a good example. The analyzer (``core.py``) emits diagnostics keyed by
these ids; ``python -m pumiumtally_tpu.analysis --explain JL001`` prints
the doc. The long-form prose (including the pragma grammar) lives in
``docs/STATIC_ANALYSIS.md``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    doc: str


RULES: dict[str, Rule] = {}


def _rule(id: str, summary: str, doc: str) -> None:
    RULES[id] = Rule(id, summary, doc.strip())


_rule(
    "JL000",
    "jaxlint pragma without a justification",
    """
A `# jaxlint: disable=JL00x` pragma MUST carry a justification string:

    bad:   flux = np.asarray(dev)  # jaxlint: disable=JL001
    good:  flux = np.asarray(dev)  # jaxlint: disable=JL001 -- result
           # fetch at the tally boundary; the sync is the API contract

An unjustified pragma does NOT suppress its diagnostics — the original
finding is reported alongside this one. The justification is the review
record for why the flagged pattern is intentional.
""",
)

_rule(
    "JL001",
    "host-synchronizing call reachable from a traced (jit/scan/"
    "while_loop/shard_map) body",
    """
`.item()`, `.tolist()`, `np.asarray`/`np.array` on traced values,
`jax.device_get`, `block_until_ready`, `float()`/`int()`/`bool()` on
traced values, and host callbacks (`jax.pure_callback`,
`io_callback`, `jax.debug.callback`) either fail at trace time
(TracerArrayConversionError) or silently serialize the device pipeline
— inside the walk/migrate hot loops a single hidden sync forfeits the
dispatch pipelining the engine is built around.

    bad:
        @jax.jit
        def step(x):
            return x * float(jnp.max(x))      # traced -> trace error

    good:
        @jax.jit
        def step(x):
            return x * jnp.max(x)             # stays on device

Fetch results on the host AFTER the jitted call returns (the tally
boundary), never inside the traced body.
""",
)

_rule(
    "JL002",
    "Python-level control flow (if/while/assert) on a traced value",
    """
Python `if`/`while`/`assert` (and `x if c else y`) evaluate their
condition at trace time; a traced array has no concrete truth value, so
this raises TracerBoolConversionError — or worse, silently bakes one
concrete branch into the compiled program when the value is a
weakly-typed constant.

    bad:
        @jax.jit
        def clamp(x):
            if x.max() > 1.0:                 # traced condition
                x = x / x.max()
            return x

    good:
        @jax.jit
        def clamp(x):
            return jnp.where(x.max() > 1.0, x / x.max(), x)

Use `jnp.where` for element selection, `lax.cond` for real branching,
and `lax.while_loop` for data-dependent iteration.
""",
)

_rule(
    "JL003",
    "buffer used after being passed in a donated argument position",
    """
`donate_argnums`/`donate_argnames` hands the argument's device buffer
to XLA for reuse; the Python array object is left pointing at freed
memory, and touching it afterwards raises (or, on some backends,
silently reads garbage).

    bad:
        step = jax.jit(update, donate_argnums=(0,))
        state = step(state_in, inputs)
        print(state_in.sum())                 # donated buffer!

    good:
        step = jax.jit(update, donate_argnums=(0,))
        state = step(state_in, inputs)        # state_in is dead here
        print(state.sum())

Rebind the name (`state = step(state, ...)`) so the stale reference
cannot escape.
""",
)

_rule(
    "JL004",
    "static argument with a list/dict/set/array default (retrace bait)",
    """
`jax.jit` keys its compilation cache on the VALUES of static arguments.
A list/dict/set default is unhashable (TypeError at call time); an
array default — or any default rebuilt per call site — makes every
call a cache MISS, silently recompiling the program each move.

    bad:
        @partial(jax.jit, static_argnames=("knobs",))
        def walk(x, knobs=[8, 4]):            # unhashable static
            ...

    good:
        @partial(jax.jit, static_argnames=("knobs",))
        def walk(x, knobs=(8, 4)):            # hashable, cache-stable
            ...

Use tuples/frozensets/scalars for static defaults, and pass arrays as
traced (non-static) arguments.
""",
)

_rule(
    "JL005",
    "mutation of module-level state inside a traced body",
    """
A traced function body runs ONCE, at trace time — not per call. Writing
module-level state from it (a `global` assignment, `CACHE[k] = v`,
`LOG.append(...)`) bakes the trace-time value in and never runs again
for subsequent calls that hit the compilation cache; it is also a
hidden retrace dependency when the mutated state feeds later traces.

    bad:
        _SEEN = []
        @jax.jit
        def step(x):
            _SEEN.append(x.shape)             # runs once, then never
            return x + 1

    good:
        @jax.jit
        def step(x):
            return x + 1
        # record shapes at the call site, outside the trace

Keep traced bodies pure; do host-side bookkeeping at the facade layer.
""",
)
