"""jaxlint rule registry: ids, one-line summaries, and full docs.

Each rule documents the JAX/TPU failure mode it guards, with a bad and
a good example. The analyzer (``core.py``) emits diagnostics keyed by
these ids; ``python -m pumiumtally_tpu.analysis --explain JL001`` prints
the doc. The long-form prose (including the pragma grammar) lives in
``docs/STATIC_ANALYSIS.md``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    doc: str


RULES: dict[str, Rule] = {}


def _rule(id: str, summary: str, doc: str) -> None:
    RULES[id] = Rule(id, summary, doc.strip())


_rule(
    "JL000",
    "jaxlint pragma without a justification",
    """
A `# jaxlint: disable=JL00x` pragma MUST carry a justification string:

    bad:   flux = np.asarray(dev)  # jaxlint: disable=JL001
    good:  flux = np.asarray(dev)  # jaxlint: disable=JL001 -- result
           # fetch at the tally boundary; the sync is the API contract

An unjustified pragma does NOT suppress its diagnostics — the original
finding is reported alongside this one. The justification is the review
record for why the flagged pattern is intentional.
""",
)

_rule(
    "JL001",
    "host-synchronizing call reachable from a traced (jit/scan/"
    "while_loop/shard_map) body",
    """
`.item()`, `.tolist()`, `np.asarray`/`np.array` on traced values,
`jax.device_get`, `block_until_ready`, `float()`/`int()`/`bool()` on
traced values, and host callbacks (`jax.pure_callback`,
`io_callback`, `jax.debug.callback`) either fail at trace time
(TracerArrayConversionError) or silently serialize the device pipeline
— inside the walk/migrate hot loops a single hidden sync forfeits the
dispatch pipelining the engine is built around.

    bad:
        @jax.jit
        def step(x):
            return x * float(jnp.max(x))      # traced -> trace error

    good:
        @jax.jit
        def step(x):
            return x * jnp.max(x)             # stays on device

Fetch results on the host AFTER the jitted call returns (the tally
boundary), never inside the traced body.
""",
)

_rule(
    "JL002",
    "Python-level control flow (if/while/assert) on a traced value",
    """
Python `if`/`while`/`assert` (and `x if c else y`) evaluate their
condition at trace time; a traced array has no concrete truth value, so
this raises TracerBoolConversionError — or worse, silently bakes one
concrete branch into the compiled program when the value is a
weakly-typed constant.

    bad:
        @jax.jit
        def clamp(x):
            if x.max() > 1.0:                 # traced condition
                x = x / x.max()
            return x

    good:
        @jax.jit
        def clamp(x):
            return jnp.where(x.max() > 1.0, x / x.max(), x)

Use `jnp.where` for element selection, `lax.cond` for real branching,
and `lax.while_loop` for data-dependent iteration.
""",
)

_rule(
    "JL003",
    "buffer used after being passed in a donated argument position",
    """
`donate_argnums`/`donate_argnames` hands the argument's device buffer
to XLA for reuse; the Python array object is left pointing at freed
memory, and touching it afterwards raises (or, on some backends,
silently reads garbage).

    bad:
        step = jax.jit(update, donate_argnums=(0,))
        state = step(state_in, inputs)
        print(state_in.sum())                 # donated buffer!

    good:
        step = jax.jit(update, donate_argnums=(0,))
        state = step(state_in, inputs)        # state_in is dead here
        print(state.sum())

Rebind the name (`state = step(state, ...)`) so the stale reference
cannot escape.
""",
)

_rule(
    "JL004",
    "static argument with a list/dict/set/array default (retrace bait)",
    """
`jax.jit` keys its compilation cache on the VALUES of static arguments.
A list/dict/set default is unhashable (TypeError at call time); an
array default — or any default rebuilt per call site — makes every
call a cache MISS, silently recompiling the program each move.

    bad:
        @partial(jax.jit, static_argnames=("knobs",))
        def walk(x, knobs=[8, 4]):            # unhashable static
            ...

    good:
        @partial(jax.jit, static_argnames=("knobs",))
        def walk(x, knobs=(8, 4)):            # hashable, cache-stable
            ...

Use tuples/frozensets/scalars for static defaults, and pass arrays as
traced (non-static) arguments.
""",
)

_rule(
    "JL005",
    "mutation of module-level state inside a traced body",
    """
A traced function body runs ONCE, at trace time — not per call. Writing
module-level state from it (a `global` assignment, `CACHE[k] = v`,
`LOG.append(...)`) bakes the trace-time value in and never runs again
for subsequent calls that hit the compilation cache; it is also a
hidden retrace dependency when the mutated state feeds later traces.

    bad:
        _SEEN = []
        @jax.jit
        def step(x):
            _SEEN.append(x.shape)             # runs once, then never
            return x + 1

    good:
        @jax.jit
        def step(x):
            return x + 1
        # record shapes at the call site, outside the trace

Keep traced bodies pure; do host-side bookkeeping at the facade layer.
""",
)

_rule(
    "JL101",
    "collective uses an axis name not declared by the enclosing "
    "shard_map mesh/axis specs",
    """
Inside a `shard_map` body, every collective (`lax.psum`, `ppermute`,
`all_gather`, `axis_index`, ...) names the mesh axis it reduces or
permutes over. An axis name that does not appear in the call site's
`in_specs`/`out_specs`/`axis_names`/mesh declaration raises
`NameError: unbound axis name` at trace time — but only on the first
trace of that code path, which for the rarely-taken resume/retry
programs can be deep into a campaign.

    bad:
        f = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                      out_specs=P("dp"))
        def body(x):
            return lax.psum(x, "data")        # axis "data" not in specs

    good:
        def body(x):
            return lax.psum(x, "dp")          # declared axis

Only statically-literal axis names are checked; axis names carried in
variables (the engine's `axis_name(mesh)` idiom) are skipped.
""",
)

_rule(
    "JL102",
    "ppermute permutation is not a total permutation",
    """
`lax.ppermute` sends shard i's value to shard j for each `(i, j)` pair;
a device NOT named as a destination receives ZEROS (not its own value),
and a device named twice is undefined. A statically-enumerable `perm`
that is not a total permutation (duplicate sources, duplicate
destinations, or source/destination sets that differ) is therefore
almost always a dropped-shard bug — the collective migrate ring relies
on every hop being a bijection.

    bad:
        lax.ppermute(x, "dp", perm=[(0, 1), (2, 1)])   # 1 hit twice,
                                                       # 0 and 2 starve

    good:
        lax.ppermute(x, "dp", perm=[(0, 1), (1, 2), (2, 0)])  # ring

Only literal pair lists are checked; computed permutations (the
`[(i, (i+1) % ndev) ...]` comprehension) are skipped.
""",
)

_rule(
    "JL103",
    "per-shard reduction returned from a shard_map body through a "
    "replicated (P()) out_spec without a psum",
    """
A `jnp.sum`/`jnp.max`/... inside a `shard_map` body reduces only the
LOCAL shard. Returning that value through an out_spec of `P()` (fully
replicated) claims all shards agree — they do not, and shard_map's
replication checker rejects the program (or, with checking disabled,
one shard's partial total silently wins).

    bad:
        f = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                      out_specs=P())
        def body(x):
            return jnp.sum(x)                 # local partial total

    good:
        def body(x):
            return lax.psum(jnp.sum(x), "dp")  # true global total

Flagged only when the out_spec at the returned position is a literal
empty `P()`; shard-varying outputs (`P("dp")`) may carry per-shard
reductions legitimately.
""",
)

_rule(
    "JL104",
    "collective inside lax.cond/while_loop controlled by a shard-local "
    "predicate (divergent-control hazard)",
    """
`lax.cond` branches and `lax.while_loop` trip counts controlled by a
SHARD-LOCAL value can diverge across shards. If the conditionally-run
code contains a collective, some shards enter it and some do not — the
program deadlocks on real hardware (each participant waits for peers
that never arrive). Predicates must be replicated: derive them from a
`psum`/`pmin`-style reduction so every shard takes the same path.

    bad:
        def body(x):
            n = jnp.sum(x > 0)                # per-shard count
            return lax.cond(n > 0,
                            lambda v: lax.psum(v, "dp"),
                            lambda v: v, x)   # divergent psum

    good:
        def body(x):
            n = lax.psum(jnp.sum(x > 0), "dp")  # replicated count
            return lax.cond(n > 0,
                            lambda v: lax.psum(v, "dp"),
                            lambda v: v, x)

Only flagged when the cond/while operand functions actually contain a
collective — shard-local early exits of pure-local loops (the walk
kernels) are legal SPMD.
""",
)

_rule(
    "JL201",
    "Pallas BlockSpec working set exceeds the documented VMEM ceiling",
    """
Mosaic rejects kernels whose scoped-VMEM working set exceeds the
compiler limit ("scoped allocation ... exceeded scoped vmem limit") —
but only at AOT-compile time on hardware this repo usually cannot
reach (ROADMAP "standing caveat"). This rule statically sums the
block-resident bytes a `pl.pallas_call`'s literal BlockSpec shapes
declare and flags working sets beyond the measured feasibility model
(`VMEM_FEASIBLE_MAX_ELEMS` in ops/vmem_walk.py: an
[8192, 32] f32 table block — 1 MiB of declared operand — is the
largest block that compiles at the production particle tile).

    bad:
        pl.pallas_call(k, in_specs=[pl.BlockSpec((65536, 32),
                                                 lambda i: (i, 0))], ...)

    good:
        pl.pallas_call(k, in_specs=[pl.BlockSpec((8192, 32),
                                                 lambda i: (i, 0))], ...)

Only statically-resolvable block dims (literals, module constants,
simple arithmetic) are summed; runtime-sized blocks are skipped.
""",
)

_rule(
    "JL202",
    "Pallas kernel writes an input ref, or reads an output ref "
    "before writing it",
    """
Pallas refs have roles fixed by the `pallas_call` signature: the first
`len(in_specs)` kernel parameters are INPUT refs (read-only views of
operand blocks), the rest are OUTPUT refs (uninitialized until the
kernel writes them). Writing an input ref is undefined (Mosaic may
alias the operand); reading an output ref before any write reads
garbage — on the interpret path it often reads zeros, so the bug only
detonates on hardware.

    bad:
        def kernel(x_ref, o_ref):
            x_ref[0] = 0.0                    # input-ref write
            acc = o_ref[...]                  # read before any write

    good:
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0     # seed output, then reuse

Revisited-block accumulation reads ARE legal once the first grid step
seeds the block (`pl.when(t == 0)` init) — the rule only flags reads
that lexically precede every write in the kernel's own statement flow.
Kernels whose in_specs are not a literal list are skipped.
""",
)

_rule(
    "JL203",
    "Pallas array dimension not divisible by its BlockSpec block "
    "dimension",
    """
A grid dimension covers its array extent in whole blocks; when the
array dimension is not a multiple of the block dimension the trailing
block reads out of bounds (masked on some backends, garbage on
others) — and Mosaic's rank-1 tiling law additionally requires
TILE-aligned block lengths (ops/vmem_walk.py TILE_1D). Statically
checkable pairs are out_shape dims vs out_specs block dims.

    bad:
        pl.pallas_call(k, out_shape=jax.ShapeDtypeStruct((100,), f32),
                       out_specs=pl.BlockSpec((64,), lambda i: (i,)))

    good:
        pl.pallas_call(k, out_shape=jax.ShapeDtypeStruct((128,), f32),
                       out_specs=pl.BlockSpec((64,), lambda i: (i,)))

Only literal/module-constant dims are compared; runtime shapes are
skipped.
""",
)

_rule(
    "JL204",
    "host-side call inside a Pallas kernel body",
    """
A Pallas kernel body lowers to Mosaic; Python-level host effects —
`print`, `open`, `time.*`, `os.*`, `logging` — run ONCE at trace time
(misleading debug output) or fail to lower outright. Device-side
debugging belongs to `pl.debug_print`; host-side I/O belongs outside
the `pallas_call`. (Host SYNC calls like `.item()` are already JL001 —
this rule covers the host-effect calls JL001's sync model does not.)

    bad:
        def kernel(x_ref, o_ref):
            print("block", x_ref[0])          # trace-time only
            o_ref[...] = x_ref[...]

    good:
        def kernel(x_ref, o_ref):
            pl.debug_print("block {}", x_ref[0])
            o_ref[...] = x_ref[...]
""",
)

_rule(
    "JL301",
    "instance state written from two thread roots without a lock",
    """
The service layer is multi-threaded by contract (worker loop, client
threads, signal-initiated drain — the thread-root registry in
analysis/concurrency.py names the entry points per class). An instance
attribute written from TWO different roots where at least one write
holds no recognized lock is a data race: torn multi-field updates,
lost wakeups, check-then-act corruption.

    bad:
        class Svc:
            def start(self):                  # client root
                self._jobs = []               # unlocked write
            def _worker_loop(self):           # worker root
                with self._lock:
                    self._jobs.append(1)

    good:
        class Svc:
            def start(self):
                with self._lock:
                    self._jobs = []
            def _worker_loop(self):
                with self._lock:
                    self._jobs.append(1)

`__init__` writes are exempt (the object is not yet shared). Locks are
the class's own threading.Lock/RLock/Condition attributes.
""",
)

_rule(
    "JL302",
    "lock-ordering cycle between recognized locks",
    """
Two code paths that acquire the same pair of locks in opposite orders
deadlock the moment they interleave. The rule builds the
acquired-while-holding graph from nested `with <lock>:` statements
(following one level of same-class method calls) and reports any
cycle.

    bad:
        def a(self):
            with self._lock_a:
                with self._lock_b: ...
        def b(self):
            with self._lock_b:
                with self._lock_a: ...        # reversed order

    good:
        def b(self):
            with self._lock_a:                # single global order
                with self._lock_b: ...

Lock identity is `ClassName.attr` (or the module-level name); the
graph is per-module.
""",
)

_rule(
    "JL303",
    "blocking call while holding a lock",
    """
`Future.result()`, thread `join()`, socket `recv`/`accept`,
`queue.get()` and untimed `wait()` block indefinitely; doing so while
holding a lock extends the critical section by an unbounded wait and
couples it to another thread's progress — the classic shape of the
service-layer deadlock (the worker needs the lock to produce the very
result being waited on). The engine's own contract is the opposite:
device work and result waits happen OUTSIDE the service lock.

    bad:
        with self._lock:
            flux = fut.result()               # unbounded, lock held

    good:
        with self._lock:
            fut = self._inflight.pop()
        flux = fut.result()                   # wait outside the lock

`Condition.wait(timeout)` on the HELD condition is exempt (it releases
the lock); calls with a timeout argument are exempt (bounded).
""",
)


_rule(
    "JL401",
    "statically-possible trace-key cardinality exceeds the budget",
    """
Every value reaching a static key position of a registered jit entry
point multiplies the number of programs XLA may compile for it. When
the full set of call sites passes only statically-enumerable values —
literals, or loop variables ranging over literal tuples — the possible
cardinality is a provable number, and it must fit inside
`config.RETRACE_BUDGETS[name]` or the runtime tripwire
(tests/conftest.py) WILL eventually fire on some knob combination CI
happened not to exercise.

    bad:
        _step = register_entry_point("walk", jax.jit(
            step, static_argnames=("mode", "order")))
        for mode in ("fast", "exact", "paranoid"):
            for order in (1, 2, 3, 4):
                _step(state, mode=mode, order=order)
        # 3 x 4 = 12 possible keys vs RETRACE_BUDGETS["walk"] = 3

    good:
        # shrink the knob domain, fold knobs together, or raise the
        # budget with a justifying comment in config.py:
        for mode in ("fast", "exact"):
            _step(state, mode=mode)           # 2 <= budget

One runtime-valued knob makes the cardinality unknowable and the check
skips the entry point entirely (the runtime tripwire still guards it).
""",
)

_rule(
    "JL402",
    "dead retrace budget: no matching entry point",
    """
A `config.RETRACE_BUDGETS` key with no `register_entry_point` site
declaring that name bounds nothing: the tripwire looks up budgets by
the REGISTERED name, so a stale key silently stops guarding the entry
point it used to describe (typically after a rename).

    bad:   RETRACE_BUDGETS = {"walk_v1": 3}   # renamed to "walk"
    good:  RETRACE_BUDGETS = {"walk": 3}      # matches the live site

Reported by the repo-wide audit (`--trace-keys`), not the per-file
lint: prune the dead key or restore the registration.
""",
)

_rule(
    "JL403",
    "unbudgeted entry point: compiles counted but never bounded",
    """
A `register_entry_point` site whose name has no
`config.RETRACE_BUDGETS` entry is profiled but untripwired: its
recompiles show up in `PUMIUMTALLY_RETRACE_RECORD` output yet no test
can ever fail on a retrace storm there.

    bad:   _step = register_entry_point("walk_v2", jax.jit(step))
           # RETRACE_BUDGETS has no "walk_v2" key
    good:  add `"walk_v2": <measured + headroom>` to RETRACE_BUDGETS
           with a justifying comment (tools/retrace_calibrate.py
           prints the measured number).

Reported by the repo-wide audit (`--trace-keys`); a registration whose
name is not a string literal is reported the same way (it cannot be
audited against the budget table at all).
""",
)

_rule(
    "JL404",
    "per-call-varying value in a static jit key position",
    """
Passing a data-dependent size — `len(batch)`, `x.shape[0]`, `x.size`
of a function argument — into a static key position of a registered
entry point compiles one program PER DISTINCT VALUE: unbounded retrace
bait that JL004's single-function view cannot see, because the
varying value crosses the caller/entry-point boundary.

    bad:
        def serve(batch):
            return _step(state, n=len(batch))   # n is static

    good:
        def serve(batch):
            padded = pad_to_bucket(batch)       # quantize the domain
            return _step(state, padded)         # size is traced shape

Route the value through a traced operand, or quantize it to a small
literal bucket set so the cardinality is provable again.
""",
)

_rule(
    "JL501",
    "unordered set iteration feeding an order-sensitive sink",
    """
Python `set` iteration order depends on hash seeding and insertion
history — it is not stable across runs, let alone hosts. Feeding it to
a device op, a wire reply, or accumulating `append`/`extend` state
(checkpoint key order) silently re-randomizes an order the device side
worked to pin, breaking the bitwise-determinism contract.
`list(...)`/`tuple(...)` of a set materializes the same hazard.

    bad:
        for sid in active_sessions:            # a set
            replies.append(encode(sid))        # wire order varies

    good:
        for sid in sorted(active_sessions):
            replies.append(encode(sid))

Dict iteration is insertion-ordered and is NOT flagged; a set used for
membership tests stays legal.
""",
)

_rule(
    "JL502",
    "non-stable sort on a segmented-commit path",
    """
The fused-scatter stability proof (PR 9's commit contract) assumes
ties keep their lane order through the sort that groups segments.
`np.argsort` defaults to quicksort, which reorders equal keys
run-to-run; in a function that also performs a segmented commit
(`.at[...].add/.set` or a `segment_sum`) that tie-break leaks into
the committed accumulation order.

    bad:   order = np.argsort(bins)            # quicksort ties
           acc = acc.at[bins[order]].add(w[order])

    good:  order = np.argsort(bins, kind="stable")
           # jnp.argsort is stable by default and stays unflagged
           # unless explicitly made unstable (stable=False).
""",
)

_rule(
    "JL503",
    "host-side float re-accumulation over device fetches",
    """
Builtin `sum()` over device fetches (`jax.device_get(...)` /
`.tolist()`) left-folds with HOST rounding order — a different
association than the device's pinned segmented reduction, so two runs
(or host/device) disagree in the last ulp and a parity gate flakes.

    bad:   total = sum(jax.device_get(flux).tolist())
    good:  total = float(jnp.sum(flux))        # reduce on device,
           # fetch one scalar; compare device-reduced values only.
""",
)
