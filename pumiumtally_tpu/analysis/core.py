"""jaxlint core: a JAX-aware trace-safety analyzer (pure stdlib).

What ruff/clang-tidy cannot see — and what actually bites a JAX/TPU
codebase — is the TRACE BOUNDARY: code that is syntactically ordinary
Python but executes inside ``jax.jit`` / ``lax.while_loop`` /
``lax.scan`` / ``shard_map`` / ``pallas_call`` tracing, where host
synchronization, Python control flow on traced arrays, donated-buffer
reuse and impure module state are all latent production bugs. This
module finds those statically, from the AST alone (no jax import, no
execution), so it can run in CI next to ruff.

Analysis model, in one paragraph: a first pass indexes every module —
import aliases (``np``/``jnp``/``lax``/…), every function definition,
and every ``jax.jit`` wrapping (decorator form, ``partial(jax.jit,…)``
form, and the ``g = jax.jit(f, …)`` assignment form, including
static/donated argument declarations). A second pass marks TRACE ROOTS:
functions jit/pmap-decorated, wrapped by ``shard_map``, or passed as
the callable operand of ``lax.while_loop``/``scan``/``cond``/
``fori_loop``/``map``/``switch``/``pallas_call``. Each root's body is
then checked, and calls from it into same-module helpers are followed
ONE level deep (taint flows through the matched call arguments).
Traced-value taint starts at the root's non-static parameters and
propagates through assignments and ``jax.*`` calls; rules JL001/JL002
consult it so that branching on a static config knob inside a traced
body stays legal while branching on a particle array does not.

Suppression: ``# jaxlint: disable=JL00x -- <why>`` on the flagged line;
the justification is mandatory (a bare pragma reports JL000 and
suppresses nothing). See ``rules.py`` / docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Optional, Union

from pumiumtally_tpu.analysis.rules import RULES

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# Callables that TRACE their function operand, and which positional
# argument(s) hold it. Keys are canonical dotted names after alias
# resolution ("lax" -> "jax.lax", "jnp" -> "jax.numpy", ...).
_TRACE_CALL_POSITIONS: dict[str, tuple[int, ...]] = {
    "jax.jit": (0,),
    "jax.pmap": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.scan": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),  # arg 1 is a sequence of branches
    "jax.lax.fori_loop": (2,),
    "jax.lax.map": (0,),
    "jax.shard_map": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
    "jax.experimental.pallas.pallas_call": (0,),
}

# Host-sync calls flagged in traced code regardless of operand taint:
# these APIs only exist to touch device buffers / the host.
_SYNC_DOTTED = {
    "jax.device_get",
    "jax.block_until_ready",
    "jax.pure_callback",
    "jax.debug.callback",
    "jax.experimental.io_callback",
}
# Method names with the same property (obj.item() etc.).
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Calls that sync ONLY when handed a traced value (np.asarray of a
# static tuple at trace time is fine; of a tracer it is an error).
_TAINT_SYNC_DOTTED = {"numpy.asarray", "numpy.array"}
_TAINT_SYNC_BUILTINS = {"float", "int", "bool", "complex"}

# Default-expression constructors that make a static argument retrace
# bait (JL004) — cache-key-unstable or unhashable.
_ARRAY_MAKER_PREFIXES = ("numpy.", "jax.numpy.")

_PRAGMA_RE = re.compile(
    r"#\s*jaxlint:\s*disable=(JL\d+(?:\s*,\s*JL\d+)*)\s*(?:--\s*(\S.*))?$"
)

# Mutating container methods for JL005.
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "sort", "reverse",
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass
class JitSpec:
    """Static/donated argument declarations of one jit wrapping."""

    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    donate_argnums: tuple[int, ...] = ()
    donate_argnames: tuple[str, ...] = ()


def _const_strings(node: Optional[ast.AST]) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _const_ints(node: Optional[ast.AST]) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def _jit_spec_from_keywords(call: ast.Call) -> JitSpec:
    spec = JitSpec()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            spec.static_argnums = _const_ints(kw.value)
        elif kw.arg == "static_argnames":
            spec.static_argnames = _const_strings(kw.value)
        elif kw.arg == "donate_argnums":
            spec.donate_argnums = _const_ints(kw.value)
        elif kw.arg == "donate_argnames":
            spec.donate_argnames = _const_strings(kw.value)
    return spec


class _ModuleIndex(ast.NodeVisitor):
    """First pass: aliases, function defs, jit wrappings, module state."""

    def __init__(self, tree: ast.Module) -> None:
        # name -> canonical dotted module path ("np" -> "numpy").
        self.aliases: dict[str, str] = {}
        # simple name -> [FunctionDef, ...] anywhere in the module.
        self.functions: dict[str, list[ast.AST]] = {}
        # id(FunctionDef) -> JitSpec for every jit-wrapped function.
        self.jit_specs: dict[int, JitSpec] = {}
        # local callable name -> JitSpec for donated-jit call targets
        # (covers `step = jax.jit(f, donate_argnums=...)`).
        self.donating_names: dict[str, JitSpec] = {}
        # Names assigned at module level (JL005 targets).
        self.module_names: set[str] = set()
        # Lexical scoping: scope key (None = module, else id(func)) ->
        # name -> [defs in that scope]; and func id -> enclosing func.
        self.scope_defs: dict[Optional[int], dict[str, list[ast.AST]]] = {}
        self.owner_of: dict[int, Optional[ast.AST]] = {}
        self._tree = tree
        self._index()
        self._collect_scopes(None, tree.body)

    # -- lexical scopes ---------------------------------------------------
    @staticmethod
    def _iter_scope_nodes(roots: list) -> Iterable[ast.AST]:
        """All nodes under ``roots`` excluding nested function
        INTERIORS (the nested def/lambda node itself is yielded)."""
        stack = list(roots)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _collect_scopes(self, owner: Optional[ast.AST], body: list) -> None:
        key = None if owner is None else id(owner)
        defs = self.scope_defs.setdefault(key, {})
        for node in self._iter_scope_nodes(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                self.owner_of[id(node)] = owner
                self._collect_scopes(node, node.body)
            elif isinstance(node, ast.Lambda):
                self.owner_of[id(node)] = owner
                self._collect_scopes(node, [node.body])

    def resolve_in_scope(
        self, name: str, owner: Optional[ast.AST], line: int
    ) -> Optional[ast.AST]:
        """Innermost-scope function def visible from (owner, line):
        the enclosing-function chain first, then module level. Within a
        scope, the latest def at or before ``line`` wins (lexical
        shadowing — e.g. the per-window ``cond`` redefinitions in the
        walk cascade)."""
        key = None if owner is None else id(owner)
        while True:
            cands = self.scope_defs.get(key, {}).get(name, [])
            if cands:
                before = [c for c in cands if c.lineno <= line]
                if before:
                    return max(before, key=lambda c: c.lineno)
                return min(cands, key=lambda c: c.lineno)
            if key is None:
                return None
            parent = self.owner_of.get(key)
            key = None if parent is None else id(parent)

    # -- imports ---------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module is None or node.level:
            return
        for a in node.names:
            self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def is_module_func(self, node: ast.AST) -> bool:
        """Whether a call's func expression is rooted at an imported
        name (``np.asarray``) rather than a runtime object's method
        (``arr.item()``)."""
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        return isinstance(root, ast.Name) and root.id in self.aliases

    # -- dotted-name resolution ------------------------------------------
    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression, alias-resolved
        ("jnp.where" -> "jax.numpy.where"), or None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        return ".".join([base] + list(reversed(parts)))

    # -- jit wrapping recognition ----------------------------------------
    def _jit_spec_of_wrapper(self, expr: ast.AST) -> Optional[JitSpec]:
        """JitSpec if ``expr`` is a jit-ish wrapper expression:
        ``jax.jit`` / ``jax.pmap`` / ``partial(jax.jit, ...)``."""
        d = self.dotted(expr)
        if d in ("jax.jit", "jax.pmap"):
            return JitSpec()
        if isinstance(expr, ast.Call):
            fd = self.dotted(expr.func)
            if fd in ("jax.jit", "jax.pmap"):
                # jax.jit(static_argnames=...) used as a decorator factory
                return _jit_spec_from_keywords(expr)
            if fd in ("functools.partial", "partial") and expr.args:
                inner = self.dotted(expr.args[0])
                if inner in ("jax.jit", "jax.pmap"):
                    return _jit_spec_from_keywords(expr)
        return None

    def _is_shard_map_wrapper(self, expr: ast.AST) -> bool:
        d = self.dotted(expr)
        if d and d.split(".")[-1] == "shard_map":
            return True
        if isinstance(expr, ast.Call):
            fd = self.dotted(expr.func)
            if fd and fd.split(".")[-1] == "shard_map":
                return True
            if fd in ("functools.partial", "partial") and expr.args:
                inner = self.dotted(expr.args[0])
                if inner and inner.split(".")[-1] == "shard_map":
                    return True
        return False

    # -- indexing --------------------------------------------------------
    def _index(self) -> None:
        for stmt in self._tree.body:
            for tgt in self._assign_targets(stmt):
                self.module_names.add(tgt)
        for node in ast.walk(self._tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self.visit(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
                for dec in node.decorator_list:
                    spec = self._jit_spec_of_wrapper(dec)
                    if spec is not None:
                        self.jit_specs[id(node)] = spec
            elif isinstance(node, ast.Assign):
                self._index_assign(node)

    @staticmethod
    def _assign_targets(stmt: ast.stmt) -> list[str]:
        tgts: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            tgts = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            tgts = [stmt.target]
        out = []
        for t in tgts:
            if isinstance(t, ast.Name):
                out.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                out.extend(e.id for e in t.elts if isinstance(e, ast.Name))
        return out

    def _find_jit_wrapping(
        self, v: ast.AST, depth: int = 0
    ) -> Optional[tuple]:
        """(JitSpec, wrapped-fn expr) if ``v`` is a jit wrapping:
        ``jax.jit(f, ...)`` / ``partial(jax.jit, ...)(f)`` — possibly
        nested inside ANOTHER call's arguments, e.g.
        ``register_entry_point("walk", jax.jit(f))`` (the retrace
        wrapper must not hide the jit from trace-root discovery)."""
        if not isinstance(v, ast.Call) or depth > 2:
            return None
        fd = self.dotted(v.func)
        if fd in ("jax.jit", "jax.pmap") and v.args:
            return _jit_spec_from_keywords(v), v.args[0]
        if isinstance(v.func, ast.Call):
            wrapper = self._jit_spec_of_wrapper(v.func)
            if wrapper is not None and v.args:
                return wrapper, v.args[0]
        for arg in v.args:
            found = self._find_jit_wrapping(arg, depth + 1)
            if found is not None:
                return found
        return None

    def _index_assign(self, node: ast.Assign) -> None:
        """Recognize ``g = jax.jit(f, ...)`` and
        ``g = partial(jax.jit, ...)(f)`` (including the jit call nested
        in a wrapper's arguments) — mark f's def as jitted and record g
        as a donating call target when buffers are donated."""
        found = self._find_jit_wrapping(node.value)
        if found is None:
            return
        spec, target_fn = found
        # jax.jit(partial(f, ...)) — resolve through the partial.
        if isinstance(target_fn, ast.Call):
            td = self.dotted(target_fn.func)
            if td in ("functools.partial", "partial") and target_fn.args:
                target_fn = target_fn.args[0]
        if isinstance(target_fn, ast.Name):
            for fn in self.functions.get(target_fn.id, []):
                self.jit_specs[id(fn)] = spec
        # Donation is a property of the CALL-SITE name, whatever got
        # wrapped (named function, lambda, partial).
        if spec.donate_argnums or spec.donate_argnames:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.donating_names[t.id] = spec

    def resolve_function(self, name: str) -> Optional[ast.AST]:
        """The module's unique function def called ``name`` (ambiguous
        or unknown names resolve to None — the analyzer then simply
        does not follow the call)."""
        cands = self.functions.get(name, [])
        return cands[0] if len(cands) == 1 else None


_STMT_BODY_FIELDS = ("body", "orelse", "finalbody", "handlers")


def _iter_stmt_exprs(stmt: ast.stmt) -> Iterable[ast.AST]:
    """Expression nodes belonging to one statement's own evaluation:
    its test/iter/targets/value/etc., excluding nested statement lists
    (the recursion visits those with updated taint) and nested function
    defs (analyzed via their own calls/trace roots). Lambdas ARE
    descended into — an inline lambda's body executes in the enclosing
    traced context when called."""
    stack: list[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if field in _STMT_BODY_FIELDS:
            continue
        vs = value if isinstance(value, list) else [value]
        stack.extend(v for v in vs if isinstance(v, ast.AST))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _func_params(fn: FuncNode) -> list[ast.arg]:
    a = fn.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _static_param_names(fn: FuncNode, spec: Optional[JitSpec]) -> set[str]:
    if spec is None:
        return set()
    params = _func_params(fn)
    names = set(spec.static_argnames)
    for i in spec.static_argnums:
        if 0 <= i < len(params):
            names.add(params[i].arg)
    return names


# Attribute reads that are STATIC under trace (shape metadata, not
# array data) — a branch on them is trace-safe.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
# Builtins whose result is concrete even on a traced operand.
_STATIC_FUNCS = {
    "len", "isinstance", "issubclass", "hasattr", "getattr", "callable",
    "type", "range", "enumerate", "zip", "id", "repr",
}


class _Taint:
    """Forward may-be-traced analysis over one function body.

    The cut-offs matter as much as the sources: ``x is None``,
    ``x.shape[0]``, ``len(x)`` are all concrete at trace time even when
    ``x`` is a tracer — flagging them would make the linter unusable on
    exactly the static-shape bookkeeping a JAX kernel is full of.
    """

    def __init__(self, index: _ModuleIndex, traced: set[str]) -> None:
        self.index = index
        self.traced = set(traced)

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            return False  # identity checks yield concrete bools
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call):
            d = self.index.dotted(node.func)
            if d in _STATIC_FUNCS:
                return False
            if d and (d.startswith("jax.numpy.") or
                      d.startswith("jax.lax.")):
                return True  # jnp/lax calls produce traced arrays
        return any(
            self.expr_tainted(sub) for sub in ast.iter_child_nodes(node)
        )

    def absorb(self, stmt: ast.stmt) -> None:
        """Update taint for one statement (assignments only — the
        precision a linter needs, not a verifier's)."""
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is None:
                return
            tainted = self.expr_tainted(value)
            if isinstance(stmt, ast.AugAssign):
                # `x += 1` reads x: a traced x stays traced even when
                # the RHS operand is concrete.
                tainted = tainted or self.expr_tainted(stmt.target)
            for name in _ModuleIndex._assign_targets(stmt):
                if tainted:
                    self.traced.add(name)
                else:
                    self.traced.discard(name)


class Analyzer:
    """Per-file rule driver. ``run()`` returns the diagnostics."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.diags: list[Diagnostic] = []

    # -- entry -----------------------------------------------------------
    def run(self) -> list[Diagnostic]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            return [
                Diagnostic(
                    self.path, e.lineno or 1, "JL000",
                    f"could not parse file: {e.msg}",
                )
            ]
        index = _ModuleIndex(tree)
        roots = self._trace_roots(tree, index)
        seen: set[tuple[int, str]] = set()
        for root, spec in roots:
            self._check_traced_function(root, spec, index, seen)
        self._check_donation(tree, index)
        self._check_static_defaults(tree, index)
        # The JL1xx/2xx/3xx/4xx/5xx passes share this parse + index
        # and feed the same dedup/pragma pipeline below. Imported
        # lazily: the pass modules import Diagnostic/_ModuleIndex
        # from here.
        from pumiumtally_tpu.analysis import (
            collective,
            concurrency,
            determinism,
            pallas,
            tracekeys,
        )

        for check in (collective.check, pallas.check, concurrency.check,
                      tracekeys.check, determinism.check):
            self.diags.extend(check(tree, index, self.path))
        # Nested defs are reachable both through their own walk and the
        # enclosing function's — keep the first of any exact duplicate.
        unique: dict[tuple, Diagnostic] = {}
        for d in self.diags:
            unique.setdefault((d.path, d.line, d.rule, d.message), d)
        self.diags = list(unique.values())
        return self._apply_pragmas(self.diags)

    # -- trace-root discovery --------------------------------------------
    def _trace_roots(
        self, tree: ast.Module, index: _ModuleIndex
    ) -> list[tuple[FuncNode, Optional[JitSpec]]]:
        roots: dict[int, tuple[FuncNode, Optional[JitSpec]]] = {}

        def add(node: ast.AST, spec: Optional[JitSpec]) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                roots.setdefault(id(node), (node, spec))

        def add_operand(
            op: ast.AST,
            spec: Optional[JitSpec],
            owner: Optional[ast.AST],
            line: int,
        ) -> None:
            if isinstance(op, ast.Lambda):
                add(op, spec)
            elif isinstance(op, ast.Name):
                fn = index.resolve_in_scope(op.id, owner, line)
                if fn is not None:
                    add(fn, spec)
            elif isinstance(op, (ast.Tuple, ast.List)):
                for e in op.elts:  # lax.switch branch sequences
                    add_operand(e, spec, owner, line)
            elif isinstance(op, ast.Call):
                # partial(f, ...) / partial(shard_map, ...)(f)-style
                d = index.dotted(op.func)
                if d in ("functools.partial", "partial") and op.args:
                    add_operand(op.args[0], spec, owner, line)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                spec = index.jit_specs.get(id(node))
                if spec is not None:
                    add(node, spec)
                    continue
                if any(
                    index._is_shard_map_wrapper(dec)
                    for dec in node.decorator_list
                ):
                    add(node, None)

        def scan_scope(owner: Optional[ast.AST], body: list) -> None:
            for node in _ModuleIndex._iter_scope_nodes(body):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    scan_scope(node, node.body)
                elif isinstance(node, ast.Lambda):
                    scan_scope(node, [node.body])
                elif isinstance(node, ast.Call):
                    d = index.dotted(node.func)
                    if d is None:
                        continue
                    positions = _TRACE_CALL_POSITIONS.get(d)
                    if positions is None and (
                        d.split(".")[-1] == "shard_map"
                    ):
                        positions = (0,)
                    if positions is None:
                        continue
                    spec = (
                        _jit_spec_from_keywords(node)
                        if d in ("jax.jit", "jax.pmap")
                        else None
                    )
                    for i in positions:
                        if i < len(node.args):
                            add_operand(
                                node.args[i], spec, owner, node.lineno
                            )

        scan_scope(None, tree.body)
        return list(roots.values())

    # -- traced-body checks (JL001/JL002/JL005) --------------------------
    def _check_traced_function(
        self,
        fn: FuncNode,
        spec: Optional[JitSpec],
        index: _ModuleIndex,
        seen: set[tuple[int, str]],
        taint_override: Optional[set[str]] = None,
        depth: int = 0,
    ) -> None:
        static = _static_param_names(fn, spec)
        if taint_override is not None:
            traced = taint_override
        else:
            traced = {
                p.arg
                for p in _func_params(fn)
                if p.arg not in static and p.arg not in ("self", "cls")
            }
        taint = _Taint(index, traced)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        globals_declared: set[str] = set()
        for stmt in body:
            self._check_stmt(
                stmt, taint, index, seen, globals_declared, depth, fn
            )

    def _emit(
        self,
        node: ast.AST,
        rule: str,
        message: str,
        seen: set[tuple[int, str]],
    ) -> None:
        line = getattr(node, "lineno", 1)
        key = (line, rule)
        if key in seen:
            return  # one report per (line, rule): helpers shared by
        seen.add(key)  # several trace roots flag once
        self.diags.append(Diagnostic(self.path, line, rule, message))

    def _check_stmt(
        self,
        stmt: ast.stmt,
        taint: _Taint,
        index: _ModuleIndex,
        seen: set[tuple[int, str]],
        globals_declared: set[str],
        depth: int,
        scope: Optional[ast.AST] = None,
    ) -> None:
        # JL002: Python control flow on traced values.
        if isinstance(stmt, (ast.If, ast.While)) and taint.expr_tainted(
            stmt.test
        ):
            kind = "if" if isinstance(stmt, ast.If) else "while"
            self._emit(
                stmt, "JL002",
                f"Python `{kind}` on a traced value inside a traced "
                "body; use jnp.where / lax.cond / lax.while_loop "
                f"(rule docs: {RULES['JL002'].summary})",
                seen,
            )
        elif isinstance(stmt, ast.Assert) and taint.expr_tainted(stmt.test):
            self._emit(
                stmt, "JL002",
                "Python `assert` on a traced value inside a traced "
                "body; use checkify or move the check to the host "
                "boundary",
                seen,
            )

        # JL005: module-state mutation under trace.
        if isinstance(stmt, ast.Global):
            globals_declared.update(stmt.names)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                base = t
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if not isinstance(base, ast.Name):
                    continue
                is_container_write = base is not t
                if base.id in globals_declared or (
                    is_container_write and base.id in index.module_names
                ):
                    self._emit(
                        stmt, "JL005",
                        f"mutation of module-level state `{base.id}` "
                        "inside a traced body runs once at trace time, "
                        "not per call",
                        seen,
                    )

        # Expression-level checks (JL001, JL005 mutators, IfExp, and
        # one-level helper resolution) — over THIS statement's own
        # expressions only. Nested statements are visited exclusively
        # by the recursion below, with taint as of their position; a
        # flat ast.walk here would re-check them with stale
        # pre-statement taint and pin the wrong verdict in `seen`.
        for node in _iter_stmt_exprs(stmt):
            if isinstance(node, ast.IfExp) and taint.expr_tainted(node.test):
                self._emit(
                    node, "JL002",
                    "conditional expression on a traced value inside a "
                    "traced body; use jnp.where",
                    seen,
                )
            if isinstance(node, ast.Call):
                self._check_call(node, taint, index, seen, depth, scope)

        taint.absorb(stmt)

        # Recurse into compound statements' bodies.
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, []) or []:
                if isinstance(sub, ast.stmt):
                    self._check_stmt(
                        sub, taint, index, seen, globals_declared, depth,
                        scope,
                    )
        for handler in getattr(stmt, "handlers", []) or []:
            for sub in handler.body:
                self._check_stmt(
                    sub, taint, index, seen, globals_declared, depth,
                    scope,
                )

    def _check_call(
        self,
        node: ast.Call,
        taint: _Taint,
        index: _ModuleIndex,
        seen: set[tuple[int, str]],
        depth: int,
        scope: Optional[ast.AST] = None,
    ) -> None:
        d = index.dotted(node.func)

        # JL001: unconditional host syncs.
        if d in _SYNC_DOTTED:
            self._emit(
                node, "JL001",
                f"`{d}` is a host synchronization point inside a traced "
                "body; fetch results after the jitted call returns",
                seen,
            )
            return
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            # a real method call on an object, not a module function
            and not index.is_module_func(node.func)
        ):
            self._emit(
                node, "JL001",
                f"`.{node.func.attr}()` forces a device->host transfer "
                "inside a traced body",
                seen,
            )
            return

        # JL001: taint-gated syncs (np.asarray(tracer), float(tracer)).
        first_tainted = bool(node.args) and taint.expr_tainted(node.args[0])
        if d in _TAINT_SYNC_DOTTED and first_tainted:
            self._emit(
                node, "JL001",
                f"`{d}` on a traced value materializes it on the host "
                "(TracerArrayConversionError at trace time); stay in "
                "jnp, or fetch at the tally boundary",
                seen,
            )
            return
        if d in _TAINT_SYNC_BUILTINS and first_tainted:
            self._emit(
                node, "JL001",
                f"`{d}()` on a traced value forces concretization "
                "inside a traced body",
                seen,
            )
            return

        # JL005: mutating a module-level container under trace.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in index.module_names
            and node.func.value.id not in taint.traced
        ):
            self._emit(
                node, "JL005",
                f"`{node.func.value.id}.{node.func.attr}(...)` mutates "
                "module-level state inside a traced body (runs once at "
                "trace time)",
                seen,
            )

        # One-level helper resolution: a direct call to a same-module
        # function pulls that body into the traced context (depth 1).
        if depth >= 1 or not isinstance(node.func, ast.Name):
            return
        helper = index.resolve_in_scope(
            node.func.id, scope, node.lineno
        ) or index.resolve_function(node.func.id)
        if helper is None or isinstance(helper, ast.Lambda):
            return
        params = _func_params(helper)
        helper_taint: set[str] = set()
        for i, arg in enumerate(node.args):
            if i < len(params) and taint.expr_tainted(arg):
                helper_taint.add(params[i].arg)
        for kw in node.keywords:
            if kw.arg and taint.expr_tainted(kw.value):
                helper_taint.add(kw.arg)
        self._check_traced_function(
            helper, index.jit_specs.get(id(helper)), index, seen,
            taint_override=helper_taint, depth=depth + 1,
        )

    # -- JL003: donated-buffer reuse -------------------------------------
    def _check_donation(self, tree: ast.Module, index: _ModuleIndex) -> None:
        if not index.donating_names:
            return
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._check_donation_in(fn, index)

    def _check_donation_in(self, fn: ast.AST, index: _ModuleIndex) -> None:
        """Statement-ordered may-use-after-donate scan of one function
        (nested defs excluded — they get their own pass).

        Per statement, in source order: loads of already-donated names
        flag FIRST (so a donating call's own multi-line argument list
        never flags itself), then this statement's donations record,
        then its assignment targets clear — which makes the canonical
        rebind ``state = step(state, ...)`` clean by evaluation order
        rather than by line arithmetic.
        """
        donated: dict[str, int] = {}  # name -> donating call's line
        stmts = sorted(
            (n for n in _ModuleIndex._iter_scope_nodes(fn.body)
             if isinstance(n, ast.stmt)),
            key=lambda s: (s.lineno, s.col_offset),
        )
        for stmt in stmts:
            exprs = list(_iter_stmt_exprs(stmt))
            donations: list[tuple[str, int]] = []
            for node in exprs:
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)):
                    continue
                spec = index.donating_names.get(node.func.id)
                if spec is None:
                    continue
                for i in spec.donate_argnums:
                    if i < len(node.args) and isinstance(
                        node.args[i], ast.Name
                    ):
                        donations.append((node.args[i].id, node.lineno))
                for kw in node.keywords:
                    if kw.arg in spec.donate_argnames and isinstance(
                        kw.value, ast.Name
                    ):
                        donations.append((kw.value.id, node.lineno))
            for node in exprs:
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in donated
                ):
                    self.diags.append(
                        Diagnostic(
                            self.path, node.lineno, "JL003",
                            f"`{node.id}` was donated to a jitted call "
                            f"on line {donated[node.id]} "
                            "(donate_argnums); its device buffer is "
                            "dead — use the call's result instead",
                        )
                    )
                    del donated[node.id]  # one report per donation
            for name, line in donations:
                donated[name] = line
            for node in exprs:
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Store
                ):
                    donated.pop(node.id, None)

    # -- JL004: retrace-bait static defaults -----------------------------
    def _check_static_defaults(
        self, tree: ast.Module, index: _ModuleIndex
    ) -> None:
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            spec = index.jit_specs.get(id(fn))
            if spec is None:
                continue
            static = _static_param_names(fn, spec)
            defaults = list(fn.args.defaults)
            # positional defaults align to the TAIL of pos params
            pos_params = list(fn.args.posonlyargs) + list(fn.args.args)
            pairs = list(
                zip(pos_params[len(pos_params) - len(defaults):], defaults)
            )
            pairs += [
                (p, d)
                for p, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults)
                if d is not None
            ]
            for param, default in pairs:
                if param.arg not in static:
                    continue
                bad = None
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    bad = "an unhashable (list/dict/set) default"
                elif isinstance(default, ast.Call):
                    d = index.dotted(default.func)
                    if d and d.startswith(_ARRAY_MAKER_PREFIXES):
                        bad = f"an array default (`{d}`)"
                if bad:
                    self.diags.append(
                        Diagnostic(
                            self.path, default.lineno, "JL004",
                            f"static argument `{param.arg}` of jitted "
                            f"`{fn.name}` has {bad}: unhashable or "
                            "cache-key-unstable -> retrace bait; use a "
                            "tuple/scalar",
                        )
                    )

    # -- pragmas ---------------------------------------------------------
    def _comment_lines(self) -> list[tuple[int, str]]:
        """(line, text) of every COMMENT token — pragmas live in real
        comments only, so pragma examples inside docstrings/string
        literals (e.g. the rule docs themselves) are never parsed."""
        import io
        import tokenize

        try:
            return [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline
                )
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError,
                SyntaxError):  # pragma: no cover — parse already passed
            return list(enumerate(self.source.splitlines(), start=1))

    def _apply_pragmas(
        self, diags: list[Diagnostic]
    ) -> list[Diagnostic]:
        disabled: dict[int, set[str]] = {}
        out: list[Diagnostic] = []
        for i, text in self._comment_lines():
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            ids = {s.strip().upper() for s in m.group(1).split(",")}
            ids.discard("")
            justification = (m.group(2) or "").strip()
            if not justification:
                out.append(
                    Diagnostic(
                        self.path, i, "JL000",
                        "jaxlint pragma without a justification "
                        "(grammar: `# jaxlint: disable=JL00x -- why`); "
                        "the pragma is IGNORED",
                    )
                )
                continue
            unknown = ids - set(RULES)
            if unknown:
                out.append(
                    Diagnostic(
                        self.path, i, "JL000",
                        f"pragma names unknown rule(s) "
                        f"{sorted(unknown)}; known: "
                        f"{sorted(r for r in RULES if r != 'JL000')}",
                    )
                )
            disabled[i] = ids
        for d in diags:
            if d.rule in disabled.get(d.line, ()):  # justified pragma
                continue
            out.append(d)
        return out


def iter_python_files(paths: Iterable[str]) -> list[str]:
    """Every lintable file under ``paths``, fully deterministic:
    caches (``__pycache__``), VCS internals, and scratch dirs/files
    (``.tmp-*`` — editors and the A/B harnesses drop them) are
    pruned, the walk itself visits directories in sorted order, and
    the result is sorted — so ``--format json`` output is byte-stable
    across filesystems and readdir orders."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    dn for dn in dirnames
                    if dn not in ("__pycache__", ".git")
                    and not dn.startswith(".tmp-")
                )
                out.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py") and not f.startswith(".tmp-")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_source(source: str, path: str = "<string>") -> list[Diagnostic]:
    """Lint one source string (the unit the test corpus drives)."""
    return Analyzer(path, source).run()


def lint_paths(paths: Iterable[str]) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    for f in iter_python_files(paths):
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            diags.append(Diagnostic(f, 1, "JL000", f"unreadable: {e}"))
            continue
        diags.extend(lint_source(src, f))
    diags.sort(key=lambda d: (d.path, d.line, d.rule))
    return diags
