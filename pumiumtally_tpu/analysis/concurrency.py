"""jaxlint host-concurrency pass: rules JL301-JL303 (pure stdlib).

The service layer (``service/``, ``resilience/``) is the one part of
the engine where plain Python threading rules apply and where the
tests can only pin invariants with timing-sensitive scenarios. This
pass checks the three mechanical invariants statically:

- JL301 — instance state written from two different THREAD ROOTS
  where at least one write holds no lock. The roots are declared in
  ``THREAD_ROOTS`` below: per registered class, which methods are
  entered by which thread (the service worker loop, socket
  accept/connection threads, client calls, the signal-initiated drain
  path). Classes NOT in the registry are exempt by design — e.g.
  ``service/session.py``'s ``TallySession`` is documented as
  guarded-by the owning ``TallyService`` lock and holds no lock of
  its own.
- JL302 — lock-ordering cycles in the acquired-while-holding graph
  (nested ``with`` blocks, following one level of same-class method
  calls). Lock identity is ``ClassName.attr`` / module-level name;
  the graph is per-module.
- JL303 — unbounded blocking calls (`Future.result()`, `join()`,
  `queue.get()`, socket `recv`/`accept`, untimed `wait`) while a
  recognized lock is held. ``Condition.wait`` ON the held condition
  is exempt (it releases the lock), as is any call with a timeout.

Locks are attributes assigned ``threading.Lock/RLock/Condition/
Semaphore`` in the class body, or module-level names so assigned.
``__init__`` writes are exempt from JL301 (the object is not shared
until construction returns).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from pumiumtally_tpu.analysis.core import Diagnostic, _ModuleIndex

# Thread-root registry: class name -> {method: root kind}. The
# special key "*public*" declares every public (non-underscore)
# method not otherwise listed as entered by that root kind. Only
# classes listed here are analyzed for JL301 — declaring the roots is
# the contract that makes "written from >= 2 roots" decidable.
THREAD_ROOTS: dict[str, dict[str, str]] = {
    # The multi-session service: ONE worker thread owns device work;
    # client threads call the public API; the signal dispatcher
    # (resilience.install_drain_owner) trips the drain flag via
    # request_drain semantics.
    "TallyService": {
        "_worker_loop": "worker",
        "request_drain": "signal-dispatcher",
        "*public*": "client",
    },
    # Socket frontends: an accept-loop thread spawns one thread per
    # connection; stop()/start() come from the owning (client) thread.
    "SocketFrontend": {
        "_accept_loop": "accept-thread",
        "_serve_conn": "connection-thread",
        "*public*": "client",
    },
    "SessionRouter": {
        "_accept_loop": "accept-thread",
        "_serve_conn": "connection-thread",
        "*public*": "client",
    },
}

_LOCK_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
}

# Mutating container methods (shared shape with core's JL005 set).
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "sort", "reverse",
}

_BLOCKING_METHODS = {"result", "join", "get", "wait", "wait_for"}
_SOCKET_METHODS = {"recv", "recv_into", "recvfrom", "accept"}


def _is_lock_ctor(index: _ModuleIndex, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = index.dotted(node.func)
    return bool(d) and d.split(".")[-1] in _LOCK_CTORS and (
        d.startswith("threading.") or d.startswith("multiprocessing.")
    )


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class _Write:
    line: int
    attr: str
    locked: bool  # lexically under a recognized lock at the site


@dataclasses.dataclass
class _SelfCall:
    method: str
    locked: bool
    held: tuple[str, ...]
    line: int


@dataclasses.dataclass
class _Blocking:
    line: int
    desc: str
    held: tuple[str, ...]  # lock ids held at the call


@dataclasses.dataclass
class _MethodFacts:
    writes: list[_Write]
    calls: list[_SelfCall]
    edges: list[tuple[str, str, int]]  # (held, acquired, line)
    acquires: list[str]
    blocking: list[_Blocking]


class _ClassScan:
    """Per-class lock inventory + per-method facts."""

    def __init__(self, cls: ast.ClassDef, index: _ModuleIndex,
                 module_locks: set[str]) -> None:
        self.cls = cls
        self.index = index
        self.module_locks = module_locks
        self.methods: dict[str, ast.FunctionDef] = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: set[str] = set()
        for n in ast.walk(cls):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    attr = _self_attr(t)
                    if attr and _is_lock_ctor(index, n.value):
                        self.lock_attrs.add(attr)
        self.facts: dict[str, _MethodFacts] = {
            name: self._scan_method(m)
            for name, m in self.methods.items()
        }

    # -- lock identity ---------------------------------------------------
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.lock_attrs:
            return f"{self.cls.name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return expr.id
        return None

    # -- method scan -----------------------------------------------------
    def _scan_method(self, fn: ast.FunctionDef) -> _MethodFacts:
        facts = _MethodFacts([], [], [], [], [])

        def visit(stmts: list, held: tuple[str, ...]) -> None:
            for s in stmts:
                if isinstance(s, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    # Nested defs (callbacks) run later, with held
                    # state unknown: scan with no lock held.
                    visit(s.body, ())
                    continue
                if isinstance(s, ast.With):
                    acquired = []
                    inner = held
                    for item in s.items:
                        lid = self._lock_id(item.context_expr)
                        if lid is not None:
                            for h in inner:
                                facts.edges.append((h, lid, s.lineno))
                            facts.acquires.append(lid)
                            acquired.append(lid)
                            inner = inner + (lid,)
                    self._scan_exprs(s, inner, facts)
                    visit(s.body, inner)
                    continue
                self._scan_exprs(s, held, facts)
                for field in ("body", "orelse", "finalbody"):
                    visit(getattr(s, field, []) or [], held)
                for h in getattr(s, "handlers", []) or []:
                    visit(h.body, held)
        visit(fn.body, ())
        return facts

    def _scan_exprs(self, stmt: ast.stmt, held: tuple[str, ...],
                    facts: _MethodFacts) -> None:
        locked = bool(held)
        # Attribute writes (assignment, augmented, container element).
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            base = t
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                attr = _self_attr(base)
                if attr is not None:
                    facts.writes.append(
                        _Write(stmt.lineno, attr, locked)
                    )
                    break
                base = base.value
        for n in _own_exprs(stmt):
            if not isinstance(n, ast.Call):
                continue
            # self.method(...) calls (for reachability + lock context).
            if isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == "self" and \
                    n.func.attr in self.methods:
                facts.calls.append(
                    _SelfCall(n.func.attr, locked, held, n.lineno)
                )
                continue
            # self.attr.append(...) container mutators.
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _MUTATORS:
                attr = _self_attr(n.func.value)
                if attr is not None:
                    facts.writes.append(
                        _Write(n.lineno, attr, locked)
                    )
            if locked:
                desc = self._blocking_desc(n, held)
                if desc:
                    facts.blocking.append(
                        _Blocking(n.lineno, desc, held)
                    )

    def _blocking_desc(self, call: ast.Call,
                       held: tuple[str, ...]) -> Optional[str]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            d = self.index.dotted(f)
            if d == "time.sleep":
                return "time.sleep"
            return None
        name = f.attr
        has_timeout = bool(call.args) or any(
            kw.arg == "timeout" for kw in call.keywords
        )
        if name in _SOCKET_METHODS:
            return f".{name}()"
        if name not in _BLOCKING_METHODS:
            return None
        if name == "wait_for":
            has_timeout = len(call.args) > 1 or any(
                kw.arg == "timeout" for kw in call.keywords
            )
        if has_timeout:
            return None
        if name in ("wait", "wait_for"):
            # Condition.wait on the HELD condition releases the lock.
            lid = self._lock_id(f.value)
            if lid is not None and lid in held:
                return None
        if name == "get" and call.keywords:
            return None  # q.get(block=...) variants: assume bounded
        return f".{name}()"


def _own_exprs(stmt: ast.stmt):
    """Expression nodes of one statement, excluding nested statement
    bodies and nested defs (same contract as core._iter_stmt_exprs)."""
    stack: list[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        vs = value if isinstance(value, list) else [value]
        stack.extend(v for v in vs if isinstance(v, ast.AST))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _root_methods(scan: _ClassScan, registry: dict[str, str]
                  ) -> dict[str, str]:
    roots: dict[str, str] = {}
    for name, kind in registry.items():
        if name == "*public*":
            continue
        if name in scan.methods:
            roots[name] = kind
    public_kind = registry.get("*public*")
    if public_kind:
        for name in scan.methods:
            if not name.startswith("_") and name not in roots:
                roots[name] = public_kind
    return roots


def _check_shared_state(scan: _ClassScan, roots: dict[str, str],
                        path: str, diags: list[Diagnostic]) -> None:
    # Reachability over (method, called-with-lock-held) states.
    # attr -> root kinds that can write it; and the unlocked write
    # sites reachable with no lock held anywhere on the call chain.
    writers: dict[str, set[str]] = {}
    unsafe: dict[str, set[tuple[int, str]]] = {}
    for root, kind in roots.items():
        seen: set[tuple[str, bool]] = set()
        stack: list[tuple[str, bool]] = [(root, False)]
        while stack:
            method, held = stack.pop()
            if (method, held) in seen:
                continue
            seen.add((method, held))
            facts = scan.facts.get(method)
            if facts is None:
                continue
            for w in facts.writes:
                writers.setdefault(w.attr, set()).add(kind)
                if not w.locked and not held:
                    unsafe.setdefault(w.attr, set()).add(
                        (w.line, kind)
                    )
            for c in facts.calls:
                stack.append((c.method, held or c.locked))
    for attr, kinds in sorted(writers.items()):
        if len(kinds) < 2:
            continue
        for line, kind in sorted(unsafe.get(attr, ())):
            diags.append(Diagnostic(
                path, line, "JL301",
                f"`self.{attr}` is written from multiple thread roots "
                f"({', '.join(sorted(kinds))}) but this "
                f"{kind}-root write holds no lock "
                f"(locks: {sorted(scan.lock_attrs) or 'none'})",
            ))


def _check_lock_order(edges: list[tuple[str, str, int]], path: str,
                      diags: list[Diagnostic]) -> None:
    graph: dict[str, set[str]] = {}
    edge_line: dict[tuple[str, str], int] = {}
    for a, b, line in edges:
        if a == b:
            continue  # re-entrant acquire (RLock/Condition pair)
        graph.setdefault(a, set()).add(b)
        edge_line.setdefault((a, b), line)

    reported: set[frozenset] = set()

    def dfs(start: str) -> Optional[list[str]]:
        stack = [(start, [start])]
        while stack:
            node, trail = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    return trail + [start]
                if nxt not in trail:
                    stack.append((nxt, trail + [nxt]))
        return None

    for start in sorted(graph):
        cycle = dfs(start)
        if cycle is None:
            continue
        key = frozenset(cycle)
        if key in reported:
            continue
        reported.add(key)
        line = min(
            edge_line.get((cycle[i], cycle[i + 1]), 1)
            for i in range(len(cycle) - 1)
        )
        diags.append(Diagnostic(
            path, line, "JL302",
            "lock-ordering cycle: " + " -> ".join(cycle)
            + "; pick one global acquisition order",
        ))


def check(tree: ast.Module, index: _ModuleIndex, path: str
          ) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    module_locks = {
        t.id
        for stmt in tree.body
        if isinstance(stmt, ast.Assign)
        for t in stmt.targets
        if isinstance(t, ast.Name) and _is_lock_ctor(index, stmt.value)
    }
    all_edges: list[tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        scan = _ClassScan(node, index, module_locks)
        for facts in scan.facts.values():
            all_edges.extend(facts.edges)
            # One level of same-class calls: locks the callee acquires
            # while the caller holds others form ordering edges too.
            for c in facts.calls:
                callee = scan.facts.get(c.method)
                if callee is None:
                    continue
                for a in c.held:
                    for b2 in callee.acquires:
                        all_edges.append((a, b2, c.line))
            for b in facts.blocking:
                diags.append(Diagnostic(
                    path, b.line, "JL303",
                    f"blocking call `{b.desc}` while holding "
                    f"{', '.join(sorted(set(b.held)))}; waits belong "
                    "outside the lock (the worker needs it to make "
                    "progress)",
                ))
        registry = THREAD_ROOTS.get(node.name)
        if registry:
            roots = _root_methods(scan, registry)
            _check_shared_state(scan, roots, path, diags)
    _check_lock_order(all_edges, path, diags)
    return diags
