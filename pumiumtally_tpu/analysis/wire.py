"""Wire-protocol auditor for the NDJSON-TCP service (pure stdlib).

``python -m pumiumtally_tpu.analysis --wire`` — the contracts.py
sibling for the socket surface. The NDJSON protocol has exactly one
authority: ``SocketFrontend._dispatch`` in service/server.py, whose
op allowlist, required request fields, and per-op reply dictionaries
ARE the schema (plus the ``SessionRouter`` augmentations: the
fleet-shape ping reply and the ``home``-qualified open reply). Every
other file that speaks the protocol — the load generator, the test
driver, the service examples, the router's own forwarded pings — is
an ENCODER that can silently drift: an op renamed on the server turns
a client loop into a flaky socket test instead of a CI failure.

This module AST-extracts the schema from the server (never importing
it — the package imports jax) and cross-checks every encoder:

* request dicts (any dict literal with a literal ``"op"`` key,
  including keys added later via ``d["k"] = v`` in the same scope)
  must name a known op and carry that op's required fields
  (``MISSING-FIELD`` / ``UNKNOWN-OP``);
* reply reads (``r["k"]`` / ``r.get("k")`` on a name bound from a
  call that was handed a request dict) must name a key the server can
  actually send for that op — the op's reply schema, the structured
  error reply, or a router augmentation (``REPLY-DRIFT``);
* every encoder file the audit is pinned to must exist
  (``MISSING-ENCODER`` — deleting the load generator doesn't silently
  shrink the audit).

Best-effort static reasoning with the usual no-false-positive bias:
a request whose op is not a string literal, or a reply bound from a
call whose request cannot be traced, is counted (``dynamic``) but
never guessed at. Exit 0 = every encoder speaks the server's
protocol; exit 1 = any finding (CI fails on drift).
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

#: The single source of truth for the protocol.
SERVER_FILE = "pumiumtally_tpu/service/server.py"

#: Every file that encodes wire requests / decodes wire replies.
#: server.py audits itself: the SessionRouter originates ping
#: requests over the same protocol it forwards.
ENCODER_FILES = (
    "pumiumtally_tpu/service/server.py",
    "tools/loadgen.py",
    "tests/_service_driver.py",
    "examples/multi_client_service.py",
)


def repo_root() -> str:
    """The repository root (the dir holding ``pumiumtally_tpu/``)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dict_keys(node: ast.Dict) -> Optional[Set[str]]:
    """Literal string keys of a dict display; None when any key is
    dynamic (then the dict cannot be schema-checked)."""
    keys: Set[str] = set()
    for k in node.keys:
        s = None if k is None else _const_str(k)
        if s is None:
            return None
        keys.add(s)
    return keys


def _dict_op(node: ast.Dict) -> Tuple[bool, Optional[str]]:
    """(is_request, op): is_request when the dict has an ``"op"``
    key; op is its literal value or None when dynamic."""
    for k, v in zip(node.keys, node.values):
        if k is not None and _const_str(k) == "op":
            return True, _const_str(v)
    return False, None


# ---------------------------------------------------------------------------
# Server-side schema extraction


@dataclass
class _Schema:
    ops: Set[str] = field(default_factory=set)
    required: Dict[str, Set[str]] = field(default_factory=dict)
    replies: Dict[str, Set[str]] = field(default_factory=dict)
    error_keys: Set[str] = field(default_factory=set)


def _test_ops(test: ast.expr) -> Optional[List[str]]:
    """ops named by ``op == "x"`` / ``op in ("a", "b")``, else None."""
    if not (
        isinstance(test, ast.Compare)
        and isinstance(test.left, ast.Name)
        and test.left.id == "op"
        and len(test.ops) == 1
    ):
        return None
    cmp = test.comparators[0]
    if isinstance(test.ops[0], ast.Eq):
        s = _const_str(cmp)
        return [s] if s is not None else None
    if isinstance(test.ops[0], ast.In) and isinstance(
        cmp, (ast.Tuple, ast.List)
    ):
        vals = [_const_str(e) for e in cmp.elts]
        if all(v is not None for v in vals):
            return list(vals)
    return None


def _allowlist_ops(test: ast.expr) -> Optional[List[str]]:
    """ops named by the ``op not in (...)`` guard, else None."""
    if (
        isinstance(test, ast.Compare)
        and isinstance(test.left, ast.Name)
        and test.left.id == "op"
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.NotIn)
        and isinstance(test.comparators[0], (ast.Tuple, ast.List))
    ):
        vals = [_const_str(e) for e in test.comparators[0].elts]
        if all(v is not None for v in vals):
            return list(vals)
    return None


def _return_key_union(fn: ast.AST) -> Set[str]:
    """Union of literal dict keys over every ``return {...}`` in a
    helper (``_ack``/``_sync``)."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(
            node.value, ast.Dict
        ):
            keys.update(_dict_keys(node.value) or set())
    return keys


def _extract_dispatch(
    fn: ast.FunctionDef, methods: Dict[str, ast.FunctionDef]
) -> _Schema:
    """Walk ``_dispatch``'s op-branch chain: required ``req[...]``
    fields and reply dict keys per op; fields/replies outside any op
    branch are shared (the post-allowlist ``req["session"]`` and the
    fall-through close reply)."""
    schema = _Schema()
    allow: List[str] = []
    branch_ops: Set[str] = set()
    shared_required: Set[str] = set()
    shared_replies: List[Set[str]] = []
    var_keys: Dict[object, Dict[str, Set[str]]] = {}

    def reply_of(value: ast.expr, label) -> Optional[Set[str]]:
        if isinstance(value, ast.Dict):
            return _dict_keys(value)
        if isinstance(value, ast.Name):
            for lab in (label, None):
                got = var_keys.get(lab, {}).get(value.id)
                if got is not None:
                    return got
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and isinstance(value.func.value, ast.Name)
            and value.func.value.id == "self"
            and value.func.attr in methods
        ):
            return _return_key_union(methods[value.func.attr])
        return None

    def record(stmt: ast.stmt, label) -> None:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == "req"
                and isinstance(node.ctx, ast.Load)
            ):
                key = _const_str(node.slice)
                if key is not None:
                    if label is None:
                        shared_required.add(key)
                    else:
                        for op in label:
                            schema.required.setdefault(
                                op, set()
                            ).add(key)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and isinstance(
                    node.value, ast.Dict
                ):
                    keys = _dict_keys(node.value)
                    if keys is not None:
                        var_keys.setdefault(label, {})[t.id] = keys
                elif (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                ):
                    key = _const_str(t.slice)
                    if key is not None:
                        for lab in (label, None):
                            got = var_keys.get(lab, {}).get(t.value.id)
                            if got is not None:
                                got.add(key)
                                break
            elif isinstance(node, ast.Return) and node.value is not None:
                keys = reply_of(node.value, label)
                if keys is None:
                    continue
                if label is None:
                    shared_replies.append(set(keys))
                else:
                    for op in label:
                        schema.replies.setdefault(
                            op, set()
                        ).update(keys)

    def visit(stmts: List[ast.stmt], label) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                a_ops = _allowlist_ops(stmt.test)
                t_ops = _test_ops(stmt.test)
                if a_ops is not None:
                    allow.extend(a_ops)
                    visit(stmt.orelse, label)
                elif t_ops is not None:
                    branch_ops.update(t_ops)
                    visit(stmt.body, tuple(t_ops))
                    visit(stmt.orelse, label)
                else:
                    record(stmt, label)
            else:
                record(stmt, label)

    visit(list(fn.body), None)
    schema.ops = branch_ops | set(allow)
    for op in allow:
        schema.required.setdefault(op, set()).update(shared_required)
        # The fall-through reply belongs to allowlist ops with no
        # branch of their own (today: "close").
        if op not in schema.replies:
            for keys in shared_replies:
                schema.replies.setdefault(op, set()).update(keys)
    return schema


def _extract_router(fn: ast.FunctionDef, schema: _Schema) -> None:
    """Fold ``SessionRouter._route`` into the schema: its own reply
    shapes (fleet ping) and reply augmentations (``dict(reply,
    session=..., home=...)`` on open) widen what a client may read."""

    def visit(stmts: List[ast.stmt], label) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                t_ops = _test_ops(stmt.test)
                if t_ops is not None:
                    visit(stmt.body, tuple(t_ops))
                    visit(stmt.orelse, label)
                    continue
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Dict)
                    and label is not None
                ):
                    keys = _dict_keys(node.value)
                    if keys:
                        for op in label:
                            schema.replies.setdefault(
                                op, set()
                            ).update(keys)
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "dict"
                    and node.args
                    and label is not None
                ):
                    extra = {
                        kw.arg for kw in node.keywords
                        if kw.arg is not None
                    }
                    if extra:
                        for op in label:
                            schema.replies.setdefault(
                                op, set()
                            ).update(extra)

    visit(list(fn.body), None)


def _extract_error_keys(tree: ast.Module) -> Set[str]:
    """Keys of the structured error reply: any dict literal carrying
    both "ok" and "error" (the _serve_conn except arm)."""
    keys: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            dk = _dict_keys(node)
            if dk and "ok" in dk and "error" in dk:
                keys.update(dk)
    return keys


def _extract_schema(server_path: str) -> Optional[_Schema]:
    try:
        with open(server_path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=server_path)
    except (OSError, SyntaxError):
        return None
    dispatch = None
    route = None
    methods: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    if item.name == "_dispatch":
                        dispatch = item
                        methods = {
                            m.name: m for m in node.body
                            if isinstance(m, ast.FunctionDef)
                        }
                    elif item.name == "_route":
                        route = item
    if dispatch is None:
        return None
    schema = _extract_dispatch(dispatch, methods)
    if route is not None:
        _extract_router(route, schema)
    schema.error_keys = _extract_error_keys(tree)
    return schema


# ---------------------------------------------------------------------------
# Encoder-side audit


def _scopes(tree: ast.Module):
    """(name, stmts) per lexical scope, nested defs excluded from the
    enclosing scope so each request/reply name binds once."""
    defs = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.append(node)
    module_stmts = [
        s for s in tree.body
        if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
    ]
    yield "<module>", module_stmts
    for d in defs:
        yield d.name, list(d.body)


def _scope_nodes(stmts: List[ast.stmt]):
    """Every node under ``stmts`` except inside nested defs, in
    source order."""
    out = []
    stack = list(reversed(stmts))
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            stack.append(child)
    out.sort(key=lambda n: (
        getattr(n, "lineno", 0), getattr(n, "col_offset", 0)
    ))
    return out


@dataclass
class _Request:
    op: Optional[str]
    keys: Set[str]
    line: int


def _request_arg_op(
    call: ast.Call, env_req: Dict[str, _Request]
) -> Optional[str]:
    """The op of the request dict handed to ``call``, when any
    argument is an inline request dict or a tracked request name."""
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Dict):
            is_req, op = _dict_op(a)
            if is_req:
                return op
        if isinstance(a, ast.Name) and a.id in env_req:
            return env_req[a.id].op
    return None


def _audit_encoder(
    path: str, rel: str, schema: _Schema
) -> Tuple[dict, List[dict]]:
    findings: List[dict] = []
    stats = {"path": rel, "requests": 0, "reply_reads": 0,
             "dynamic": 0}
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except OSError:
        findings.append({
            "kind": "MISSING-ENCODER",
            "path": rel,
            "line": 0,
            "message": (
                f"pinned encoder file {rel} is missing: the wire "
                "audit set silently shrank — restore the file or "
                "update ENCODER_FILES"
            ),
        })
        return stats, findings
    except SyntaxError as e:
        findings.append({
            "kind": "MISSING-ENCODER",
            "path": rel,
            "line": int(e.lineno or 0),
            "message": f"encoder file {rel} failed to parse: {e.msg}",
        })
        return stats, findings

    def check_read(op: Optional[str], key: str, line: int) -> None:
        stats["reply_reads"] += 1
        if op is None or op not in schema.replies:
            return
        allowed = (
            schema.replies[op] | schema.error_keys | {"ok"}
        )
        if key not in allowed:
            findings.append({
                "kind": "REPLY-DRIFT",
                "path": rel,
                "line": line,
                "message": (
                    f"reads reply key {key!r} of op {op!r}, which "
                    f"the server never sends (reply schema: "
                    f"{sorted(allowed)})"
                ),
            })

    for _scope_name, stmts in _scopes(tree):
        env_req: Dict[str, _Request] = {}
        env_reply: Dict[str, Optional[str]] = {}
        requests: List[_Request] = []
        seen_dicts: Set[int] = set()
        for node in _scope_nodes(stmts):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and isinstance(
                    node.value, ast.Dict
                ):
                    is_req, op = _dict_op(node.value)
                    if is_req:
                        r = _Request(
                            op,
                            _dict_keys(node.value) or set(),
                            node.value.lineno,
                        )
                        env_req[t.id] = r
                        requests.append(r)
                        seen_dicts.add(id(node.value))
                        env_reply.pop(t.id, None)
                        continue
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in env_req
                ):
                    key = _const_str(t.slice)
                    if key is not None:
                        env_req[t.value.id].keys.add(key)
                if isinstance(t, ast.Name) and isinstance(
                    node.value, ast.Call
                ):
                    op = _request_arg_op(node.value, env_req)
                    if op is not None or any(
                        isinstance(a, ast.Dict) and _dict_op(a)[0]
                        for a in node.value.args
                    ):
                        env_reply[t.id] = op
                        env_req.pop(t.id, None)
            elif isinstance(node, ast.Dict):
                if id(node) in seen_dicts:
                    continue
                is_req, op = _dict_op(node)
                if is_req:
                    requests.append(_Request(
                        op, _dict_keys(node) or set(), node.lineno
                    ))
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in env_reply
                and isinstance(node.ctx, ast.Load)
            ):
                key = _const_str(node.slice)
                if key is not None:
                    check_read(
                        env_reply[node.value.id], key, node.lineno
                    )
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
            ):
                key = _const_str(node.args[0])
                if key is None:
                    continue
                base = node.func.value
                if (
                    isinstance(base, ast.Name)
                    and base.id in env_reply
                ):
                    check_read(
                        env_reply[base.id], key, node.lineno
                    )
                elif isinstance(base, ast.Call):
                    op = _request_arg_op(base, env_req)
                    if op is not None:
                        check_read(op, key, node.lineno)
        for r in requests:
            stats["requests"] += 1
            if r.op is None:
                stats["dynamic"] += 1
                continue
            if r.op not in schema.ops:
                findings.append({
                    "kind": "UNKNOWN-OP",
                    "path": rel,
                    "line": r.line,
                    "message": (
                        f"encodes unknown op {r.op!r} (server "
                        f"allowlist: {sorted(schema.ops)})"
                    ),
                })
                continue
            missing = sorted(
                schema.required.get(r.op, set()) - r.keys
            )
            if missing:
                findings.append({
                    "kind": "MISSING-FIELD",
                    "path": rel,
                    "line": r.line,
                    "message": (
                        f"op {r.op!r} request is missing required "
                        f"field(s) {missing} — the server raises "
                        "KeyError (error reply) on every send"
                    ),
                })
    return stats, findings


# ---------------------------------------------------------------------------
# Entry point + renderers


def audit_wire(root: Optional[str] = None) -> Tuple[dict, int]:
    """Cross-check every pinned encoder against the AST-extracted
    ``SocketFrontend``/``SessionRouter`` wire schema. Returns
    (report, exit_code): 0 = every encoder speaks the server's
    protocol, 1 = any finding."""
    root = root or repo_root()
    server_path = os.path.join(root, SERVER_FILE)
    schema = _extract_schema(server_path)
    findings: List[dict] = []
    encoders: List[dict] = []
    if schema is None or not schema.ops:
        findings.append({
            "kind": "NO-SERVER",
            "path": SERVER_FILE,
            "line": 0,
            "message": (
                f"could not extract the wire schema from "
                f"{SERVER_FILE} (missing file or no _dispatch op "
                "chain) — the protocol has no authority to audit "
                "against"
            ),
        })
    else:
        for rel in ENCODER_FILES:
            stats, f = _audit_encoder(
                os.path.join(root, rel), rel, schema
            )
            encoders.append(stats)
            findings.extend(f)
    findings.sort(
        key=lambda f: (f["path"], f["line"], f["kind"])
    )
    report = {
        "server": {
            "path": SERVER_FILE,
            "ops": sorted(schema.ops) if schema else [],
            "required": {
                op: sorted(v)
                for op, v in (schema.required if schema else {}).items()
            },
            "replies": {
                op: sorted(v)
                for op, v in (schema.replies if schema else {}).items()
            },
            "error_keys": sorted(
                schema.error_keys if schema else []
            ),
        },
        "encoders": encoders,
        "findings": findings,
    }
    return report, (1 if findings else 0)


def render_text(report: dict) -> str:
    lines = []
    srv = report["server"]
    lines.append(
        f"wire protocol ({srv['path']}): {len(srv['ops'])} op(s)"
    )
    lines.append("  " + ", ".join(srv["ops"]))
    lines.append(
        f"  error reply keys: {', '.join(srv['error_keys'])}"
    )
    lines.append("")
    grid = [["encoder", "requests", "reply reads", "dynamic"]]
    for enc in report["encoders"]:
        grid.append([
            enc["path"],
            str(enc["requests"]),
            str(enc["reply_reads"]),
            str(enc["dynamic"]),
        ])
    widths = [max(len(r[i]) for r in grid) for i in range(len(grid[0]))]
    for i, r in enumerate(grid):
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        )
        if i == 0:
            lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    lines.append("")
    if report["findings"]:
        for f in report["findings"]:
            lines.append(
                f"{f['kind']}: {f['path']}:{f['line']} — "
                f"{f['message']}"
            )
    else:
        lines.append("every encoder speaks the server's protocol")
    return "\n".join(lines)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True)
