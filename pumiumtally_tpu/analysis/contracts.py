"""Facade contract auditor (``python -m pumiumtally_tpu.analysis
--contracts``).

The package ships five user-facing tally facades (ROADMAP item 5):

    monolithic              PumiTally                     api/tally.py
    sharded                 PumiTally(device_mesh=...)    api/tally.py
    streaming               StreamingTally                api/streaming.py
    partitioned             PartitionedPumiTally          api/partitioned.py
    streaming_partitioned   StreamingPartitionedTally     api/streaming.py

All five must implement the same hook surface — the points where the
service layer, checkpointing, and batch fusion attach:

    batch-close       close_batch
    move-end          MoveToNextLocation
    checkpoint-rows   checkpoint_now
    lane-bank         score_bank
    fusion-key        _fusion_key

Like the rest of jaxlint this auditor is pure stdlib-AST: the api
modules import jax, so they are parsed, never imported.  For every
(facade, hook) cell it reports where the hook is defined (inherited
vs overridden, with file:line) and whether an override's signature is
compatible with the base definition.  Compatible means: identical
parameter names/order/default-ness, or the base parameter list
extended only by trailing defaulted parameters.  Anything else is
rendered as ``DRIFT`` — informational (exit 0); a MISSING hook is a
contract break (exit 1).

The audit also cross-checks ``utils/checkpoint.py::_engine_kind``:
every facade kind must be dispatchable so checkpoint state rows carry
the right engine tag.  ``sharded`` is the monolithic class with a
device mesh, so it shares the ``monolithic`` kind.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: facade name -> (class name, module path relative to package root).
FACADES: List[Tuple[str, str, str]] = [
    ("monolithic", "PumiTally", "api/tally.py"),
    ("sharded", "PumiTally", "api/tally.py"),
    ("streaming", "StreamingTally", "api/streaming.py"),
    ("partitioned", "PartitionedPumiTally", "api/partitioned.py"),
    (
        "streaming_partitioned",
        "StreamingPartitionedTally",
        "api/streaming.py",
    ),
]

#: hook surface: (contract point, method name).
HOOKS: List[Tuple[str, str]] = [
    ("batch-close", "close_batch"),
    ("move-end", "MoveToNextLocation"),
    ("checkpoint-rows", "checkpoint_now"),
    ("lane-bank", "score_bank"),
    ("fusion-key", "_fusion_key"),
]

#: facade -> the tag _engine_kind must be able to produce for it.
ENGINE_KINDS = {
    "monolithic": "monolithic",
    "sharded": "monolithic",  # same class, mesh-selected arm
    "streaming": "streaming",
    "partitioned": "partitioned",
    "streaming_partitioned": "streaming_partitioned",
}

_API_MODULES = ("api/tally.py", "api/streaming.py", "api/partitioned.py")


def package_root() -> str:
    """Repo-relative package dir, valid from any cwd."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# AST harvest


@dataclass(frozen=True)
class _Method:
    cls: str
    module: str  # path relative to the package root
    line: int
    args: ast.arguments
    is_property: bool


@dataclass
class _Class:
    name: str
    module: str
    line: int
    bases: List[str]
    methods: Dict[str, _Method]


def _harvest(root: str) -> Dict[str, _Class]:
    classes: Dict[str, _Class] = {}
    for rel in _API_MODULES:
        path = os.path.join(root, rel)
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            methods: Dict[str, _Method] = {}
            for item in node.body:
                if not isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                is_prop = any(
                    (isinstance(d, ast.Name) and d.id == "property")
                    or (
                        isinstance(d, ast.Attribute)
                        and d.attr in ("setter", "getter")
                    )
                    for d in item.decorator_list
                )
                if item.name in methods and not is_prop:
                    continue  # keep the getter for properties
                methods[item.name] = _Method(
                    cls=node.name,
                    module=rel,
                    line=item.lineno,
                    args=item.args,
                    is_property=is_prop,
                )
            bases = []
            for b in node.bases:
                if isinstance(b, ast.Name):
                    bases.append(b.id)
                elif isinstance(b, ast.Attribute):
                    bases.append(b.attr)
            classes[node.name] = _Class(
                name=node.name,
                module=rel,
                line=node.lineno,
                bases=bases,
                methods=methods,
            )
    return classes


def _mro(classes: Dict[str, _Class], name: str) -> List[_Class]:
    """Linear base chain (the facade hierarchy is single-inheritance)."""
    chain: List[_Class] = []
    seen = set()
    while name in classes and name not in seen:
        seen.add(name)
        cls = classes[name]
        chain.append(cls)
        name = cls.bases[0] if cls.bases else ""
    return chain


def _find_hook(
    classes: Dict[str, _Class], facade_cls: str, method: str
) -> Optional[_Method]:
    for cls in _mro(classes, facade_cls):
        if method in cls.methods:
            return cls.methods[method]
    return None


# ---------------------------------------------------------------------------
# Signature compatibility


def _sig_shape(args: ast.arguments) -> List[Tuple[str, bool]]:
    """(name, has_default) per positional param, ``self`` dropped;
    vararg/kwonly params appended with sentinel markers."""
    pos = list(args.posonlyargs) + list(args.args)
    n_default = len(args.defaults)
    shape: List[Tuple[str, bool]] = []
    for i, a in enumerate(pos):
        if i == 0 and a.arg in ("self", "cls"):
            continue
        shape.append((a.arg, i >= len(pos) - n_default))
    if args.vararg is not None:
        shape.append(("*" + args.vararg.arg, True))
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        shape.append((a.arg, d is not None))
    if args.kwarg is not None:
        shape.append(("**" + args.kwarg.arg, True))
    return shape


def _compat(base: ast.arguments, override: ast.arguments) -> str:
    """'ok' | 'ok(+extras)' | 'DRIFT'."""
    b, o = _sig_shape(base), _sig_shape(override)
    if b == o:
        return "ok"
    if len(o) > len(b) and o[: len(b)] == b and all(
        d for _, d in o[len(b):]
    ):
        return "ok(+extras)"
    return "DRIFT"


# ---------------------------------------------------------------------------
# _engine_kind coverage


def _engine_kinds_dispatched(root: str) -> set:
    path = os.path.join(root, "utils/checkpoint.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "_engine_kind"
        ):
            return {
                n.value.value
                for n in ast.walk(node)
                if isinstance(n, ast.Return)
                and isinstance(n.value, ast.Constant)
                and isinstance(n.value.value, str)
            }
    return set()


# ---------------------------------------------------------------------------
# Audit


def audit_contracts(root: Optional[str] = None) -> Tuple[dict, int]:
    """Returns (report, exit_code): 0 clean/drift-only, 1 contract
    break (missing hook or undispatchable engine kind)."""
    root = root or package_root()
    classes = _harvest(root)
    kinds = _engine_kinds_dispatched(root)
    exit_code = 0
    rows = []
    for facade, cls_name, module in FACADES:
        if cls_name not in classes:
            rows.append(
                {"facade": facade, "class": cls_name, "module": module,
                 "error": "class not found"}
            )
            exit_code = 1
            continue
        hooks = {}
        base_cls = _mro(classes, cls_name)[-1].name
        for point, method in HOOKS:
            m = _find_hook(classes, cls_name, method)
            if m is None:
                hooks[point] = {"method": method, "status": "MISSING"}
                exit_code = 1
                continue
            base_def = _find_hook(classes, base_cls, method)
            if m.cls == cls_name and base_def is not None and (
                base_def.cls != cls_name
            ):
                status = "override:" + _compat(base_def.args, m.args)
            elif m.cls == cls_name:
                status = "defines"
            else:
                status = "inherit"
            hooks[point] = {
                "method": method,
                "status": status,
                "defined_in": "%s:%d" % (m.module, m.line),
                "class": m.cls,
            }
        kind = ENGINE_KINDS[facade]
        kind_ok = kind in kinds
        if not kind_ok:
            exit_code = 1
        rows.append(
            {
                "facade": facade,
                "class": cls_name,
                "module": module,
                "engine_kind": kind,
                "engine_kind_dispatched": kind_ok,
                "hooks": hooks,
            }
        )
    report = {
        "facades": rows,
        "hook_points": [p for p, _ in HOOKS],
        "engine_kinds_dispatched": sorted(kinds),
    }
    return report, exit_code


def render_text(report: dict) -> str:
    points = report["hook_points"]
    grid = [["facade"] + points + ["engine-kind"]]
    for row in report["facades"]:
        if "error" in row:
            grid.append(
                [row["facade"], "!! " + row["error"]]
                + [""] * len(points)
            )
            continue
        cells = [row["facade"]]
        for p in points:
            h = row["hooks"][p]
            if h["status"] == "MISSING":
                cells.append("MISSING")
            else:
                cells.append(
                    "%s %s"
                    % (h["status"], h["defined_in"].split("/")[-1])
                )
        kind = row["engine_kind"]
        cells.append(
            kind if row["engine_kind_dispatched"] else kind + "(!)"
        )
        grid.append(cells)
    widths = [
        max(len(r[i]) for r in grid) for i in range(len(grid[0]))
    ]
    lines = []
    for i, r in enumerate(grid):
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        )
        if i == 0:
            lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    lines.append("")
    lines.append(
        "_engine_kind dispatches: %s"
        % ", ".join(report["engine_kinds_dispatched"])
    )
    return "\n".join(lines)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True)
