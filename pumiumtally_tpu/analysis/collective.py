"""jaxlint collective-safety pass: rules JL101-JL104 (pure stdlib).

The shard_map programs (``parallel/sharded.py`` scaffolds, the PR 12
collective migrate, the partitioned round programs) fail in ways the
trace-safety rules cannot see: an axis name that is not in the mesh
spec (JL101), a ``ppermute`` whose pair list is not a bijection
(JL102), a per-shard partial total escaping through a replicated
out_spec (JL103), and — the one that deadlocks real hardware rather
than erroring — a collective guarded by shard-local control flow
(JL104).

Everything here is best-effort STATIC reasoning with a hard
no-false-positive bias: a check only fires when the relevant operand
(axis name, permutation list, out_spec, predicate) is statically
enumerable; the engine's own runtime-parameterized idioms
(``axis_name(mesh)`` variables, ``[(i, (i+1) % n)]`` comprehension
rings, spec tuples built by concatenation) are skipped, not guessed
at. See docs/STATIC_ANALYSIS.md for the per-rule contracts.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from pumiumtally_tpu.analysis.core import Diagnostic, _ModuleIndex

# lax collectives -> positional index of their axis-name argument.
_COLLECTIVES: dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "pbroadcast": 1, "pshuffle": 1, "pvary": 1,
    "axis_index": 0, "axis_size": 0,
}
# Collectives whose RESULT is globally combined/replicated — they
# clear per-shard-reduction taint (JL103) and replicate predicates
# (JL104).
_REPLICATING = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "all_to_all",
}
# jnp reductions that collapse the shard-LOCAL block (JL103 sources);
# also recognized as methods (``x.sum()``).
_REDUCTIONS = {
    "sum", "mean", "max", "min", "prod", "any", "all", "count_nonzero",
}


def _is_lax_collective(index: _ModuleIndex, call: ast.Call) -> Optional[str]:
    """The collective's short name if ``call`` is a ``jax.lax``
    collective, else None."""
    d = index.dotted(call.func)
    if not d:
        return None
    leaf = d.split(".")[-1]
    if leaf in _COLLECTIVES and (
        d.startswith("jax.lax.") or d.startswith("jax.")
    ):
        return leaf
    return None


def _axis_literals(call: ast.Call, leaf: str) -> Optional[tuple[str, ...]]:
    """Literal axis name(s) of a collective call, or None when the
    axis operand is not statically a string (a variable, an
    ``axis_name(mesh)`` result, ...)."""
    pos = _COLLECTIVES[leaf]
    node: Optional[ast.AST] = None
    if pos < len(call.args):
        node = call.args[pos]
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis_names"):
            node = kw.value
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _is_partition_spec(index: _ModuleIndex, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = index.dotted(node.func)
    return bool(d) and d.split(".")[-1] in ("PartitionSpec", "P")


def _spec_axes(node: ast.Call) -> Optional[set[str]]:
    """Literal axis names of one P(...) call; None when any operand is
    non-literal (the declared set would be incomplete)."""
    axes: set[str] = set()
    for a in node.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            axes.add(a.value)
        elif isinstance(a, ast.Constant) and a.value is None:
            continue
        elif isinstance(a, (ast.Tuple, ast.List)):
            for e in a.elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, str
                ):
                    axes.add(e.value)
                else:
                    return None
        else:
            return None
    return axes


@dataclasses.dataclass
class _Site:
    """One statically-discovered shard_map application."""

    line: int
    body: Optional[ast.AST]  # FunctionDef / Lambda, when resolvable
    owner: Optional[ast.AST]  # scope the site appears in
    in_specs: Optional[ast.AST]
    out_specs: Optional[ast.AST]
    declared_axes: Optional[set[str]]  # None = not statically known


def _collect_declared_axes(
    index: _ModuleIndex,
    mesh: Optional[ast.AST],
    in_specs: Optional[ast.AST],
    out_specs: Optional[ast.AST],
) -> Optional[set[str]]:
    """Union of literal axis names across the mesh/in_specs/out_specs
    expressions, or None when the declared set cannot be COMPLETE:
    any spec container holding a non-literal element (a ``pp = P(ax)``
    variable, a concatenated tuple, a dict comprehension) makes the
    bound unknowable, and JL101 must not guess."""
    axes: set[str] = set()
    found = False

    def take_spec(node: ast.AST) -> bool:
        """Fold one spec expression; False = not fully literal."""
        nonlocal axes, found
        if isinstance(node, ast.Constant) and node.value is None:
            return True
        if _is_partition_spec(index, node):
            got = _spec_axes(node)  # type: ignore[arg-type]
            if got is None:
                return False
            axes |= got
            found = True
            return True
        return False

    for specs in (in_specs, out_specs):
        if specs is None:
            continue
        elts = (
            list(specs.elts)
            if isinstance(specs, (ast.Tuple, ast.List))
            else [specs]
        )
        for e in elts:
            if not take_spec(e):
                return None
    if isinstance(mesh, ast.Call):
        d = index.dotted(mesh.func)
        leaf = d.split(".")[-1] if d else ""
        if leaf in ("Mesh", "make_mesh", "AbstractMesh"):
            names: Optional[ast.AST] = (
                mesh.args[1] if len(mesh.args) > 1 else None
            )
            for kw in mesh.keywords:
                if kw.arg == "axis_names":
                    names = kw.value
            got = _const_str_set(names)
            if got is None:
                return None
            axes |= got
            found = True
    return axes if found else None


def _const_str_set(node: Optional[ast.AST]) -> Optional[set[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: set[str] = set()
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.add(e.value)
        return out
    return None


def _shard_map_kwargs(call: ast.Call) -> dict[str, ast.AST]:
    kwargs: dict[str, ast.AST] = {}
    # shard_map(f, mesh, in_specs, out_specs) positional fallback.
    for i, name in enumerate(("mesh", "in_specs", "out_specs")):
        if i + 1 < len(call.args):
            kwargs[name] = call.args[i + 1]
    for kw in call.keywords:
        if kw.arg:
            kwargs[kw.arg] = kw.value
    return kwargs


def _resolve_body(
    index: _ModuleIndex,
    op: Optional[ast.AST],
    owner: Optional[ast.AST],
    line: int,
) -> Optional[ast.AST]:
    if op is None:
        return None
    if isinstance(op, ast.Lambda):
        return op
    if isinstance(op, ast.Name):
        return index.resolve_in_scope(op.id, owner, line)
    return None


def _walk_with_owner(roots, owner=None):
    """(node, enclosing-function) pairs over a subtree."""
    stack = [(owner, r) for r in roots]
    while stack:
        own, n = stack.pop()
        yield n, own
        nxt = (
            n
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
            else own
        )
        stack.extend((nxt, c) for c in ast.iter_child_nodes(n))


def _discover_sites(tree: ast.Module, index: _ModuleIndex) -> list[_Site]:
    sites: list[_Site] = []

    def is_sm(node: ast.AST) -> bool:
        d = index.dotted(node)
        return bool(d) and d.split(".")[-1] == "shard_map"

    def add(call: ast.Call, body, owner) -> None:
        kw = _shard_map_kwargs(call)
        sites.append(
            _Site(
                line=call.lineno,
                body=body,
                owner=owner,
                in_specs=kw.get("in_specs"),
                out_specs=kw.get("out_specs"),
                declared_axes=_collect_declared_axes(
                    index,
                    kw.get("mesh"),
                    kw.get("in_specs"),
                    kw.get("out_specs"),
                ),
            )
        )

    for node, owner in _walk_with_owner(tree.body):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # @partial(shard_map, mesh=..., ...) / @shard_map(...)
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                fd = index.dotted(dec.func)
                if is_sm(dec.func):
                    add(dec, node, owner)
                elif fd in ("functools.partial", "partial") and dec.args \
                        and is_sm(dec.args[0]):
                    add(dec, node, owner)
        elif isinstance(node, ast.Call):
            if is_sm(node.func) and node.args:
                add(
                    node,
                    _resolve_body(index, node.args[0], owner, node.lineno),
                    owner,
                )
            else:
                fd = index.dotted(node.func)
                if fd in ("functools.partial", "partial") and node.args \
                        and is_sm(node.args[0]) and len(node.args) > 1:
                    add(
                        node,
                        _resolve_body(
                            index, node.args[1], owner, node.lineno
                        ),
                        owner,
                    )
    return sites


def _out_spec_positions(
    index: _ModuleIndex, out_specs: Optional[ast.AST]
) -> Optional[list[str]]:
    """Per-output-position spec classification: "replicated" (a
    literal empty ``P()``), "varying" (a literal ``P`` with axes), or
    "unknown". None when out_specs is not a literal tuple/list (or a
    single spec)."""

    def classify(node: ast.AST) -> str:
        if _is_partition_spec(index, node):
            axes = _spec_axes(node)  # type: ignore[arg-type]
            if axes is None:
                return "unknown"
            return "replicated" if not axes else "varying"
        return "unknown"

    if out_specs is None:
        return None
    if isinstance(out_specs, (ast.Tuple, ast.List)):
        return [classify(e) for e in out_specs.elts]
    cls = classify(out_specs)
    return [cls] if cls != "unknown" else None


class _BodyState:
    """Single forward pass over a shard_map body: which names are
    shard-VARYING (derived from sharded inputs) and which carry an
    un-psum'd per-shard REDUCTION (JL103 taint)."""

    def __init__(
        self,
        index: _ModuleIndex,
        body: ast.AST,
        in_positions: Optional[list[str]],
    ) -> None:
        self.index = index
        self.varying: set[str] = set()
        self.reduced: set[str] = set()
        params = []
        if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = body.args
            params = list(a.posonlyargs) + list(a.args)
            if a.vararg:
                params.append(a.vararg)
        for i, p in enumerate(params):
            spec = (
                in_positions[i]
                if in_positions and i < len(in_positions)
                else "unknown"
            )
            if spec != "replicated":
                self.varying.add(p.arg)

    def is_varying(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.varying
        if isinstance(node, ast.Call):
            leaf = _is_lax_collective(self.index, node)
            if leaf in _REPLICATING:
                return False
        return any(
            self.is_varying(c) for c in ast.iter_child_nodes(node)
        )

    def is_reduced(self, node: ast.AST) -> bool:
        """Whether ``node`` may BE (or carry) an un-psum'd per-shard
        reduction."""
        if isinstance(node, ast.Name):
            return node.id in self.reduced
        if isinstance(node, ast.Call):
            leaf = _is_lax_collective(self.index, node)
            if leaf in _REPLICATING:
                return False
            d = self.index.dotted(node.func)
            red = bool(d) and d.split(".")[-1] in _REDUCTIONS and (
                d.startswith("jax.numpy.") or d.startswith("jax.")
            )
            if not red and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _REDUCTIONS \
                    and not self.index.is_module_func(node.func):
                red = True  # x.sum() method form
            if red and (
                any(self.is_varying(a) for a in node.args)
                or any(self.is_varying(k.value) for k in node.keywords)
            ):
                return True
        return any(
            self.is_reduced(c) for c in ast.iter_child_nodes(node)
        )

    def absorb(self, stmt: ast.stmt) -> None:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return
        value = stmt.value
        if value is None:
            return
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        # Elementwise tuple unpack keeps the maps precise for the
        # `a, b = f(x), g(x)` style; otherwise the flags smear over
        # every target (conservative).
        if (
            len(targets) == 1
            and isinstance(targets[0], (ast.Tuple, ast.List))
            and isinstance(value, (ast.Tuple, ast.List))
            and len(targets[0].elts) == len(value.elts)
        ):
            pairs = list(zip(targets[0].elts, value.elts))
        else:
            pairs = [(t, value) for t in targets]
        for tgt, val in pairs:
            names = (
                [tgt.id] if isinstance(tgt, ast.Name)
                else [e.id for e in getattr(tgt, "elts", [])
                      if isinstance(e, ast.Name)]
            )
            var = self.is_varying(val)
            red = self.is_reduced(val)
            for name in names:
                (self.varying.add if var else self.varying.discard)(name)
                (self.reduced.add if red else self.reduced.discard)(name)


def _body_stmts(body: ast.AST) -> list[ast.stmt]:
    """The body's statements in lexical order, descending into
    compound statements but NOT nested function defs (those run when
    called, with their own rules)."""
    out: list[ast.stmt] = []
    roots = body.body if isinstance(body.body, list) else []
    stack = list(reversed(roots))
    while stack:
        s = stack.pop()
        out.append(s)
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        sub: list[ast.stmt] = []
        for field in ("body", "orelse", "finalbody"):
            sub.extend(getattr(s, field, []) or [])
        for h in getattr(s, "handlers", []) or []:
            sub.extend(h.body)
        stack.extend(reversed(sub))
    return out


def _contains_collective(index: _ModuleIndex, fn: Optional[ast.AST]) -> bool:
    if fn is None:
        return False
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and _is_lax_collective(index, n):
            return True
    return False


def _closure_reads(fn: ast.AST) -> set[str]:
    """Names loaded in ``fn`` that are not its own params or locals."""
    params = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if a.vararg:
            params.add(a.vararg.arg)
    local_stores = {
        n.id
        for n in ast.walk(fn)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
    }
    return {
        n.id
        for n in ast.walk(fn)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        and n.id not in params and n.id not in local_stores
    }


def check(tree: ast.Module, index: _ModuleIndex, path: str
          ) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    sites = _discover_sites(tree, index)

    # JL102 is site-independent: a literal non-bijective perm is wrong
    # wherever it appears.
    for node, _ in _walk_with_owner(tree.body):
        if isinstance(node, ast.Call) and \
                _is_lax_collective(index, node) == "ppermute":
            _check_perm(node, path, diags)

    for site in sites:
        body = site.body
        if body is None:
            continue
        in_positions = _out_spec_positions(index, site.in_specs)
        out_positions = _out_spec_positions(index, site.out_specs)
        state = _BodyState(index, body, in_positions)

        # JL101: literal axis names vs the statically-declared set.
        if site.declared_axes is not None:
            for n in ast.walk(body):
                if not isinstance(n, ast.Call):
                    continue
                leaf = _is_lax_collective(index, n)
                if leaf is None:
                    continue
                axes = _axis_literals(n, leaf)
                for ax in axes or ():
                    if ax not in site.declared_axes:
                        diags.append(Diagnostic(
                            path, n.lineno, "JL101",
                            f"collective `{leaf}` uses axis {ax!r} "
                            "which is not declared by this shard_map's "
                            "mesh/axis specs "
                            f"({sorted(site.declared_axes)})",
                        ))

        # Forward pass: taint + JL104 divergent-control checks, in
        # statement order so predicates see the right state.
        stmts = (
            _body_stmts(body)
            if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef))
            else []
        )
        for stmt in stmts:
            for expr in ast.walk(stmt):
                if isinstance(expr, ast.Call):
                    _check_divergent(
                        expr, state, index, body, path, diags
                    )
            state.absorb(stmt)

        # JL103: reduction-tainted returns through replicated specs.
        returns: list[ast.AST] = []
        if isinstance(body, ast.Lambda):
            returns = [body.body]
        else:
            returns = [
                s.value for s in stmts
                if isinstance(s, ast.Return) and s.value is not None
            ]
        for ret in returns:
            elts = (
                list(ret.elts) if isinstance(ret, ast.Tuple) else [ret]
            )
            for i, elt in enumerate(elts):
                spec = (
                    out_positions[i]
                    if out_positions and i < len(out_positions)
                    else "unknown"
                )
                if spec == "replicated" and state.is_reduced(elt):
                    diags.append(Diagnostic(
                        path, elt.lineno, "JL103",
                        "per-shard reduction returned through a "
                        f"replicated P() out_spec (position {i}); "
                        "psum it over the mesh axis first",
                    ))
    return diags


def _check_perm(node: ast.Call, path: str, diags: list[Diagnostic]) -> None:
    perm: Optional[ast.AST] = node.args[2] if len(node.args) > 2 else None
    for kw in node.keywords:
        if kw.arg == "perm":
            perm = kw.value
    if not isinstance(perm, (ast.List, ast.Tuple)):
        return  # computed perm (comprehension ring, ...): skip
    pairs: list[tuple[int, int]] = []
    for e in perm.elts:
        if not (isinstance(e, (ast.Tuple, ast.List))
                and len(e.elts) == 2
                and all(isinstance(x, ast.Constant)
                        and isinstance(x.value, int) for x in e.elts)):
            return  # not statically enumerable
        pairs.append((e.elts[0].value, e.elts[1].value))
    if not pairs:
        return
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    problem = None
    if len(set(srcs)) != len(srcs):
        problem = "duplicate source shard"
    elif len(set(dsts)) != len(dsts):
        problem = "duplicate destination shard"
    elif set(srcs) != set(dsts):
        problem = "source and destination sets differ"
    if problem:
        diags.append(Diagnostic(
            path, node.lineno, "JL102",
            f"ppermute perm {pairs} is not a total permutation "
            f"({problem}); unnamed destinations receive zeros",
        ))


def _check_divergent(
    call: ast.Call,
    state: _BodyState,
    index: _ModuleIndex,
    body: ast.AST,
    path: str,
    diags: list[Diagnostic],
) -> None:
    d = index.dotted(call.func)
    leaf = d.split(".")[-1] if d else ""
    if leaf not in ("cond", "while_loop") or not d or \
            not d.startswith("jax."):
        return

    def operand(i: int) -> Optional[ast.AST]:
        if i >= len(call.args):
            return None
        return _resolve_body(index, call.args[i], body, call.lineno)

    if leaf == "cond":
        pred = call.args[0] if call.args else None
        branches = [operand(1), operand(2)]
        if pred is None:
            return
        shard_local = state.is_varying(pred) or state.is_reduced(pred)
        has_coll = any(_contains_collective(index, b) for b in branches)
    else:  # while_loop
        cond_fn = operand(0)
        body_fn = operand(1)
        if cond_fn is None:
            return
        reads = _closure_reads(cond_fn)
        shard_local = bool(
            reads & (state.varying | state.reduced)
        )
        has_coll = _contains_collective(index, body_fn) or \
            _contains_collective(index, cond_fn)
    if shard_local and has_coll:
        diags.append(Diagnostic(
            path, call.lineno, "JL104",
            f"`lax.{leaf}` predicate derives from a shard-local value "
            "and its operand contains a collective: shards can "
            "diverge and the collective deadlocks; derive the "
            "predicate from a psum'd (replicated) value",
        ))
