"""jaxlint Pallas-kernel pass: rules JL201-JL204 (pure stdlib).

The Pallas kernels (``ops/vmem_walk.py``, ``ops/pallas_walk.py``) are
checked on hardware this repo usually cannot reach — Mosaic's
scoped-VMEM limit, ref-role discipline and block/array divisibility
all surface only at AOT-compile time (ROADMAP "standing caveat").
This pass front-loads the statically-decidable share of those checks:

- JL201 sums the block-resident bytes declared by LITERAL BlockSpec
  shapes against the measured feasibility model (the
  ``VMEM_FEASIBLE_MAX_ELEMS`` constant documented in ops/vmem_walk.py;
  mirrored here because the analyzer must not import jax).
- JL202 splits kernel params into input/output refs by counting a
  literal ``in_specs`` list and flags input-ref writes plus
  output-ref reads that precede every in-flow write.
- JL203 checks literal out_shape dims divide by their out_specs block
  dims.
- JL204 forbids host-effect calls (print/open/os./time./logging)
  inside kernel bodies — `pl.debug_print` is the device-side tool.

Same no-false-positive bias as the collective pass: runtime-sized
blocks, `+=`-assembled spec lists and `*refs` kernels are skipped,
not guessed at.
"""

from __future__ import annotations

import ast
from typing import Optional

from pumiumtally_tpu.analysis.core import Diagnostic, _ModuleIndex

# Mirror of the ops/vmem_walk.py feasibility model (the analyzer is
# jax-free by contract, so the constants cannot be imported): the
# largest measured-feasible resident operand at the production
# particle tile is the [VMEM_FEASIBLE_MAX_ELEMS, TABLE_PAD_COLS] f32
# table block — 1 MiB of declared block bytes. Blocks declaring more
# than that hit Mosaic's "exceeded scoped vmem limit" on every chip
# generation (it is a compiler constant, not physical VMEM).
_VMEM_FEASIBLE_MAX_ELEMS = 8192
_TABLE_PAD_COLS = 32
VMEM_BLOCK_BUDGET_BYTES = _VMEM_FEASIBLE_MAX_ELEMS * _TABLE_PAD_COLS * 4

# dtype leaf name -> element bytes (for ShapeDtypeStruct-declared
# outputs; inputs default to 4 — the kernels are f32/int32 by the
# Mosaic rank-1 tiling law documented in ops/vmem_walk.py).
_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}

_HOST_CALL_NAMES = {"print", "open", "input", "breakpoint"}
_HOST_CALL_PREFIXES = ("os.", "time.", "logging.", "sys.", "io.")

_MUTATING_ASSIGN = (ast.Assign, ast.AugAssign, ast.AnnAssign)


def _module_int_consts(tree: ast.Module) -> dict[str, int]:
    """Module-level integer constants, folded in definition order
    (``TILE_1D = 1024``; ``BF16_MAX = 2 * MAX``)."""
    consts: dict[str, int] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            val = _fold_int(stmt.value, consts)
            name = stmt.targets[0].id
            if val is not None:
                consts[name] = val
            else:
                consts.pop(name, None)
    return consts


def _fold_int(node: Optional[ast.AST], consts: dict[str, int]
              ) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _fold_int(node.operand, consts)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        lo = _fold_int(node.left, consts)
        hi = _fold_int(node.right, consts)
        if lo is None or hi is None:
            return None
        if isinstance(node.op, ast.Add):
            return lo + hi
        if isinstance(node.op, ast.Sub):
            return lo - hi
        if isinstance(node.op, ast.Mult):
            return lo * hi
        if isinstance(node.op, ast.FloorDiv) and hi != 0:
            return lo // hi
        if isinstance(node.op, ast.Mod) and hi != 0:
            return lo % hi
    return None


def _is_call_leaf(index: _ModuleIndex, node: ast.AST, leaf: str) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = index.dotted(node.func)
    return bool(d) and d.split(".")[-1] == leaf


def _block_shape(index: _ModuleIndex, spec: ast.AST
                 ) -> Optional[list[Optional[ast.AST]]]:
    """The block-shape dim expressions of one literal BlockSpec call,
    or None when the call/shape is not statically structured."""
    if not _is_call_leaf(index, spec, "BlockSpec"):
        return None
    call = spec  # type: ignore[assignment]
    shape: Optional[ast.AST] = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "block_shape":
            shape = kw.value
    if isinstance(shape, (ast.Tuple, ast.List)):
        return list(shape.elts)
    return None


def _spec_list(node: Optional[ast.AST]) -> Optional[list[ast.AST]]:
    """Elements of a LITERAL in_specs/out_specs list, or None
    (``+=``-assembled or otherwise runtime-shaped lists — the
    pallas_walk.py variant — are not statically countable)."""
    if isinstance(node, (ast.List, ast.Tuple)):
        return list(node.elts)
    return None


def check(tree: ast.Module, index: _ModuleIndex, path: str
          ) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    consts = _module_int_consts(tree)

    stack: list[tuple[Optional[ast.AST], ast.AST]] = [
        (None, n) for n in tree.body
    ]
    while stack:
        owner, node = stack.pop()
        nxt = (
            node
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            )
            else owner
        )
        stack.extend((nxt, c) for c in ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        d = index.dotted(node.func)
        if not d or d.split(".")[-1] != "pallas_call":
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        kernel: Optional[ast.AST] = None
        if node.args:
            op = node.args[0]
            if isinstance(op, ast.Lambda):
                kernel = op
            elif isinstance(op, ast.Name):
                kernel = index.resolve_in_scope(
                    op.id, owner, node.lineno
                )
        _check_vmem_budget(node, kwargs, index, consts, path, diags)
        _check_divisibility(kwargs, index, consts, path, diags)
        if isinstance(kernel, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_ref_discipline(node, kernel, kwargs, index, path,
                                  diags)
            _check_host_calls(kernel, index, path, diags)
    return diags


# -- JL201 ----------------------------------------------------------------
def _check_vmem_budget(
    call: ast.Call,
    kwargs: dict[str, ast.AST],
    index: _ModuleIndex,
    consts: dict[str, int],
    path: str,
    diags: list[Diagnostic],
) -> None:
    out_dtypes = _out_dtype_bytes(kwargs.get("out_shape"), index)
    total = 0
    resolved_any = False
    for which in ("in_specs", "out_specs"):
        specs = _spec_list(kwargs.get(which))
        if specs is None and which == "out_specs" and \
                kwargs.get("out_specs") is not None:
            specs = [kwargs["out_specs"]]  # single un-listed spec
        for i, spec in enumerate(specs or []):
            dims = _block_shape(index, spec)
            if dims is None:
                continue
            elems = 1
            ok = True
            for dim in dims:
                v = _fold_int(dim, consts)
                if v is None:
                    ok = False
                    break
                elems *= v
            if not ok:
                continue
            resolved_any = True
            bytes_per = 4
            if which == "out_specs" and i < len(out_dtypes) and \
                    out_dtypes[i] is not None:
                bytes_per = out_dtypes[i]
            total += elems * bytes_per
    if resolved_any and total > VMEM_BLOCK_BUDGET_BYTES:
        diags.append(Diagnostic(
            path, call.lineno, "JL201",
            f"declared BlockSpec working set is {total} bytes "
            f"({total // 1024} KiB), beyond the "
            f"{VMEM_BLOCK_BUDGET_BYTES // 1024} KiB feasibility model "
            "(VMEM_FEASIBLE_MAX_ELEMS, ops/vmem_walk.py); Mosaic will "
            "reject this at AOT compile",
        ))


def _out_dtype_bytes(out_shape: Optional[ast.AST], index: _ModuleIndex
                     ) -> list[Optional[int]]:
    structs = _spec_list(out_shape)
    if structs is None:
        structs = [out_shape] if out_shape is not None else []
    out: list[Optional[int]] = []
    for s in structs:
        b: Optional[int] = None
        if s is not None and _is_call_leaf(index, s, "ShapeDtypeStruct"):
            dt: Optional[ast.AST] = (
                s.args[1] if len(s.args) > 1 else None
            )
            for kw in s.keywords:
                if kw.arg == "dtype":
                    dt = kw.value
            dd = index.dotted(dt) if dt is not None else None
            if dd:
                b = _DTYPE_BYTES.get(dd.split(".")[-1])
        out.append(b)
    return out


# -- JL203 ----------------------------------------------------------------
def _check_divisibility(
    kwargs: dict[str, ast.AST],
    index: _ModuleIndex,
    consts: dict[str, int],
    path: str,
    diags: list[Diagnostic],
) -> None:
    shapes = _spec_list(kwargs.get("out_shape"))
    if shapes is None and kwargs.get("out_shape") is not None:
        shapes = [kwargs["out_shape"]]
    specs = _spec_list(kwargs.get("out_specs"))
    if specs is None and kwargs.get("out_specs") is not None:
        specs = [kwargs["out_specs"]]
    if not shapes or not specs:
        return
    for pos, (sd, sp) in enumerate(zip(shapes, specs)):
        if not _is_call_leaf(index, sd, "ShapeDtypeStruct"):
            continue
        arr: Optional[ast.AST] = sd.args[0] if sd.args else None
        for kw in sd.keywords:
            if kw.arg == "shape":
                arr = kw.value
        if not isinstance(arr, (ast.Tuple, ast.List)):
            continue
        dims = _block_shape(index, sp)
        if dims is None:
            continue
        for arr_dim, blk_dim in zip(arr.elts, dims):
            a = _fold_int(arr_dim, consts)
            b = _fold_int(blk_dim, consts)
            if a is None or b is None or b <= 0:
                continue
            if a % b != 0:
                diags.append(Diagnostic(
                    path, sp.lineno, "JL203",
                    f"output {pos}: array dim {a} is not divisible by "
                    f"its BlockSpec block dim {b}; the trailing block "
                    "reads out of bounds",
                ))


# -- JL202 ----------------------------------------------------------------
def _check_ref_discipline(
    call: ast.Call,
    kernel: ast.FunctionDef,
    kwargs: dict[str, ast.AST],
    index: _ModuleIndex,
    path: str,
    diags: list[Diagnostic],
) -> None:
    specs = _spec_list(kwargs.get("in_specs"))
    if specs is None:
        return  # runtime-assembled in_specs (pallas_walk.py): skip
    n_in = len(specs)
    params = [
        p.arg
        for p in (list(kernel.args.posonlyargs) + list(kernel.args.args))
    ]
    vararg = kernel.args.vararg.arg if kernel.args.vararg else None
    if n_in > len(params):
        return  # inputs spill into the vararg: roles ambiguous
    inputs = set(params[:n_in])
    outputs = set(params[n_in:])
    aliases: dict[str, str] = {}  # local alias -> underlying ref name

    def resolve(name: str) -> Optional[str]:
        seen = set()
        while name in aliases and name not in seen:
            seen.add(name)
            name = aliases[name]
        if name in inputs or name in outputs or name == vararg:
            return name
        return None

    def ref_of(expr: ast.AST) -> Optional[str]:
        """The ref a subscript/name expression designates, following
        vararg indexing (``flux_outs[0]`` is an output ref)."""
        if isinstance(expr, ast.Name):
            return resolve(expr.id)
        if isinstance(expr, ast.Subscript):
            return ref_of(expr.value)
        return None

    def role(name: str) -> str:
        return "input" if name in inputs else "output"

    # In-flow statements: the kernel's own flow plus decorated nested
    # defs (`@pl.when(...)` blocks execute at their definition point);
    # bare nested defs (while_loop bodies) run later — excluded from
    # the read-before-write ordering, included for input-ref writes.
    flow: list[tuple[ast.stmt, bool]] = []  # (stmt, in_flow)

    def collect(stmts: list, in_flow: bool) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                collect(s.body, in_flow and bool(s.decorator_list))
                continue
            flow.append((s, in_flow))
            for field in ("body", "orelse", "finalbody"):
                collect(getattr(s, field, []) or [], in_flow)
            for h in getattr(s, "handlers", []) or []:
                collect(h.body, in_flow)

    collect(kernel.body, True)

    first_write: dict[str, int] = {}
    writes: list[tuple[int, str]] = []
    reads: list[tuple[int, str, bool]] = []  # (line, ref, in_flow)

    for stmt, in_flow in flow:
        # Alias bookkeeping: `a = b` / `a = b[i]` where b is a ref.
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tgt = stmt.targets[0].id
            src: Optional[str] = None
            v = stmt.value
            if isinstance(v, ast.Name):
                src = v.id
            elif isinstance(v, ast.Subscript) and \
                    isinstance(v.value, ast.Name) and \
                    resolve(v.value.id) == vararg and vararg:
                src = v.value.id  # vararg element IS a ref
            if src is not None and resolve(src) is not None:
                aliases[tgt] = src
        # Writes: subscript stores + pl.store.
        tgts: list[ast.AST] = []
        if isinstance(stmt, _MUTATING_ASSIGN):
            tgts = (
                list(stmt.targets) if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
        for t in tgts:
            if isinstance(t, ast.Subscript):
                name = ref_of(t)
                if name:
                    writes.append((stmt.lineno, name))
                    if in_flow:
                        first_write.setdefault(name, stmt.lineno)
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                d = index.dotted(n.func)
                leaf = d.split(".")[-1] if d else ""
                if leaf == "store" and n.args:
                    name = ref_of(n.args[0])
                    if name:
                        writes.append((n.lineno, name))
                        if in_flow:
                            first_write.setdefault(name, n.lineno)
                elif leaf == "load" and n.args:
                    name = ref_of(n.args[0])
                    if name:
                        reads.append((n.lineno, name, in_flow))
            elif isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, ast.Load):
                name = ref_of(n)
                if name and name != vararg:
                    reads.append((n.lineno, name, in_flow))

    for line, name in writes:
        if role(name) == "input":
            diags.append(Diagnostic(
                path, line, "JL202",
                f"kernel writes input ref `{name}` (param of "
                f"`{kernel.name}` backed by in_specs); input blocks "
                "may alias the operand — write an output ref",
            ))
    seen_read: set[tuple[int, str]] = set()
    for line, name, in_flow in reads:
        if not in_flow or role(name) != "output":
            continue
        fw = first_write.get(name)
        if fw is not None and line >= fw:
            continue
        if (line, name) in seen_read:
            continue
        seen_read.add((line, name))
        diags.append(Diagnostic(
            path, line, "JL202",
            f"kernel reads output ref `{name}` before any write "
            "seeds it; output blocks are uninitialized until written",
        ))


# -- JL204 ----------------------------------------------------------------
def _check_host_calls(
    kernel: ast.FunctionDef,
    index: _ModuleIndex,
    path: str,
    diags: list[Diagnostic],
) -> None:
    for n in ast.walk(kernel):
        if not isinstance(n, ast.Call):
            continue
        bad: Optional[str] = None
        if isinstance(n.func, ast.Name) and n.func.id in _HOST_CALL_NAMES:
            bad = n.func.id
        else:
            d = index.dotted(n.func)
            if d and d.startswith(_HOST_CALL_PREFIXES) and \
                    index.is_module_func(n.func):
                bad = d
        if bad:
            diags.append(Diagnostic(
                path, n.lineno, "JL204",
                f"host-side call `{bad}` inside Pallas kernel "
                f"`{kernel.name}` runs at trace time only (or fails "
                "to lower); use pl.debug_print / move I/O outside "
                "the pallas_call",
            ))
