"""jaxlint — a JAX-aware static analyzer for the tally engine.

ruff and clang-tidy (.github/workflows/static-analysis.yml) are the
generic correctness backstop; this package is the JAX-specific one,
organised as four passes over one shared parse + module index:

* **Trace safety (JL000–JL005)** — understands where the TRACE
  BOUNDARY lies (``jax.jit`` / ``lax.while_loop`` / ``lax.scan`` /
  ``shard_map`` / ``pallas_call`` bodies) and flags hidden host
  synchronization in hot loops (JL001), Python control flow on traced
  arrays (JL002), donated-buffer reuse (JL003), retrace-bait static
  arguments (JL004), and module-state mutation under trace (JL005).
* **Collective safety (JL101–JL104)** — axis names used inside
  ``shard_map`` bodies must appear in the mesh/axis-spec (JL101),
  statically enumerable ``ppermute`` perms must be total permutations
  (JL102), per-shard scalars returned un-psum'd from collective bodies
  (JL103), and ``lax.cond``/``while_loop`` predicates derived from
  shard-local values around collectives — divergent-control deadlock
  bait (JL104).
* **Pallas kernels (JL201–JL204)** — BlockSpec working sets bounded
  against the ``ops/vmem_walk.py`` VMEM feasibility model (JL201),
  ref discipline: no input-ref writes or output-ref reads-before-write
  (JL202), grid/block divisibility (JL203), and host calls in kernel
  bodies (JL204).
* **Host concurrency (JL301–JL303)** — for the ``service/`` and
  ``resilience/`` layers: shared state written from multiple thread
  entry points without a recognized lock (JL301), lock-ordering cycles
  (JL302), and blocking calls while holding a lock (JL303). Thread
  entry points come from the ``THREAD_ROOTS`` registry in
  ``analysis/concurrency.py``.

Pure stdlib: no jax import, no code execution — safe for CI.

Usage::

    python -m pumiumtally_tpu.analysis pumiumtally_tpu/   # lint a tree
    python -m pumiumtally_tpu.analysis --format json ...  # machine use
    python -m pumiumtally_tpu.analysis --contracts        # facade audit
    python -m pumiumtally_tpu.analysis --explain JL101    # rule docs
    python tools/jaxlint.py ...                           # same CLI

``--contracts`` audits the five tally facades (monolithic, sharded,
streaming, partitioned, streaming_partitioned) against the shared hook
surface — batch-close, move-end, checkpoint rows, lane-bank registry,
fusion-key — and prints the drift table referenced by ROADMAP item 5.

Suppression (justification REQUIRED — see docs/STATIC_ANALYSIS.md)::

    flux = np.asarray(dev)  # jaxlint: disable=JL001 -- result fetch at
                            # the tally boundary

The runtime counterpart — the retrace tripwire that catches what static
analysis cannot (cache-key instability observable only at run time) —
is ``pumiumtally_tpu.utils.profiling.retrace_guard``.
"""

from pumiumtally_tpu.analysis.contracts import audit_contracts
from pumiumtally_tpu.analysis.core import (
    Analyzer,
    Diagnostic,
    iter_python_files,
    lint_paths,
    lint_source,
)
from pumiumtally_tpu.analysis.rules import RULES, Rule

__all__ = [
    "Analyzer",
    "Diagnostic",
    "RULES",
    "Rule",
    "audit_contracts",
    "iter_python_files",
    "lint_paths",
    "lint_source",
]
