"""jaxlint — a JAX-aware trace-safety analyzer for the tally engine.

ruff and clang-tidy (.github/workflows/static-analysis.yml) are the
generic correctness backstop; this package is the JAX-specific one: it
understands where the TRACE BOUNDARY lies (``jax.jit`` /
``lax.while_loop`` / ``lax.scan`` / ``shard_map`` / ``pallas_call``
bodies) and flags the failure modes that actually bite a JAX/TPU
codebase — hidden host synchronization in the hot loops (JL001),
Python control flow on traced arrays (JL002), donated-buffer reuse
(JL003), retrace-bait static arguments (JL004), and module-state
mutation under trace (JL005). Pure stdlib: no jax import, no code
execution — safe for CI.

Usage::

    python -m pumiumtally_tpu.analysis pumiumtally_tpu/   # lint a tree
    python -m pumiumtally_tpu.analysis --explain JL001    # rule docs
    python tools/jaxlint.py ...                           # same CLI

Suppression (justification REQUIRED — see docs/STATIC_ANALYSIS.md)::

    flux = np.asarray(dev)  # jaxlint: disable=JL001 -- result fetch at
                            # the tally boundary

The runtime counterpart — the retrace tripwire that catches what static
analysis cannot (cache-key instability observable only at run time) —
is ``pumiumtally_tpu.utils.profiling.retrace_guard``.
"""

from pumiumtally_tpu.analysis.core import (
    Analyzer,
    Diagnostic,
    iter_python_files,
    lint_paths,
    lint_source,
)
from pumiumtally_tpu.analysis.rules import RULES, Rule

__all__ = [
    "Analyzer",
    "Diagnostic",
    "RULES",
    "Rule",
    "iter_python_files",
    "lint_paths",
    "lint_source",
]
