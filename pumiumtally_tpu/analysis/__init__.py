"""jaxlint — a JAX-aware static analyzer for the tally engine.

ruff and clang-tidy (.github/workflows/static-analysis.yml) are the
generic correctness backstop; this package is the JAX-specific one,
organised as six passes over one shared parse + module index:

* **Trace safety (JL000–JL005)** — understands where the TRACE
  BOUNDARY lies (``jax.jit`` / ``lax.while_loop`` / ``lax.scan`` /
  ``shard_map`` / ``pallas_call`` bodies) and flags hidden host
  synchronization in hot loops (JL001), Python control flow on traced
  arrays (JL002), donated-buffer reuse (JL003), retrace-bait static
  arguments (JL004), and module-state mutation under trace (JL005).
* **Collective safety (JL101–JL104)** — axis names used inside
  ``shard_map`` bodies must appear in the mesh/axis-spec (JL101),
  statically enumerable ``ppermute`` perms must be total permutations
  (JL102), per-shard scalars returned un-psum'd from collective bodies
  (JL103), and ``lax.cond``/``while_loop`` predicates derived from
  shard-local values around collectives — divergent-control deadlock
  bait (JL104).
* **Pallas kernels (JL201–JL204)** — BlockSpec working sets bounded
  against the ``ops/vmem_walk.py`` VMEM feasibility model (JL201),
  ref discipline: no input-ref writes or output-ref reads-before-write
  (JL202), grid/block divisibility (JL203), and host calls in kernel
  bodies (JL204).
* **Host concurrency (JL301–JL303)** — for the ``service/`` and
  ``resilience/`` layers: shared state written from multiple thread
  entry points without a recognized lock (JL301), lock-ordering cycles
  (JL302), and blocking calls while holding a lock (JL303). Thread
  entry points come from the ``THREAD_ROOTS`` registry in
  ``analysis/concurrency.py``.
* **Trace-key cardinality (JL401–JL404)** — the static half of the
  retrace-budget contract: a registered entry point whose
  statically-enumerable knob domains multiply past its
  ``config.RETRACE_BUDGETS`` entry (JL401) and per-call-varying
  values (``len(batch)``, ``x.shape``) reaching static key positions
  (JL404) are caught per-file; the repo-wide ``--trace-keys`` audit
  adds dead budgets (JL402) and unbudgeted entry points (JL403) and
  prints the calibration inventory (``analysis/tracekeys.py``).
* **Determinism (JL501–JL503)** — host seams of the bitwise
  contract: unordered set iteration feeding device ops, wire
  replies, or checkpoint key order (JL501), non-stable sorts on
  segmented-commit paths (JL502), and host-side float
  re-accumulation — builtin ``sum()`` over device fetches — inside
  parity-gated tools (JL503) (``analysis/determinism.py``).

Pure stdlib: no jax import, no code execution — safe for CI.

Usage::

    python -m pumiumtally_tpu.analysis pumiumtally_tpu/   # lint a tree
    python -m pumiumtally_tpu.analysis --format json ...  # machine use
    python -m pumiumtally_tpu.analysis --contracts        # facade audit
    python -m pumiumtally_tpu.analysis --trace-keys       # budget audit
    python -m pumiumtally_tpu.analysis --wire             # wire audit
    python -m pumiumtally_tpu.analysis --explain JL101    # rule docs
    python tools/jaxlint.py ...                           # same CLI

``--contracts`` audits the five tally facades (monolithic, sharded,
streaming, partitioned, streaming_partitioned) against the shared hook
surface — batch-close, move-end, checkpoint rows, lane-bank registry,
fusion-key — and prints the drift table referenced by ROADMAP item 5.
``--trace-keys`` is the same idea for the retrace-budget table
(ROADMAP item 5's other recurring tax), and ``--wire`` for the NDJSON
socket protocol: every encoder (tools/loadgen.py, the test driver,
the examples, the router's own forwarded pings) is cross-checked
against the op allowlist and reply schemas AST-extracted from
``service/server.py`` (``analysis/wire.py``).

Suppression (justification REQUIRED — see docs/STATIC_ANALYSIS.md)::

    flux = np.asarray(dev)  # jaxlint: disable=JL001 -- result fetch at
                            # the tally boundary

The runtime counterpart — the retrace tripwire that catches what static
analysis cannot (cache-key instability observable only at run time) —
is ``pumiumtally_tpu.utils.profiling.retrace_guard``.
"""

from pumiumtally_tpu.analysis.contracts import audit_contracts
from pumiumtally_tpu.analysis.tracekeys import audit_trace_keys
from pumiumtally_tpu.analysis.wire import audit_wire
from pumiumtally_tpu.analysis.core import (
    Analyzer,
    Diagnostic,
    iter_python_files,
    lint_paths,
    lint_source,
)
from pumiumtally_tpu.analysis.rules import RULES, Rule

__all__ = [
    "Analyzer",
    "Diagnostic",
    "RULES",
    "Rule",
    "audit_contracts",
    "audit_trace_keys",
    "audit_wire",
    "iter_python_files",
    "lint_paths",
    "lint_source",
]
