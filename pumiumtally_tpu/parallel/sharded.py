"""shard_map'd walk steps: particles sharded over ``dp``, flux psum'd.

Replicated-mesh data parallelism — the TPU-native form of the
reference's latent MPI mode (SURVEY.md §2.3): every chip holds the full
tet mesh (as every reference rank does, PumiTallyImpl.cpp:530-539), each
chip walks its shard of the particle batch independently (the walk is
embarrassingly parallel across particles), and the per-element flux is
all-reduced with ``psum`` over the ICI mesh axis — replacing the
device-atomic + MPI-reduction combination of the reference
(Kokkos::atomic_add at PumiTallyImpl.cpp:376; vtk::write_parallel's
rank-aware output at cpp:415).

The particle-batch size must be divisible by the mesh size; the API
layer pads its capacity to guarantee this (padded slots carry
``in_flight=0, dest=x`` and finish on the first walk iteration with
zero contribution).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pumiumtally_tpu.mesh.tetmesh import TetMesh
from pumiumtally_tpu.ops.walk import walk
from pumiumtally_tpu.utils.profiling import register_entry_point

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


def _pvary(x, axis_name: str):
    """Mark a body-constructed constant as varying over the mesh axis
    (shard_map's while_loop carries require consistent varying types).

    On jax versions predating the varying-axis type system (no
    ``lax.pcast`` and no ``lax.pvary``, e.g. 0.4.x) there is nothing to
    tag — shard_map's ``check_rep`` tracks replication without explicit
    promotion — so the value passes through unchanged."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis_name,))
    return x  # pre-vma jax: untagged values are fine under check_rep


def shard_map_check_kwargs(check: bool = True) -> dict:
    """Version-portable output-type-checking kwargs for shard_map —
    splat into every shard_map call: ``shard_map(...,
    **shard_map_check_kwargs())``.

    Current jax spells the checker ``check_vma`` (varying-axis types):
    ``check`` maps straight onto it. Pre-vma jax (0.4.x) spells it
    ``check_rep`` — but its replication checker lacks rules for the
    control flow this engine is built on (``NotImplementedError: No
    replication rule for while``), so checking is always DISABLED
    there; the vma-era runs keep pinning the real invariants."""
    import inspect

    try:
        params = inspect.signature(shard_map).parameters
    except (TypeError, ValueError):  # pragma: no cover — exotic wrapper
        return {}
    if "check_vma" in params:
        return {"check_vma": check}
    if "check_rep" in params:
        return {"check_rep": False}
    return {}  # pragma: no cover — future rename: accept the default


def axis_name(device_mesh: Mesh) -> str:
    """The single particle-sharding axis of a 1-D device mesh."""
    if len(device_mesh.axis_names) != 1:
        raise ValueError(
            f"expected a 1-D device mesh, got axes {device_mesh.axis_names}"
        )
    return device_mesh.axis_names[0]


_axis_name = axis_name


@partial(
    jax.jit,
    static_argnames=("device_mesh", "tol", "max_iters", "walk_kw"),
)
def sharded_localize_step(
    device_mesh: Mesh,
    mesh: TetMesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    dest: jnp.ndarray,
    *,
    tol: float,
    max_iters: int,
    walk_kw: tuple = (),
):
    """Non-tallying localization walk, particles sharded over ``dp``.

    Returns (x, elem, done, exited) with particle arrays sharded.
    """
    ax = _axis_name(device_mesh)
    pp = P(ax)

    @partial(
        shard_map,
        mesh=device_mesh,
        in_specs=(P(), pp, pp, pp),
        out_specs=(pp, pp, pp, pp),
        **shard_map_check_kwargs(),
    )
    def step(mesh_, x_, elem_, dest_):
        n = x_.shape[0]
        # A tally=False walk never touches flux — zero-size dummy
        # (carry-type consistent: it never mixes with varying values).
        r = walk(
            mesh_,
            x_,
            elem_,
            dest_,
            _pvary(jnp.ones((n,), jnp.int8), ax),
            _pvary(jnp.zeros((n,), x_.dtype), ax),
            jnp.zeros((0,), x_.dtype),
            tally=False,
            tol=tol,
            max_iters=max_iters,
            **dict(walk_kw),
        )
        return r.x, r.elem, r.done, r.exited

    return step(mesh, x, elem, dest)


@partial(jax.jit, static_argnames=("device_mesh", "tol"))
def sharded_locate(
    device_mesh: Mesh,
    mesh: TetMesh,
    pts: jnp.ndarray,
    *,
    tol: float,
):
    """MXU point location with the points sharded over ``dp`` and the
    face-plane tables replicated (each chip locates its shard — the
    locate-mode pre-pass of TallyConfig.localization for the sharded
    facade). Returns sharded element ids, −1 where unlocated."""
    from pumiumtally_tpu.ops.geometry import locate_by_planes

    ax = _axis_name(device_mesh)
    pp = P(ax)

    @partial(
        shard_map,
        mesh=device_mesh,
        in_specs=(P(), pp),
        out_specs=pp,
        **shard_map_check_kwargs(),
    )
    def step(mesh_, pts_):
        return locate_by_planes(
            mesh_.face_normals, mesh_.face_offsets, pts_, tol
        )

    return step(mesh, pts)


def _sharded_tally_step(device_mesh, step_fn, mesh, particle_args, flux,
                        tol, max_iters, walk_kw=(), score_kinds=(),
                        score_ops=None):
    """Common shard_map scaffold for the tallied move variants.

    ``particle_args`` are sharded over the particle axis; the tet mesh
    and the flux array are replicated. Each chip runs ``step_fn`` (a
    single-chip move from api.tally) on its shard, accumulating a local
    flux delta from a varying zero; deltas are ``psum``'d over ICI, so
    the returned flux is identical (and bitwise deterministic) on every
    chip. The per-particle ``done`` mask and phase-B ray coordinate
    ``s`` stay sharded like the other particle outputs — the facade
    reduces the mask for the found-all check and the sentinel's
    straggler ladder consumes both (round 9: every tallied step
    returns the mask + s, not a pre-reduced scalar).

    ``score_ops = (bank, bin_off, fac)`` (round 10): bin offsets /
    factor rows shard with the particles, the lane bank replicates
    like flux — each chip's bank delta psum's over ICI the same way,
    so scoring inherits the flux lane's determinism; the accumulated
    bank returns as a SIXTH output.
    """
    ax = _axis_name(device_mesh)
    pp = P(ax)
    scoring = score_ops is not None
    extra_in = (pp, pp) if scoring else ()
    extra_tail = (P(),) if scoring else ()

    @partial(
        shard_map,
        mesh=device_mesh,
        in_specs=(
            (P(),) + (pp,) * len(particle_args) + extra_in + (P(),)
            + extra_tail
        ),
        out_specs=(pp, pp, P(), pp, pp) + extra_tail,
        **shard_map_check_kwargs(),
    )
    def step(mesh_, *rest):
        if scoring:
            *pargs, sbin_, sfac_, flux_, bank_ = rest
        else:
            *pargs, flux_ = rest
        zero_flux = _pvary(jnp.zeros_like(flux_), ax)
        kw = {}
        if scoring:
            kw = {
                "score_kinds": score_kinds,
                "score_ops": (
                    _pvary(jnp.zeros_like(bank_), ax), sbin_, sfac_
                ),
            }
        res = step_fn(
            mesh_, *pargs, zero_flux, tol=tol, max_iters=max_iters,
            walk_kw=walk_kw, **kw,
        )
        x2, elem2, dflux, local_done, local_s = res[:5]
        flux_out = flux_ + lax.psum(dflux, ax)
        if scoring:
            return (x2, elem2, flux_out, local_done, local_s,
                    bank_ + lax.psum(res[5], ax))
        return x2, elem2, flux_out, local_done, local_s

    if scoring:
        bank, sbin, sfac = score_ops
        return step(mesh, *particle_args, sbin, sfac, flux, bank)
    return step(mesh, *particle_args, flux)


@partial(
    jax.jit,
    static_argnames=(
        "device_mesh", "tol", "max_iters", "walk_kw", "score_kinds",
    ),
)
def sharded_move_step(
    device_mesh: Mesh,
    mesh: TetMesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    origins: jnp.ndarray,
    dests: jnp.ndarray,
    flying: jnp.ndarray,
    weights: jnp.ndarray,
    flux: jnp.ndarray,
    *,
    tol: float,
    max_iters: int,
    walk_kw: tuple = (),
    score_kinds: tuple = (),
    score_ops=None,
):
    """One two-phase MoveToNextLocation over the device mesh."""
    from pumiumtally_tpu.api.tally import move_step

    return _sharded_tally_step(
        device_mesh, move_step, mesh,
        (x, elem, origins, dests, flying, weights), flux, tol, max_iters,
        walk_kw=walk_kw, score_kinds=score_kinds, score_ops=score_ops,
    )


@partial(
    jax.jit,
    static_argnames=(
        "device_mesh", "tol", "max_iters", "walk_kw", "score_kinds",
    ),
)
def sharded_move_step_continue(
    device_mesh: Mesh,
    mesh: TetMesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    dests: jnp.ndarray,
    flying: jnp.ndarray,
    weights: jnp.ndarray,
    flux: jnp.ndarray,
    *,
    tol: float,
    max_iters: int,
    walk_kw: tuple = (),
    score_kinds: tuple = (),
    score_ops=None,
):
    """Phase-B-only sharded move: transport straight from the committed
    (sharded) state — the ``origins=None`` fast path of the API (see
    ``api.tally.move_step_continue``)."""
    from pumiumtally_tpu.api.tally import move_step_continue

    return _sharded_tally_step(
        device_mesh, move_step_continue, mesh,
        (x, elem, dests, flying, weights), flux, tol, max_iters,
        walk_kw=walk_kw, score_kinds=score_kinds, score_ops=score_ops,
    )


# Retrace accounting (tests/conftest.py tripwire + bench compile
# column): the sharded walk has the same one-compile-per-shape contract
# as the monolithic one. Rebinds, not bare calls — only calls through
# the returned counting wrapper are counted, and the facades import
# these names.
sharded_move_step = register_entry_point("sharded_walk", sharded_move_step)
sharded_move_step_continue = register_entry_point(
    "sharded_walk_continue", sharded_move_step_continue
)
sharded_localize_step = register_entry_point(
    "sharded_localize", sharded_localize_step
)
sharded_locate = register_entry_point("sharded_locate", sharded_locate)
