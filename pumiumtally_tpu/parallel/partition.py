"""Partitioned-mesh mode: element ownership + particle migration.

The TPU-native form of the reference's mesh-partition parallelism
(SURVEY.md §2.3): PUMIPic's ``picparts`` assigns every element an owner
rank and ``search(migrate)`` ships particles that crossed a partition
boundary to the owning rank, rebuilding the particle structure
(reference PumiTallyImpl.cpp:530-539 builds the partition — with
all-zeros owners as shipped — and cpp:111,145 set the migration cadence).
Here:

- **Ownership** comes from a recursive coordinate bisection (RCB) over
  element centroids — balanced contiguous blocks per chip, computed once
  on the host (replaces EnGPar/owner files).
- **Per-chip mesh shard**: elements are renumbered so each chip's block
  is contiguous and padded to a common length L; the packed walk table
  (mesh/tetmesh.py) is rebuilt per chip with LOCAL adjacency: a face
  entry is a local element id, ``-1`` for the domain boundary (vacuum
  BC), or ``-(glid+2)`` for a neighbor owned by another chip, where
  ``glid = owner·L + local_id`` is the padded global id.
- **Local walk** (`walk_local`): the same masked lock-step ray/tet walk
  as ops/walk.py, but a particle whose exit face is remote PAUSES at
  the partition face (its partial track length is already tallied) and
  records the target glid in ``pending``.
- **Migration** (`migrate`): a SORT-FREE rank/scatter that moves paused
  particles to their owning chip's slot range — each slot's destination
  is its stable within-target counting rank (ops/bucketize.py), so the
  whole shuffle is one packed scatter (the seed paid a full-capacity
  stable argsort plus a permutation gather per round). Under jit over a
  sharded mesh this lowers to the all-to-all/collective-permute the
  reference gets from MPI. Slots are over-provisioned by
  ``capacity_factor``; overflow raises rather than silently dropping.
  In-loop rounds can run FRONTIER-LOCAL (``TallyConfig.cap_frontier``):
  only the rows that actually paused move, through a static slab, with
  stayers fixed in place and a bitwise full-capacity fallback when the
  crossing front overflows the slab (``_frontier_migrate_impl``;
  docs/DESIGN.md "Frontier-local migration").
- **Flux** is owned: each chip accumulates only elements it owns, so no
  cross-chip reduction is needed at all (the ICI traffic is particle
  migration) and the result is deterministic by construction.

Localization (CopyInitialPosition) is SHARDED point location, not a
replicated walk: the reference walks every particle from element 0's
centroid to its source point only because it has no search structure
(PumiTallyImpl.cpp:492-528) — the observable contract is just "each
particle ends in the element containing its source point, zero flux".
Here every chip tests all source points against its OWN elements' four
face planes — one [C,3]×[3,4L] matmul per point chunk, MXU-shaped —
and claims the points it contains; claims are combined with a single
``pmin`` over the mesh axis (ties on shared faces resolve to the lowest
padded global id, deterministically). No [E]-sized replicated array is
touched, and an all-particles-in-one-element start cannot overflow a
single chip's slots the way a literal walk-from-element-0 would.
"""

from __future__ import annotations

import dataclasses
import warnings
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pumiumtally_tpu.mesh.tetmesh import (
    TetMesh,
    WALK_PLANE_WIDTH,
    WALK_TABLE_ADJ,
    WALK_TABLE_LO_WIDTH,
    WALK_TABLE_NORMALS,
    WALK_TABLE_OFFSETS,
)
from pumiumtally_tpu.ops.bucketize import (
    PARTITION_METHODS,
    counting_ranks,
    partition_perm,
    unpermute,
)
from pumiumtally_tpu.ops.geometry import locate_chunk_by_planes
from pumiumtally_tpu.ops.walk import (
    _MIN_WINDOW,
    COND_EVERY_DEFAULT,
    fused_tally_body,
    refine_face_hi,
    score_pair,
    select_faces_lo,
)
from pumiumtally_tpu.parallel.sharded import _axis_name, shard_map_check_kwargs
from pumiumtally_tpu.scoring.binding import ScoreOps
from pumiumtally_tpu.utils.profiling import phase_timer, register_entry_point

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


# ---------------------------------------------------------------------------
# Host-side partition build
# ---------------------------------------------------------------------------

def rcb_partition(centroids: np.ndarray, nparts: int) -> np.ndarray:
    """owner[E] via recursive coordinate bisection of element centroids.

    Splits along the longest axis into two parts whose target sizes are
    proportional to the number of leaves on each side, so any nparts
    (not just powers of two) comes out balanced to ±1.
    """
    ne = centroids.shape[0]
    owner = np.zeros(ne, dtype=np.int32)

    def rec(idx: np.ndarray, first_part: int, nparts: int) -> None:
        if nparts == 1:
            owner[idx] = first_part
            return
        nl = nparts // 2
        nr = nparts - nl
        c = centroids[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        split = int(round(len(idx) * nl / nparts))
        rec(idx[order[:split]], first_part, nl)
        rec(idx[order[split:]], first_part + nl, nr)

    rec(np.arange(ne), 0, nparts)
    return owner


PLACEMENTS = ("linear", "pod_rcb")


def pod_rcb_partition(
    centroids: np.ndarray, nparts: int, host_parts
) -> np.ndarray:
    """owner[E] via HIERARCHICAL recursive coordinate bisection: hosts
    first, then parts within each host (round 19, docs/DESIGN.md
    "Topology-aware placement").

    ``host_parts`` lists how many of the ``nparts`` parts each host
    owns, in mesh device order (hosts own contiguous part ranges —
    ``derive_host_counts`` enforces the device-order contiguity this
    rests on). The element set is first bisected recursively across the
    HOST list, each cut sized proportional to the part counts on either
    side, then flat-RCB'd within each host's region — so spatially
    adjacent parts land on the same host except across the few
    host-region boundaries, and cross-host particle migration is
    confined to where the mesh geometry actually crosses hosts.

    Split arithmetic (axis choice, stable argsort, proportional
    rounding) is IDENTICAL to ``rcb_partition``; when every host
    boundary aligns with the flat binary recursion tree (e.g. two equal
    hosts — the top flat split IS the host boundary) the two functions
    are bitwise-equal, which is the degeneracy pin in
    tests/test_placement.py. They differ exactly when a host boundary
    is misaligned (unequal hosts), where the flat tree would cut
    through a host's region.
    """
    host_parts = [int(h) for h in host_parts]
    if any(h < 1 for h in host_parts) or sum(host_parts) != nparts:
        raise ValueError(
            f"host_parts {host_parts} must be positive and sum to the "
            f"{nparts}-part partition"
        )
    ne = centroids.shape[0]
    owner = np.zeros(ne, dtype=np.int32)

    def split(idx: np.ndarray, nl: int, nr: int):
        c = centroids[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        at = int(round(len(idx) * nl / (nl + nr)))
        return idx[order[:at]], idx[order[at:]]

    def rec_parts(idx: np.ndarray, first_part: int, np_h: int) -> None:
        if np_h == 1:
            owner[idx] = first_part
            return
        nl = np_h // 2
        li, ri = split(idx, nl, np_h - nl)
        rec_parts(li, first_part, nl)
        rec_parts(ri, first_part + nl, np_h - nl)

    def rec_hosts(idx: np.ndarray, hosts, first_part: int) -> None:
        if len(hosts) == 1:
            rec_parts(idx, first_part, hosts[0])
            return
        nh = len(hosts) // 2
        left, right = hosts[:nh], hosts[nh:]
        li, ri = split(idx, sum(left), sum(right))
        rec_hosts(li, left, first_part)
        rec_hosts(ri, right, first_part + sum(left))

    rec_hosts(np.arange(ne), host_parts, 0)
    return owner


@dataclasses.dataclass(frozen=True)
class MeshPartition:
    """Per-chip mesh shards + id mappings (host-built, device-resident)."""

    ndev: int
    nelems: int  # original element count E
    L: int  # padded per-chip element count
    owner: np.ndarray  # [E] original elem -> chip
    glid_of_orig: Any  # [E] int32, original elem -> padded global id
    orig_of_glid: Any  # [ndev*L] int32, padded global id -> orig elem (-1 pad)
    # The SELECT-tier walk rows the per-crossing gather touches:
    # [ndev*L, 20] float packed rows (adj local-encoded) in the
    # single-tier layout, or [ndev*L, WALK_TABLE_LO_WIDTH] bf16 plane
    # rows when the partition was built two-tier (table_hi non-None;
    # adjacency then rides the refinement rows' adj lane).
    table: Any
    # Non-None when the padded id range exceeds what the float dtype
    # represents exactly (f32 past 2^24): adjacency then lives in its
    # own int32 array and the table's adj lanes are unused. Costs the
    # walk a second (4-int) gather per iteration but removes the mesh
    # size ceiling — a ~2M-tet f32 mesh on 8 chips builds fine.
    adj_int: Any = None  # [ndev*L, 4] int32 local-encoded adjacency
    # Two-tier refinement tier (docs/PERF_NOTES.md "Table precision
    # tiers"): full-precision per-face (plane, local-encoded adj) rows,
    # row glid*4 + f, gathered ONCE per crossing for the winning face
    # only.
    table_hi: Any = None  # [ndev*L*4, WALK_PLANE_WIDTH]
    # Directed cross-part face census [(src_part, dst_part, nfaces)],
    # host numpy (round 19): the static input of the modeled cross-host
    # migration-bytes diagnostic (distributed.py
    # modeled_cross_host_migration_bytes). Host-side only — no device
    # allocation rides on it.
    remote_faces: Any = None  # [K, 3] int64

    def flux_to_original(self, flux_padded: jnp.ndarray) -> jnp.ndarray:
        """Reorder an owned [ndev*L] flux into original element order."""
        return flux_padded[self.glid_of_orig]


def derive_blocks_per_chip(
    nelems: int, ndev: int, vmem_walk_max_elems: Optional[int]
) -> int:
    """Blocks per chip for the VMEM sub-split: the smallest k whose
    balanced ndev*k-way partition keeps every block within the VMEM
    bound (RCB is balanced ±1, so ceil(E/nparts) bounds the padded
    block length). 1 when the knob is unset."""
    if vmem_walk_max_elems is None:
        return 1
    return max(
        1, -(-int(nelems) // (int(ndev) * int(vmem_walk_max_elems)))
    )


def resolve_block_kernel(block_kernel: str, table_dtype: str) -> str:
    """The block kernel a partition actually runs.

    The vmem one-hot kernel has no two-tier lowering (bf16 adjacency
    lanes are impossible — 8 mantissa bits — and a resident f32
    refinement operand would give back the VMEM the select tier saved;
    see ops/vmem_walk.py), so bf16 partitions route blocked walks
    through the GATHER block kernel, whose resident-block benefit is
    exactly what the half-width select tier doubles. Since round 17
    that reroute is a LOGGED diagnostic, not a silent downgrade: the
    two-tier one-kernel walk exists (``walk_kernel='pallas'``,
    ops/pallas_walk.py) and is the intended destination for bf16
    blocked configurations.

    ``"pallas"`` is two-tier ONLY — its select fetch is a bf16 matmul
    and its refinement operand is the per-face tier — so a float32
    partition cannot run it; that mismatch is a configuration error,
    not a reroute (TallyConfig validates the same pair earlier with
    the config-level message; this guard catches engine-level callers
    and prebuilt-partition overrides)."""
    if block_kernel == "pallas":
        if table_dtype != "bfloat16":
            raise ValueError(
                "block_kernel='pallas' needs the bf16 two-tier tables "
                f"(got table_dtype={table_dtype!r}); build the "
                "partition with table_dtype='bfloat16'"
            )
        return block_kernel
    if table_dtype == "bfloat16" and block_kernel == "vmem":
        from pumiumtally_tpu.utils.logging import get_logger

        get_logger().info(
            "bfloat16 tables with block_kernel='vmem': the vmem "
            "kernel has no two-tier lowering — rerouting blocked "
            "walks to the gather kernel (set walk_kernel='pallas' "
            "for the two-tier one-kernel walk, ops/pallas_walk.py)"
        )
        return "gather"
    return block_kernel


def block_elems_bound(
    vmem_walk_max_elems: Optional[int], table_dtype: str
) -> Optional[int]:
    """The per-block ELEMENT bound the sub-split derives blocks from.
    The knob is calibrated in f32-table resident bytes (80 B/elem); the
    bf16 select tier is 32 B/elem, so the same byte budget covers 2x
    the elements — block tables at 2x L, halving block count and with
    it the migration-round pressure (the lattice's 45-round problem,
    docs/PERF_NOTES.md)."""
    if vmem_walk_max_elems is None:
        return None
    if table_dtype == "bfloat16":
        return int(vmem_walk_max_elems) * 2
    return int(vmem_walk_max_elems)


def build_partition(
    mesh: TetMesh,
    ndev: int,
    dtype: Optional[Any] = None,
    force_split_adj: bool = False,
    table_dtype: str = "float32",
    placement: str = "linear",
    hosts=None,
) -> MeshPartition:
    """Partition ``mesh`` into ``ndev`` contiguous padded element blocks.

    ``force_split_adj`` stores adjacency as int32 out-of-row even when
    the float dtype could hold it exactly (the automatic fallback for
    big f32 meshes, forced for testing). ``table_dtype="bfloat16"``
    builds the two-tier per-chip tables: ``table`` becomes the bf16
    select tier and ``table_hi`` the full-precision per-face
    refinement tier, whose adj lane carries the local-encoded neighbor
    (one 20 B gather serves refinement AND adjacency) — ids must
    therefore fit the float dtype exactly, the SAME ceiling as the
    packed in-row encoding; past it the two-tier build refuses (use
    the f32 layout, whose int32 sidecar has no ceiling).

    ``placement`` (round 19): ``"linear"`` (default) keeps the flat
    ``rcb_partition`` ownership — bitwise-identical to every earlier
    build; ``"pod_rcb"`` bisects across ``hosts`` first (per-PART
    counts, device order) so cross-host adjacency is confined to where
    the host geometry cuts the mesh (``pod_rcb_partition``).
    """
    if placement not in PLACEMENTS:
        raise ValueError(
            f"placement must be one of {PLACEMENTS}, got {placement!r}"
        )
    if dtype is None:
        dtype = mesh.coords.dtype
    coords = np.asarray(mesh.coords, dtype=np.float64)
    tet2vert = np.asarray(mesh.tet2vert)
    face_adj = np.asarray(mesh.face_adj)
    normals = np.asarray(mesh.face_normals, dtype=np.float64)
    offsets = np.asarray(mesh.face_offsets, dtype=np.float64)
    ne = tet2vert.shape[0]
    centroids = coords[tet2vert].mean(axis=1)

    if placement == "pod_rcb":
        if hosts is None:
            raise ValueError(
                "placement='pod_rcb' needs hosts= (per-host part "
                "counts in device order)"
            )
        owner = pod_rcb_partition(centroids, ndev, hosts)
    else:
        owner = rcb_partition(centroids, ndev)
    counts = np.bincount(owner, minlength=ndev)
    L = int(counts.max())
    # Remote faces encode -(glid+2) with glid < ndev*L, so THAT is the
    # magnitude that must survive a float walk-table round-trip; past
    # the exact-id limit adjacency moves to a separate int32 array.
    two_tier = table_dtype == "bfloat16"
    if two_tier and force_split_adj:
        raise ValueError(
            "force_split_adj is incompatible with table_dtype="
            "'bfloat16': two-tier partitions carry adjacency in the "
            "refinement rows' float lane, never in an int32 sidecar"
        )
    ids_fit = (
        ndev * L + 2 < 2 ** (np.finfo(np.dtype(dtype)).nmant + 1)
    )
    if two_tier and not ids_fit:
        raise ValueError(
            f"two-tier partition tables store local-encoded neighbor "
            f"ids in {np.dtype(dtype).name} refinement rows; "
            f"{ndev}x{L} padded elements exceed the exact-id range "
            "(use walk_table_dtype='float32', whose int32 adjacency "
            "sidecar has no ceiling)"
        )
    split_adj = force_split_adj or not ids_fit

    # Renumber: elements of chip d occupy glids [d*L, d*L+counts[d]).
    order = np.argsort(owner, kind="stable")  # orig elems grouped by owner
    rank_in_chip = np.empty(ne, dtype=np.int64)
    start = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank_in_chip[order] = np.arange(ne) - start[owner[order]]
    glid_of_orig = owner.astype(np.int64) * L + rank_in_chip
    orig_of_glid = np.full(ndev * L, -1, dtype=np.int32)
    orig_of_glid[glid_of_orig] = np.arange(ne, dtype=np.int32)

    # Local adjacency encoding per face.
    nb = face_adj  # [E,4] original ids, -1 boundary
    nb_owner = np.where(nb >= 0, owner[np.clip(nb, 0, ne - 1)], -1)
    nb_glid = np.where(nb >= 0, glid_of_orig[np.clip(nb, 0, ne - 1)], -1)
    # Directed cross-part face census — how many element faces part a
    # exposes to part b. Placement-dependent (the whole point of
    # pod_rcb) and the static migration-volume proxy behind
    # distributed.modeled_cross_host_migration_bytes. Host numpy only.
    cross = (nb >= 0) & (nb_owner != owner[:, None])
    pair_key = owner[:, None].astype(np.int64) * ndev + nb_owner
    pair, nfaces = np.unique(pair_key[cross], return_counts=True)
    remote_faces = np.stack([pair // ndev, pair % ndev, nfaces], axis=1)
    same = nb_owner == owner[:, None]
    local_adj = np.where(
        nb < 0,
        -1,
        np.where(same, nb_glid - owner[:, None].astype(np.int64) * L,
                 -(nb_glid + 2)),
    ).astype(np.float64)

    # Padded per-chip walk table; padding rows have no crossing faces
    # (zero normals -> t_exit=inf -> 'reached') and are never entered.
    adj_full = np.full((ndev * L, 4), -1.0)
    adj_full[glid_of_orig] = local_adj
    adj_int = None
    table_hi = None
    if two_tier:
        # Select tier: the half-width bf16 plane rows (32 B vs 80 B
        # per crossing gather). Refinement tier: per-FACE full-
        # precision planes + the face's local-encoded neighbor, row
        # glid*4 + f — padding rows keep adj −1 (boundary), though the
        # walk never enters them (zero normals ⇒ no crossing).
        lo = np.zeros((ndev * L, WALK_TABLE_LO_WIDTH), dtype=np.float64)
        lo[glid_of_orig, 0:12] = normals.reshape(ne, 12)
        lo[glid_of_orig, 12:16] = offsets
        hi = np.zeros((ndev * L, 4, WALK_PLANE_WIDTH), dtype=np.float64)
        hi[:, :, 4] = adj_full
        hi[glid_of_orig, :, 0:3] = normals
        hi[glid_of_orig, :, 3] = offsets
        table = jnp.asarray(lo, dtype=jnp.bfloat16)
        table_hi = jnp.asarray(
            hi.reshape(ndev * L * 4, WALK_PLANE_WIDTH), dtype=dtype
        )
    else:
        table_np = np.zeros((ndev * L, 20), dtype=np.float64)
        table_np[glid_of_orig, WALK_TABLE_NORMALS] = normals.reshape(ne, 12)
        table_np[glid_of_orig, WALK_TABLE_OFFSETS] = offsets
        if split_adj:
            adj_int = jnp.asarray(adj_full.astype(np.int32))
        else:
            table_np[:, WALK_TABLE_ADJ] = adj_full
        table = jnp.asarray(table_np, dtype=dtype)

    return MeshPartition(
        ndev=ndev,
        nelems=ne,
        L=L,
        owner=owner,
        glid_of_orig=jnp.asarray(glid_of_orig, jnp.int32),
        orig_of_glid=jnp.asarray(orig_of_glid),
        table=table,
        adj_int=adj_int,
        table_hi=table_hi,
        remote_faces=remote_faces,
    )


# ---------------------------------------------------------------------------
# Device-side local walk (per chip, inside shard_map)
# ---------------------------------------------------------------------------

def walk_local(
    table: jnp.ndarray,  # [L,20] this chip's walk rows
    x: jnp.ndarray,  # [S,3]
    lelem: jnp.ndarray,  # [S] local element ids
    dest: jnp.ndarray,  # [S,3]
    flying: jnp.ndarray,  # [S] int8
    weight: jnp.ndarray,  # [S]
    done: jnp.ndarray,  # [S] bool — finished this phase
    exited: jnp.ndarray,  # [S] bool
    flux: jnp.ndarray,  # [L] owned flux
    *,
    tally: bool,
    tol: float,
    max_iters: int,
    adj_int: Optional[jnp.ndarray] = None,  # [L,4] when ids don't fit the float
    cond_every: int = COND_EVERY_DEFAULT,
    compact: bool = True,
    min_window: int = _MIN_WINDOW,
    partition_method: str = "rank",
    table_hi: Optional[jnp.ndarray] = None,  # [L*4,5] two-tier refinement
    scoring=None,  # ScoreOps over THIS slice's [L·B·S] bank
) -> Tuple[jnp.ndarray, ...]:
    """Ownership-restricted walk: like ops.walk.walk but pauses (sets
    ``pending = glid``) when the exit face's neighbor lives on another
    chip. Returns (x, lelem, done, exited, pending, flux, iters), plus
    the accumulated score bank as an EIGHTH element when ``scoring``
    (a ``scoring.ScoreOps`` whose bank/bin_off/fac are this slice's
    local views) is armed — the same segment-commit hook as the
    replicated walk (ops/walk.py ``score_pair``), scattering each
    crossing group's lane updates in ONE fused deterministic
    scatter-add beside the untouched flux scatter. A pause at a
    partition face commits its crossing (and its event count) exactly
    once: the resumed round continues from the pause point and never
    recounts it, so binned scores agree with the replicated engines.

    ``table_hi`` switches to the two-tier path (docs/PERF_NOTES.md
    "Table precision tiers"): ``table`` is then the bf16 SELECT tier
    ([L,WALK_TABLE_LO_WIDTH] plane rows), the exit face is picked from
    it, and the winning face's crossing AND local-encoded neighbor
    come from ONE full-precision ``table_hi`` row before committing —
    the same select-in-bf16 / commit-in-f32 contract as the replicated
    walk (shared helpers ops/walk.py select_faces_lo / refine_face_hi).
    ``adj_int`` is then unused (the refinement row carries adjacency).

    Parametrized by the ray coordinate ``s`` along this ROUND's fixed
    segment ``x → dest`` (see ops/walk.py): both face projections are
    against walk-constant vectors, positions materialize once at the
    end. A migrated particle starts a fresh round (and a fresh ray)
    from its pause point, so ``s`` never crosses a migration.

    Known benign divergence from the replicated walk: a destination
    lying exactly ON a tet face can commit a different (face-adjacent)
    final element here, because the restarted ray's rounding resolves
    the reached-vs-crossed tie differently after a migration. Committed
    positions and flux are identical either way — the next move walks
    the same geometry from the shared face — so only the elem_ids view
    differs, and only for on-face destinations.

    ``cond_every`` mirrors ops.walk.walk: k masked iterations per while
    step with the group's tally pairs fused into one scatter-add
    (done/paused particles are inert under the active mask).

    ``compact`` bounds lock-step waste within a round with the same
    window cascade as the replicated walk (ops/walk.py), in its
    "indirect" form: the per-slot ray invariants (x0, d0, eff_w) are
    packed once and never permuted — the loop gathers them through the
    carried original-slot index — and each stage boundary permutes only
    s plus one packed int row (lelem, pending, idx, done/exited bits).
    Inert slots here include PAUSED ones (they wait for migration), so
    the cascade retires both early finishers and early pausers: the
    stage boundary is a stable SORT-FREE ternary partition
    (active / paused / done, counting ranks — ops/bucketize.py) and the
    final restore to original slot order (migration depends on the
    slot → chip layout) is a direct scatter through the carried slot
    index, not an argsort. ``partition_method`` ("rank"/"argsort")
    switches the rank computation for parity tests and A/B — both
    yield the identical permutation, hence bitwise-identical results.
    """
    if partition_method not in PARTITION_METHODS:
        raise ValueError(
            f"partition_method must be one of {PARTITION_METHODS}, "
            f"got {partition_method!r}"
        )
    fdtype = x.dtype
    one = jnp.asarray(1.0, fdtype)
    flying_b = flying.astype(bool)
    n_slots = x.shape[0]
    x0 = x
    d0 = dest - x0
    seg_len = jnp.linalg.norm(d0, axis=1)
    s0 = jnp.zeros_like(seg_len)
    # flying/weight/seg_len enter the loop only through the tally
    # contribution — premultiply once (associativity-only, ~1 ulp).
    eff_w = jnp.where(flying_b, weight * seg_len, 0.0)
    # Derived from an input so it carries the varying type under
    # shard_map (a literal constant would break the while carry).
    pending0 = (lelem - lelem) - 1
    score_on = scoring is not None
    if score_on:
        if not tally:
            raise ValueError("scoring requires a tallying walk")
        s_kinds = scoring.kinds
        s_stride = scoring.bank.shape[0] // flux.shape[0]
        sb0, sf0, bank = scoring.bin_off, scoring.fac, scoring.bank

    def advance(s, lelem, done, exited, pending, x0_c, d0_c, eff_c,
                sb=None, sf=None):
        active = ~done & (pending < 0)
        if table_hi is not None:
            # Two-tier: bf16 select + full-precision single-face refine
            # (helpers shared with ops/walk.py so the selection
            # semantics cannot drift between engines; they take the
            # dest-based projection, so rebuild dest from the carried
            # ray invariants). The refinement row also carries the
            # winning face's local-encoded neighbor — no adjacency
            # gather, no take-along-axis.
            dest_c = x0_c + d0_c
            s_sel, f_exit = select_faces_lo(
                table, s, lelem, dest_c, d0_c, tol, one
            )
            s_exit, nxt = refine_face_hi(
                table_hi, s, lelem, f_exit, s_sel, dest_c, d0_c, tol, one
            )
        else:
            row = table[lelem]
            n = row.shape[0]
            fn = row[:, WALK_TABLE_NORMALS].reshape(n, 4, 3)
            fo = row[:, WALK_TABLE_OFFSETS]
            if adj_int is not None:
                adj = adj_int[lelem]
            else:
                adj = row[:, WALK_TABLE_ADJ].astype(jnp.int32)
            both = jnp.einsum(
                "nfc,nck->nfk", fn, jnp.stack([d0_c, x0_c], axis=-1)
            )
            a = both[..., 0]
            b = fo - both[..., 1]
            crossing = a * (one - s)[:, None] > tol
            s_f = jnp.where(crossing, b / jnp.where(crossing, a, one), jnp.inf)
            s_f = jnp.maximum(s_f, s[:, None])
            s_exit = jnp.min(s_f, axis=1)
            f_exit = jnp.argmin(s_f, axis=1)
            nxt = jnp.take_along_axis(adj, f_exit[:, None], axis=1)[:, 0]
        reached = s_exit >= one
        s_new = jnp.where(reached, one, s_exit)
        hit_boundary = (~reached) & (nxt == -1)
        goes_remote = (~reached) & (nxt <= -2)

        if tally:
            contrib = jnp.where(active, (s_new - s) * eff_c, 0.0)
            if score_on:
                # A committed crossing here includes the partition-face
                # pause (goes_remote): the face IS crossed, exactly
                # once across the migration.
                crossed = (active & ~reached).astype(contrib.dtype)
                sidx, sval = score_pair(
                    s_kinds, s_stride, lelem, sb, sf, contrib, crossed
                )
                pair = (lelem, contrib, sidx, sval)
            else:
                pair = (lelem, contrib)
        else:
            pair = None

        moving = active & ~reached & ~hit_boundary & ~goes_remote
        lelem = jnp.where(moving, nxt, lelem)
        s = jnp.where(active, s_new, s)
        pending = jnp.where(active & goes_remote, -nxt - 2, pending)
        done = done | (active & (reached | hit_boundary))
        exited = exited | (active & hit_boundary)
        return (s, lelem, done, exited, pending), pair

    it0 = jnp.asarray(0, jnp.int32)

    min_window = max(1, int(min_window))  # same clamp as ops/walk.py
    if not compact or n_slots <= min_window:
        def step(it, s, lelem, done, exited, pending):
            st, pair = advance(
                s, lelem, done, exited, pending, x0, d0, eff_w,
                sb0 if score_on else None, sf0 if score_on else None,
            )
            return (it + 1, *st), pair

        def cond(state):
            it, done, pending = state[0], state[3], state[5]
            return (it < max_iters) & jnp.any(~done & (pending < 0))

        body = fused_tally_body(step, cond_every, tally, scoring=score_on)
        carry = (it0, s0, lelem, done, exited, pending0, flux)
        if score_on:
            (it, s, lelem, done, exited, pending, flux,
             bank) = lax.while_loop(cond, body, carry + (bank,))
        else:
            it, s, lelem, done, exited, pending, flux = lax.while_loop(
                cond, body, carry
            )
        x_fin = jnp.where(
            (done & ~exited)[:, None], dest, x0 + s[:, None] * d0
        )
        out = (x_fin, lelem, done, exited, pending, flux, it)
        return out + (bank,) if score_on else out

    # ---- compaction cascade (indirect form) ----------------------------
    # NOTE: deliberately parallel to ops/walk.py's cascade (different
    # carries: pending/exited, pause-aware inertness, slot-order
    # restore) — any fix to the schedule/permute/restore machinery or
    # the concatenate-not-at[].set miscompile workaround there must be
    # mirrored here, and vice versa.
    windows = [n_slots]
    while windows[-1] > min_window:
        windows.append(max(min_window, -(-windows[-1] // 2)))
    # Ray invariants in ORIGINAL slot order, never permuted; padded to 8
    # columns to keep the row stride aligned.
    ray = jnp.concatenate(
        [x0, d0, eff_w[:, None], jnp.zeros_like(eff_w)[:, None]], axis=1
    )  # [S,8]
    idx = jnp.cumsum(jnp.ones_like(lelem)) - 1  # varying under shard_map
    cat = lambda h, a, w: jnp.concatenate([h, a[w:]], axis=0)  # noqa: E731

    s, done, exited, pending, it = s0, done, exited, pending0, it0
    for si, w in enumerate(windows):
        nxt_w = windows[si + 1] if si + 1 < len(windows) else 0
        head = lambda a, _w=w: a[:_w]  # noqa: E731 — static window slice
        idx_w = head(idx)
        # Scoring rows are per-slot walk-constants like the ray pack:
        # never permuted, gathered once per stage through idx.
        sb_w = sb0[idx_w] if score_on else None
        sf_w = sf0[idx_w] if score_on else None

        def step(it, s, lelem, done, exited, pending, _idx=idx_w,
                 _sb=sb_w, _sf=sf_w):
            r = ray[_idx]
            st, pair = advance(
                s, lelem, done, exited, pending, r[:, 0:3], r[:, 3:6],
                r[:, 6], _sb, _sf,
            )
            return (it + 1, *st), pair

        def cond(state, _nxt=nxt_w):
            it = state[0]
            done, pending = state[3], state[5]
            return (it < max_iters) & (jnp.sum(~done & (pending < 0)) > _nxt)

        body = fused_tally_body(step, cond_every, tally, scoring=score_on)
        carry = (it, head(s), head(lelem), head(done), head(exited),
                 head(pending), flux)
        if score_on:
            it, sh, eh, dh, exh, ph, flux, bank = lax.while_loop(
                cond, body, carry + (bank,)
            )
        else:
            it, sh, eh, dh, exh, ph, flux = lax.while_loop(
                cond, body, carry
            )
        # Window write-backs use concatenate, not at[].set — see the
        # miscompile note in ops/walk.py's cascade.
        if nxt_w:
            # Stable ternary partition, SORT-FREE: active slots to the
            # front, then paused (waiting for migration), then done —
            # counting ranks reproduce the stable-argsort permutation
            # of this key exactly, so no argsort runs per stage.
            key = jnp.where(dh, 2, jnp.where(ph >= 0, 1, 0))
            perm, _, _ = partition_perm(
                key, 3, method=partition_method
            )
            ip = jnp.stack(
                [eh, ph, idx[:w], dh.astype(jnp.int32)
                 + 2 * exh.astype(jnp.int32)],
                axis=1,
            )[perm]  # [w,4] — one row gather for the int carries
            s = cat(sh[perm], s, w)
            lelem = cat(ip[:, 0], lelem, w)
            pending = cat(ip[:, 1], pending, w)
            idx = cat(ip[:, 2], idx, w)
            done = cat((ip[:, 3] & 1) == 1, done, w)
            exited = cat(ip[:, 3] >= 2, exited, w)
        else:
            s = cat(sh, s, w)
            lelem = cat(eh, lelem, w)
            done = cat(dh, done, w)
            exited = cat(exh, exited, w)
            pending = cat(ph, pending, w)

    # Restore original slot order (migration depends on the slot→chip
    # layout): row i holds original slot idx[i], so one scatter through
    # idx IS the inverse permutation — no argsort(idx). x materializes
    # directly in original order since x0/d0 were never permuted.
    s, lelem = unpermute(s, idx), unpermute(lelem, idx)
    done, exited = unpermute(done, idx), unpermute(exited, idx)
    pending = unpermute(pending, idx)
    x_fin = jnp.where((done & ~exited)[:, None], dest, x0 + s[:, None] * d0)
    out = (x_fin, lelem, done, exited, pending, flux, it)
    return out + (bank,) if score_on else out


# ---------------------------------------------------------------------------
# Global migration (jit-level; XLA inserts the collectives)
# ---------------------------------------------------------------------------

def _pack_state(state: dict, defaults: dict):
    """Split a particle-state dict into ONE float matrix and ONE int32
    matrix (plus the metadata to undo it), so a permutation/scatter of
    the whole state costs two row operations instead of ~10 per-array
    ones — the same packing trick as the walk table and the cascade's
    stage boundaries. Ids stay in the int pack (int32-exact), never in
    floats, so no 2^24 exactness ceiling applies."""
    fcols, icols, layout = [], [], []
    foff = ioff = 0  # COLUMN offsets into each pack
    for k in sorted(state):
        v = state[k]
        cols = v.reshape(v.shape[0], -1) if v.ndim > 1 else v[:, None]
        if jnp.issubdtype(v.dtype, jnp.floating):
            layout.append((k, "f", foff, cols.shape[1], v.dtype, v.shape[1:]))
            fcols.append(cols)
            foff += cols.shape[1]
        else:
            layout.append((k, "i", ioff, cols.shape[1], v.dtype, v.shape[1:]))
            icols.append(cols.astype(jnp.int32))
            ioff += cols.shape[1]
    fpack = jnp.concatenate(fcols, axis=1) if fcols else None
    ipack = jnp.concatenate(icols, axis=1) if icols else None
    fdef, idef = _pack_defaults(defaults, layout)
    return fpack, ipack, fdef, idef, layout


def _pack_defaults(defaults: dict, layout):
    fcols = {}
    icols = {}
    for k, kind, start, ncols, dtype, _tail in layout:
        v = defaults[k]
        cols = v.reshape(v.shape[0], -1) if v.ndim > 1 else v[:, None]
        if kind == "f":
            fcols[start] = cols
        else:
            icols[start] = cols.astype(jnp.int32)
    f = (jnp.concatenate([fcols[s] for s in sorted(fcols)], axis=1)
         if fcols else None)
    i = (jnp.concatenate([icols[s] for s in sorted(icols)], axis=1)
         if icols else None)
    return f, i


def _unpack_state(fpack, ipack, layout) -> dict:
    out = {}
    for k, kind, start, ncols, dtype, tail in layout:
        src = fpack if kind == "f" else ipack
        cols = src[:, start:start + ncols]
        v = cols[:, 0] if not tail else cols.reshape(cols.shape[0], *tail)
        out[k] = v.astype(dtype) if v.dtype != dtype else v
    return out


def _migrate_impl(part_L: int, ndev: int, cap_per_chip: int, state: dict,
                  partition_method: str = "rank"):
    """Trace-level body of ``migrate`` (see below) — also inlined into
    the jitted phase round loop so walk+migrate rounds compile as ONE
    program with no per-round host sync.

    SORT-FREE: each slot's destination is computed IN PLACE from its
    stable within-target rank (counting ranks, ops/bucketize.py) —
    ``dest = target·cap + rank`` — and the packed state matrices
    scatter straight to those destinations. The seed paid a
    full-capacity stable argsort PLUS a permutation gather per packed
    matrix here (sort → gather → scatter); this is one scatter with the
    bitwise-identical result (same (index, row) pairs — pinned by
    tests/test_partition_rank.py). ``partition_method="argsort"`` keeps
    the old rank computation for parity/A-B."""
    cap = state["pid"].shape[0]
    slot_chip = (jnp.cumsum(jnp.ones_like(state["pid"])) - 1) // cap_per_chip
    pending = state["pending"]
    alive = state["alive"]
    target = jnp.where(pending >= 0, pending // part_L, slot_chip)
    # Dead slots rank after every real group so they never consume a
    # real slot; their state is reset to defaults on the way out.
    key = jnp.where(alive, target, ndev)
    rank = counting_ranks(key, ndev + 1, method=partition_method)
    overflow = jnp.any((key < ndev) & (rank >= cap_per_chip))
    dest_slot = jnp.where(
        key < ndev, key * cap_per_chip + rank, cap
    )  # dead -> out of bounds, dropped by the scatter

    # Move the WHOLE state as two packed matrices (one float, one int)
    # instead of ~10 per-array gather+scatter pairs — scattered
    # DIRECTLY to destination slots, no argsort, no permutation gather.
    fpack, ipack, fdef, idef, layout = _pack_state(
        state, _default_state(cap, state)
    )
    if fpack is not None:
        fpack = fdef.at[dest_slot].set(fpack, mode="drop")
    if ipack is not None:
        ipack = idef.at[dest_slot].set(ipack, mode="drop")
    new_state = _unpack_state(fpack, ipack, layout)
    # Migrated particles resume inside their new chip's local mesh.
    arrived = new_state["pending"] >= 0
    new_state["lelem"] = jnp.where(
        arrived, new_state["pending"] % part_L, new_state["lelem"]
    )
    new_state["pending"] = jnp.where(arrived, -1, new_state["pending"])
    # Overflow-safe commit (round 9): an overflowing scatter collides
    # slots, so the OLD state is kept verbatim instead — the caller
    # commits unconditionally and recovers (retry at full capacity,
    # host-side capacity escalation) from an intact pre-migrate
    # snapshot rather than raising over poisoned slots. Healthy rounds
    # select the new state bitwise (where(False, old, new) == new).
    new_state = {
        k: jnp.where(overflow, state[k], v) for k, v in new_state.items()
    }
    return new_state, overflow


@partial(
    jax.jit,
    static_argnames=("part_L", "ndev", "cap_per_chip", "partition_method"),
)
def migrate(part_L: int, ndev: int, cap_per_chip: int, state: dict,
            partition_method: str = "rank"):
    """Ship paused particles (pending >= 0) to the chip owning their
    target element; everything else stays in its chip's slot range.

    ``state`` is a dict of [cap]-shaped arrays that must travel with the
    particle (x, lelem, pending, done, exited, alive, pid, dest, fly, w).
    Returns (new_state, overflowed) — overflow means some chip received
    more particles than its slot capacity.

    Jitted as ONE program: the rank/scatter over device-sharded arrays
    lowers to a single XLA module (one set of collectives), which both
    performs better and avoids flooding the runtime with per-op
    rendezvous (observed to trip XLA:CPU's 40s collective timeout when
    issued eagerly op-by-op on 8 virtual devices).
    """
    return _migrate_impl(part_L, ndev, cap_per_chip, state,
                         partition_method)


def _default_state(cap: int, like: dict) -> dict:
    d = {}
    for k, v in like.items():
        if k == "alive":
            d[k] = jnp.zeros((cap,), bool)
        elif k == "done":
            d[k] = jnp.ones((cap,), bool)
        elif k in ("pending", "pid"):
            d[k] = jnp.full((cap,), -1, v.dtype)
        else:
            d[k] = jnp.zeros((cap,) + v.shape[1:], v.dtype)
    return d


def _occupancy_counts(done: jnp.ndarray, nparts: int) -> jnp.ndarray:
    """[nparts] count of not-done slots per part — the occupied-block
    list's ground truth, recomputed with one full-capacity scan. The
    frontier path replaces the per-round call to this with incremental
    departure/arrival deltas (``_update_occupancy``). Pinned int32
    (jnp.sum would promote to the x64 default int) so the two update
    paths carry one type."""
    return jnp.sum(
        (~done).reshape(nparts, -1), axis=1, dtype=jnp.int32
    )


def _frontier_migrate_impl(part_L: int, nparts: int, cap_per_chip: int,
                           cap_frontier: int, state: dict,
                           partition_method: str = "rank"):
    """Frontier-slab migration: per-round cost proportional to the
    CROSSING FRONT, not the capacity.

    ``_migrate_impl`` re-buckets every slot every round: a
    ``(nparts+1)``-bucket counting rank over all ``cap`` slots (the
    one-hot rank slabs scale with ``ceil(nparts/64) · cap``) plus two
    packed full-capacity scatters — even when only a handful of
    particles paused at a partition face. Here the PENDING rows are
    first compacted (stable, sort-free binary partition) into a static
    ``cap_frontier`` slab; the expensive multi-bucket rank and every
    row movement then run at slab size. Placement is STAYER-FIXED:

    - non-pending slots (alive or dead) keep their slots — zero row
      movement for the part of the population that did not cross;
    - departing slots reset to defaults, becoming free;
    - arrivals scatter into their target part's free slots, free slots
      taken in ascending slot order, arrivals ordered by source slot —
      a deterministic, permutation-free destination for every row.

    What remains O(cap) is one int32 bookkeeping lane (the free-slot
    prefix sums and the binary-partition cumsum) — a few bytes per
    slot against ``_migrate_impl``'s full state-row traffic and rank
    slabs (docs/PERF_NOTES.md "Frontier-local migration" cost model).

    The overflow condition is IDENTICAL to ``_migrate_impl``'s: part d
    overflows iff stayers + arrivals > cap_per_chip, i.e. an arrival's
    within-target rank reaches the part's free-slot count. The caller
    must guarantee ``n_pending <= cap_frontier`` (the slab-overflow
    cond in ``_inloop_migrate_step``): rows beyond the slab would be
    left unmigrated, so the full-capacity fallback is mandatory, not
    advisory.

    Returns ``(state, overflow, departures, arrivals)``; the [nparts]
    departure/arrival counts feed the incremental occupied-block
    bookkeeping.
    """
    cap = state["pid"].shape[0]
    pending = state["pending"]
    alive = state["alive"]
    moving = pending >= 0
    iota = jnp.cumsum(jnp.ones_like(pending)) - 1
    slot_chip = iota // cap_per_chip
    # Stable slab compaction: pending rows front-packed in slot order.
    perm, counts, _ = partition_perm(
        (~moving).astype(jnp.int32), 2, method=partition_method
    )
    n_move = counts[0]
    src = perm[:cap_frontier]
    slab_iota = jnp.cumsum(jnp.ones_like(src)) - 1
    valid = slab_iota < n_move
    # Free slots under stayer-fixed placement: never-occupied + the
    # slots departures vacate this round. free_list inverts
    # (part, within-part free rank) -> slot id.
    fint = ((~alive) | moving).astype(jnp.int32)
    excl = jnp.cumsum(fint) - fint
    chip_base = excl.reshape(nparts, cap_per_chip)[:, 0]
    free_rank = excl - chip_base[slot_chip]
    n_free = jnp.sum(fint.reshape(nparts, cap_per_chip), axis=1)
    fdest = jnp.where(
        fint == 1, slot_chip * cap_per_chip + free_rank, cap
    )
    free_list = jnp.full((cap,), cap, iota.dtype).at[fdest].set(
        iota, mode="drop"
    )
    # Arrival destinations: stable within-target rank over the SLAB
    # (the nparts-scaling rank now costs ceil(nparts/64)·cap_frontier).
    pend_slab = pending[src]
    tgt = jnp.clip(pend_slab // part_L, 0, nparts - 1)
    key = jnp.where(valid, tgt, nparts)
    rank = counting_ranks(key, nparts + 1, method=partition_method)
    overflow = jnp.any(valid & (rank >= n_free[tgt]))
    ridx = tgt * cap_per_chip + jnp.minimum(rank, cap_per_chip - 1)
    dest = jnp.where(valid, free_list[ridx], cap)
    src_clear = jnp.where(valid, src, cap)

    # Per-array frontier movement: gather the slab rows, clear the
    # vacated sources to defaults, place arrivals — 1 gather + 2
    # scatters of cap_frontier rows each, in place of the packed
    # full-capacity scatter (packing itself would copy cap rows).
    # Clear-before-place: an arrival's destination may be another
    # departure's vacated slot.
    defaults = _default_state(int(cap_frontier), state)
    lelem_rows = jnp.where(
        valid, pend_slab % part_L, jnp.zeros_like(pend_slab)
    )
    new_state = {}
    for k, v in state.items():
        rows = v[src]
        if k == "lelem":
            # Arrivals resume inside their new part's local mesh.
            rows = lelem_rows
        elif k == "pending":
            rows = jnp.where(valid, jnp.asarray(-1, rows.dtype), rows)
        new_state[k] = (
            v.at[src_clear].set(defaults[k], mode="drop")
            .at[dest].set(rows, mode="drop")
        )
    dep = jnp.bincount(
        jnp.where(valid, src // cap_per_chip, nparts), length=nparts + 1
    )[:nparts].astype(jnp.int32)
    arr = jnp.bincount(key, length=nparts + 1)[:nparts].astype(jnp.int32)
    # Overflow-safe commit, same contract as _migrate_impl: on overflow
    # the pre-migrate state survives verbatim (the phase loop exits on
    # the flag without walking, and the host recovery ladder resumes
    # from this intact snapshot).
    new_state = {
        k: jnp.where(overflow, state[k], v) for k, v in new_state.items()
    }
    return new_state, overflow, dep, arr


def _migrate_round(part_L: int, nparts: int, cap_per_chip: int,
                   cap_frontier, pmethod: str, state: dict,
                   n_pending: jnp.ndarray, collective_fn=None,
                   frontier_collective_fn=None):
    """One in-loop migration round: the frontier slab when the crossing
    front fits ``cap_frontier``, else the full-capacity
    ``_migrate_impl`` (today's semantics, bitwise — it also re-compacts
    every part, so an overflowing round doubles as a defragmenter).

    ``cap_frontier`` is static: ``None`` keeps the full-capacity path
    unconditionally (the historical default), ``0`` forces the
    fallback every round (the parity-testing hook). Returns
    ``(state, overflow, departures, arrivals, fellback)`` with zero
    counts on fallback rounds (occupancy recomputes from scratch then —
    ``_update_occupancy``).

    ``collective_fn`` (round 13, ``migrate_collective``): a
    ``distributed.make_collective_migrate`` closure replacing the
    full-capacity global scatter with the explicit
    all_gather + ppermute-ring collective — same
    ``(state) -> (state, overflow)`` contract, bitwise-equal result.
    ``frontier_collective_fn`` (round 18) completes the composition:
    a ``distributed.make_collective_frontier_migrate`` closure with
    ``_frontier_migrate_impl``'s ``(state) -> (state, overflow, dep,
    arr)`` contract, bitwise-equal, whose ppermute ring carries
    ``cap_frontier`` rows instead of full capacity; the slab-overflow
    cond below then falls back to the FULL-capacity collective, so a
    collective build never mixes collective and on-chip rounds. Both
    default ``None``, keeping the default trace byte-identical to
    pre-round-13 builds."""
    z = jnp.zeros((nparts,), jnp.int32)
    if cap_frontier is None or cap_frontier == 0:
        if collective_fn is not None:
            st, ovf = collective_fn(state)
        else:
            st, ovf = _migrate_impl(part_L, nparts, cap_per_chip, state,
                                    pmethod)
        return st, ovf, z, z, jnp.asarray(True)

    def full(st):
        if collective_fn is not None:
            st2, ovf = collective_fn(st)
        else:
            st2, ovf = _migrate_impl(part_L, nparts, cap_per_chip, st,
                                     pmethod)
        return st2, ovf, z, z

    def frontier(st):
        if frontier_collective_fn is not None:
            return frontier_collective_fn(st)
        return _frontier_migrate_impl(part_L, nparts, cap_per_chip,
                                      cap_frontier, st, pmethod)

    fellback = n_pending > cap_frontier
    st, ovf, dep, arr = lax.cond(fellback, full, frontier, state)
    return st, ovf, dep, arr, fellback


def _update_occupancy(nparts: int, cap_frontier, state: dict,
                      n_act: jnp.ndarray, dep: jnp.ndarray,
                      arr: jnp.ndarray, fellback: jnp.ndarray):
    """Next round's occupied-block counts: departure/arrival deltas on
    frontier rounds, a full recompute after a full-capacity round
    (whose re-compaction scrambles the slot layout the deltas assume
    — and whose dep/arr counts are zeros)."""
    if cap_frontier is None or cap_frontier == 0:
        return _occupancy_counts(state["done"], nparts)
    return lax.cond(
        fellback,
        lambda _: _occupancy_counts(state["done"], nparts),
        lambda _: n_act - dep + arr,
        None,
    )


def _inloop_migrate_step(part_L: int, nparts: int, cap_per_chip: int,
                         cap_frontier, pmethod: str, state: dict,
                         n_act: jnp.ndarray, n_pending: jnp.ndarray,
                         collective_fn=None, frontier_collective_fn=None):
    """Migration + occupancy bookkeeping for one phase-loop round —
    the composition the fused phase program inlines; the profiled
    driver dispatches the same two pieces separately so each section
    can be fenced and timed."""
    st, ovf, dep, arr, fellback = _migrate_round(
        part_L, nparts, cap_per_chip, cap_frontier, pmethod, state,
        n_pending, collective_fn, frontier_collective_fn,
    )
    n_act2 = _update_occupancy(nparts, cap_frontier, st, n_act, dep,
                               arr, fellback)
    return st, ovf, n_act2, fellback


OVERFLOW_MESSAGE = (
    "partitioned-mode chip capacity exceeded during particle "
    "migration; raise TallyConfig.capacity_factor"
)

LADDER_EXHAUSTED_MESSAGE = (
    "partitioned-mode chip capacity exceeded during particle migration "
    "and the recovery ladder (full-capacity retry, one host-side "
    "capacity escalation) could not place the particles; the engine is "
    "poisoned — resume from checkpoint with a larger "
    "TallyConfig.capacity_factor"
)


def _grow_state(state: dict, old_cb: int, new_cb: int, nparts: int) -> dict:
    """Re-home every slot of a ``nparts``-block state into a larger
    per-block capacity (the overflow-recovery capacity escalation):
    block d's slot r moves from ``d·old_cb + r`` to ``d·new_cb + r``;
    the new tail slots take the dead-slot defaults. Pure relabeling —
    no particle moves between blocks, so the escalated engine resumes
    the interrupted phase from bitwise-identical particle state."""
    iota = np.arange(nparts * old_cb)
    new_slot = jnp.asarray(
        (iota // old_cb) * new_cb + (iota % old_cb), jnp.int32
    )
    defaults = _default_state(nparts * new_cb, state)
    return {
        k: defaults[k].at[new_slot].set(v) for k, v in state.items()
    }


@dataclasses.dataclass
class PhaseProfile:
    """Component budget of profiled walk/migrate phases
    (``PartitionedEngine.move(..., profile=...)``).

    Sections are fenced wall seconds (utils/profiling.phase_timer):
    ``walk_s`` the per-round block walks, ``migrate_s`` the
    frontier/full migration, ``occupancy_s`` the occupied-block
    bookkeeping, ``bookkeeping_s`` host-side staging and flag fetches.
    ``frontier_sizes`` records each migration round's crossing-front
    size (``n_pending``); ``fallback_rounds`` counts rounds the slab
    overflowed into the full-capacity path (always 0 when
    ``cap_frontier`` is unset). Profiled phases pay one host sync per
    section per round — a measurement mode, not a production path; the
    fused phase program stays the throughput path.
    """

    walk_s: float = 0.0
    migrate_s: float = 0.0
    occupancy_s: float = 0.0
    bookkeeping_s: float = 0.0
    rounds: int = 0
    dispatches: int = 0
    fallback_rounds: int = 0
    cap_frontier: Optional[int] = None
    frontier_sizes: list = dataclasses.field(default_factory=list)

    @property
    def frontier_max(self) -> int:
        return max(self.frontier_sizes, default=0)

    @property
    def frontier_mean(self) -> float:
        if not self.frontier_sizes:
            return 0.0
        return float(sum(self.frontier_sizes) / len(self.frontier_sizes))

    def as_dict(self) -> dict:
        """The bench row's shape (bench.py blocked_profile): per-phase
        totals in ms plus per-round means and the frontier stats."""
        r = max(self.rounds, 1)
        return {
            "walk_ms": self.walk_s * 1e3,
            "migrate_ms": self.migrate_s * 1e3,
            "occupancy_ms": self.occupancy_s * 1e3,
            "bookkeeping_ms": self.bookkeeping_s * 1e3,
            "walk_ms_per_round": self.walk_s * 1e3 / r,
            "migrate_ms_per_round": self.migrate_s * 1e3 / r,
            "occupancy_ms_per_round": self.occupancy_s * 1e3 / r,
            "rounds": self.rounds,
            "dispatches": self.dispatches,
            "fallback_rounds": self.fallback_rounds,
            "cap_frontier": self.cap_frontier,
            "frontier_max": self.frontier_max,
            "frontier_mean": self.frontier_mean,
        }


# ---------------------------------------------------------------------------
# Sharded point location (localization without a replicated mesh)
# ---------------------------------------------------------------------------

def _locate_chunk(
    table: jnp.ndarray,  # [L,20] this chip's walk rows
    valid: jnp.ndarray,  # [L] bool, False on padding rows
    pts: jnp.ndarray,  # [C,3]
    tol: float,
) -> jnp.ndarray:
    """Local element containing each point, or -1 — the shared
    half-space matmul test (ops.geometry.locate_chunk_by_planes) over
    this chip's slice of the walk table."""
    L = table.shape[0]
    return locate_chunk_by_planes(
        table[:, WALK_TABLE_NORMALS].reshape(L * 4, 3),
        table[:, WALK_TABLE_OFFSETS],
        valid,
        pts,
        tol,
    )


def _locate_chunk_hi(
    table_hi: jnp.ndarray,  # [L*4,5] refinement-tier (plane, adj) rows
    valid: jnp.ndarray,
    pts: jnp.ndarray,
    tol: float,
) -> jnp.ndarray:
    """Two-tier variant of ``_locate_chunk``: point location reads the
    FULL-PRECISION refinement tier (bf16 planes would misplace points
    near faces), whose per-face row layout is exactly what the
    half-space test wants."""
    L = table_hi.shape[0] // 4
    return locate_chunk_by_planes(
        table_hi[:, 0:3], table_hi[:, 3].reshape(L, 4), valid, pts, tol,
    )


# ---------------------------------------------------------------------------
# Round-driving engine
# ---------------------------------------------------------------------------

class PartitionedEngine:
    """Owns the partitioned particle state and drives walk/migrate rounds.

    Slots: ``cap = ndev * cap_per_chip`` particle slots; chip d owns
    slots [d*cap_per_chip, (d+1)*cap_per_chip). A particle's slot moves
    between chips only via ``migrate``; ``pid`` tracks its external
    (caller-visible) index.
    """

    def __init__(
        self,
        mesh: TetMesh,
        device_mesh: Mesh,
        num_particles: int,
        *,
        capacity_factor: float = 1.5,
        tol: float,
        max_iters: int,
        max_rounds: int = 64,
        check_found_all: bool = True,
        part: Optional[MeshPartition] = None,
        shared_jit_cache: Optional[dict] = None,
        cond_every: int = COND_EVERY_DEFAULT,
        min_window: int = _MIN_WINDOW,
        vmem_walk_max_elems: Optional[int] = None,
        block_kernel: str = "vmem",
        partition_method: str = "rank",
        table_dtype: str = "float32",
        cap_frontier: Optional[int] = None,
        scoring=None,
        migrate_collective: bool = False,
        placement: str = "linear",
        placement_hosts=None,
    ):
        """``part`` reuses a prebuilt partition (chunked engines over
        the same mesh share one); ``shared_jit_cache`` shares the
        compiled locate/phase programs between engines with identical
        partition/tolerance/round parameters — without it every chunk
        engine would recompile the phase while_loop.

        ``vmem_walk_max_elems`` (TallyConfig.walk_vmem_max_elems): use
        the VMEM one-hot MXU local walk (ops/vmem_walk.py) when the
        per-chip element count fits the bound. A chip whose partition
        EXCEEDS the bound is SUB-SPLIT instead: the mesh is partitioned
        into ``ndev * blocks_per_chip`` blocks (``blocks_per_chip``
        derived so each block fits), each chip owns a contiguous run of
        blocks, and migration routes at BLOCK granularity — cross-block
        moves inside one chip pause and re-bucket exactly like
        cross-chip moves, minus the collectives. Only partitions
        needing the int adjacency sidecar keep the gather walk
        silently.

        ``cap_frontier`` (TallyConfig.cap_frontier): per-round
        migration frontier slab — in-loop migration rounds move only
        the pending rows through a static slab of this many slots
        (stayer-fixed placement, ``_frontier_migrate_impl``); a round
        whose crossing front exceeds the slab falls back to the
        full-capacity ``_migrate_impl`` bitwise. ``None`` (default)
        keeps the full-capacity migrate every round (historical
        behavior, bitwise-stable); ``0`` forces the fallback every
        round (testing hook). Localization and revival always use the
        full migrate — their frontier IS the whole population.

        ``scoring`` (a ``scoring.ScoringSpec``, round 10): arms the
        binned scoring lanes — the engine grows an OWNED padded lane
        bank (``score_padded [nparts·L·B·S]``, sharded like
        ``flux_padded``) plus two migrating per-slot state rows
        (``sbin``/``sfac``, staged per move via ``move(sbin_n=,
        sfac_n=)``), and every tallying phase threads the bank through
        its round programs. The VMEM one-hot block kernel has no
        scoring lowering; a scoring-armed engine routes blocked walks
        through the gather kernel (same reroute as the bf16 tier) and
        never uses the vmem walk.

        ``placement``/``placement_hosts`` (round 19,
        TallyConfig.placement): ``"pod_rcb"`` builds element-block
        ownership by host-hierarchical RCB (``pod_rcb_partition``) so
        the migration ring crosses hosts only where the mesh geometry
        does. ``placement_hosts`` gives per-HOST chip counts in mesh
        device order (virtual multi-host layouts on one process);
        ``None`` derives them from the mesh's process boundaries
        (``distributed.derive_host_counts``). ``"linear"`` (default)
        keeps the flat RCB byte-identically. A prebuilt ``part=``
        carries its own placement (streaming threads the knob into its
        own ``build_partition`` call)."""
        self.check_found_all = check_found_all
        self.device_mesh = device_mesh
        self.axis = _axis_name(device_mesh)
        self.ndev = int(device_mesh.devices.size)
        self.n = int(num_particles)
        # The full TetMesh is consumed here once and NOT retained: after
        # build_partition every engine path (localization included)
        # touches only per-chip sharded tables.
        # Hardware ceiling, measured by the chipless AOT sweep: clamp
        # the bound — finer sub-split, same intent — instead of dying
        # in Mosaic's scoped-VMEM allocator at first compile. Callers
        # that prebuild a partition (streaming) clamp through the same
        # helper before deriving it, so part= and the bound agree.
        # The gather block kernel has no Mosaic scoped-VMEM stack, so
        # its block size is not clamped (the measured sweet spot is
        # L<=~3k, above the vmem ceiling — docs/PERF_NOTES.md round 4).
        if block_kernel not in ("vmem", "gather", "pallas"):
            raise ValueError(
                f"block_kernel must be 'vmem', 'gather' or 'pallas', "
                f"got {block_kernel!r}"
            )
        if partition_method not in PARTITION_METHODS:
            raise ValueError(
                f"partition_method must be one of {PARTITION_METHODS}, "
                f"got {partition_method!r}"
            )
        # A prebuilt partition fixes the precision tier regardless of
        # the knob (the tables ARE the tier); the vmem kernel has no
        # two-tier lowering, so bf16 reroutes blocked walks to gather.
        if part is not None:
            table_dtype = (
                "bfloat16" if part.table_hi is not None else "float32"
            )
        self.table_dtype = table_dtype
        block_kernel = resolve_block_kernel(block_kernel, table_dtype)
        if scoring is not None and block_kernel == "vmem":
            # No scoring lowering in the f32 one-hot kernel — same
            # reroute as the bf16 tier (resolve_block_kernel). The
            # two-tier pallas kernel DOES lower scoring lanes
            # (ops/pallas_walk.py), so it is not rerouted here.
            block_kernel = "gather"
        self.block_kernel = block_kernel
        self.scoring = scoring
        self.score_stride = (
            0 if scoring is None else scoring.n_bins * scoring.n_scores
        )
        self.partition_method = partition_method
        if block_kernel == "vmem":
            from pumiumtally_tpu.ops.vmem_walk import effective_vmem_bound

            vmem_walk_max_elems = effective_vmem_bound(vmem_walk_max_elems)
        elif block_kernel == "pallas":
            # The pallas kernel's resident table block is the bf16
            # select tier: clamp through the projected bf16 ceiling
            # (the streamed refinement operand rides the same scoped
            # stack — re-measured by the next chip window's AOT sweep).
            from pumiumtally_tpu.ops.vmem_walk import effective_vmem_bound

            vmem_walk_max_elems = effective_vmem_bound(
                vmem_walk_max_elems, "bfloat16"
            )
        if placement not in PLACEMENTS:
            raise ValueError(
                f"placement must be one of {PLACEMENTS}, "
                f"got {placement!r}"
            )
        self.placement = placement
        if placement_hosts is not None:
            self.host_chips = tuple(int(h) for h in placement_hosts)
            if (any(h < 1 for h in self.host_chips)
                    or sum(self.host_chips) != self.ndev):
                raise ValueError(
                    f"placement_hosts {self.host_chips} must be "
                    f"positive chip counts summing to the "
                    f"{self.ndev}-device mesh"
                )
        else:
            from pumiumtally_tpu.parallel.distributed import (
                derive_host_counts,
            )

            self.host_chips = derive_host_counts(device_mesh)
        if part is not None:
            self.part = part
            nparts = self.part.ndev  # build_partition's part count
        else:
            nparts = self.ndev * derive_blocks_per_chip(
                mesh.nelems, self.ndev,
                block_elems_bound(vmem_walk_max_elems, table_dtype),
            )
            bpc = nparts // self.ndev
            self.part = build_partition(
                mesh, nparts, table_dtype=table_dtype,
                placement=placement,
                hosts=(
                    None if placement == "linear"
                    else [h * bpc for h in self.host_chips]
                ),
            )
        if nparts % self.ndev:
            raise ValueError(
                f"partition has {nparts} parts, not a multiple of the "
                f"{self.ndev}-device mesh"
            )
        self.nparts = nparts
        self.two_tier = self.part.table_hi is not None
        self.blocks_per_chip = nparts // self.ndev
        cap_b = int(-(-self.n // nparts) * capacity_factor + 1)
        if self.blocks_per_chip > 1 and block_kernel in ("vmem", "pallas"):
            # The blocked vmem kernel tiles each block's slot group:
            # round the per-block capacity up to whole tiles. The
            # gather block kernel only needs cap divisible by blocks
            # (guaranteed by cap = blocks*cap_b) — tile-rounding it
            # would inflate every block's lock-step walk with dead
            # slots.
            from pumiumtally_tpu.ops.vmem_walk import W_TILE_DEFAULT

            cap_b = -(-cap_b // W_TILE_DEFAULT) * W_TILE_DEFAULT
        self.cap_per_block = cap_b
        self.cap_per_chip = self.blocks_per_chip * cap_b
        self.cap = nparts * cap_b
        # Clamp to capacity: a slab of cap rows IS the full-capacity
        # frontier migrate (the parity-testing arm) — anything larger
        # only wastes memory.
        self.cap_frontier = (
            None if cap_frontier is None
            else max(0, min(int(cap_frontier), self.cap))
        )
        # Round 13 + 18: lower in-loop migration to explicit named
        # collectives (all_gather + ppermute ring inside a shard_map
        # over the engine mesh) instead of the GSPMD-partitioned global
        # scatter — bitwise-equal by construction (unique stable
        # destination ranks), built once here so every phase-family
        # program shares one closure. Round 18 adds the frontier form
        # (the ring at cap_frontier rows), so the two migrate
        # optimizations compose; a truthy cap_frontier arms it, and
        # cap_frontier=0 (the forced-fallback hook) dispatches every
        # round to the full-capacity collective exactly like the
        # on-chip path falls back to _migrate_impl.
        self.migrate_collective = bool(migrate_collective)
        self._build_collective_fns()
        self.tol = tol
        self.max_iters = max_iters
        self.max_rounds = max_rounds
        # Overflow-recovery ladder state (round 9): recovery is
        # always-armed — it only ever engages where the engine
        # previously raised over a half-migrated round. ``poisoned``
        # latches when the ladder exhausts; every facade call then
        # refuses with a clear resume-from-checkpoint error instead of
        # computing garbage. The callbacks let a facade report
        # recoveries to its sentinel runner and trigger a resilience
        # safety save before the poisoned raise.
        self.capacity_factor = float(capacity_factor)
        self.poisoned = False
        self.overflow_recoveries = 0
        self.capacity_escalations = 0
        self.on_overflow_recovered = None  # callable(escalated: bool)
        self.on_poisoned = None  # callable() — safety-save hook
        self._last_phase_tally = False  # defer-mode recovery context
        self._last_defer_flags = None  # (ovf_phase_a, ovf_phase_b) lazy
        self.cond_every = int(cond_every)
        self.min_window = int(min_window)
        self.use_vmem_walk = (
            block_kernel == "vmem"  # bf16/scoring never resolve to vmem
            and vmem_walk_max_elems is not None
            and self.part.L <= int(vmem_walk_max_elems)
            and self.part.adj_int is None
            and not self.two_tier
            and scoring is None
        )
        # The two-tier one-kernel walk (ops/pallas_walk.py): always-on
        # once selected — blocks=1 runs the whole chip partition as one
        # resident block, blocks>1 streams the sub-split block tables
        # through the grid pipeline (no L-ceiling gate; the bound above
        # only SIZES the blocks). Adjacency rides the refinement tier,
        # so the int sidecar has nothing to feed the kernel.
        if block_kernel == "pallas" and self.part.adj_int is not None:
            raise ValueError(
                "block_kernel='pallas' needs row-resident adjacency "
                "(the refinement tier's adj lane), but this partition "
                "carries the int-adjacency sidecar — rebuild without "
                "force_split_adj or use walk_kernel='gather'"
            )
        self.use_pallas_walk = block_kernel == "pallas"
        if self.blocks_per_chip > 1 and not (
            self.use_vmem_walk or self.use_pallas_walk
        ) and block_kernel != "gather":
            raise ValueError(
                "sub-split partitions (blocks_per_chip > 1) with "
                "block_kernel='vmem' need the VMEM walk, but this "
                "configuration cannot use it (walk_vmem_max_elems "
                "unset/exceeded, or the mesh needs the int-adjacency "
                "sidecar). Set a satisfiable walk_vmem_max_elems, use "
                "walk_block_kernel='gather', or pass a partition with "
                "one part per device"
            )
        dtype = mesh.coords.dtype
        self.flux_padded = jnp.zeros((self.nparts * self.part.L,), dtype)
        # Owned scoring lane bank, padded-glid layout like flux_padded:
        # rows [g·B·S, (g+1)·B·S) hold padded element g's lanes.
        self.score_padded = (
            None if scoring is None else jnp.zeros(
                (self.nparts * self.part.L * self.score_stride,), dtype
            )
        )
        # Initial layout: particle pid occupies slot pid (chips get
        # contiguous pid blocks); lelem/pending meaningless until the
        # first localization.
        pid = np.full(self.cap, -1, np.int32)
        pid[: self.n] = np.arange(self.n, dtype=np.int32)
        alive = pid >= 0
        cache = shared_jit_cache if shared_jit_cache is not None else {}
        self._jit_cache = cache
        self._n_lost_dev = None
        self._n_lost_cache = 0
        self._last_rounds_dev = None
        self._last_rounds_cache = 0
        self._last_disp_dev = None
        self._last_disp_cache = 0
        self._last_frontier_max_dev = None
        self._last_frontier_max_cache = 0
        self._last_frontier_sum_dev = None
        self._last_frontier_sum_cache = 0
        self._last_fallback_dev = None
        self._last_fallback_cache = 0
        self._valid = self.part.orig_of_glid >= 0  # [ndev*L] bool
        self.state = {
            "x": jnp.zeros((self.cap, 3), dtype),
            "lelem": jnp.zeros((self.cap,), jnp.int32),
            "pending": jnp.full((self.cap,), -1, jnp.int32),
            "pid": jnp.asarray(pid),
            "alive": jnp.asarray(alive),
            "done": jnp.asarray(~alive),
            "exited": jnp.zeros((self.cap,), bool),
            # Localization failures (source point in no element): such
            # particles are excluded from every walk (fly forced 0) so
            # they can never tally phantom track length from an
            # undefined element.
            "lost": jnp.zeros((self.cap,), bool),
            "dest": jnp.zeros((self.cap, 3), dtype),
            "fly": jnp.zeros((self.cap,), jnp.int8),
            "w": jnp.zeros((self.cap,), dtype),
        }
        if scoring is not None:
            # Per-slot scoring rows MIGRATE with the particle (the
            # generic state-dict pack/scatter machinery handles them):
            # the bin-lane offset staged each move and the [S] factor
            # row. Scoring-off engines never carry these keys — the
            # bitwise/allocation-free off contract.
            self.state["sbin"] = jnp.zeros((self.cap,), jnp.int32)
            self.state["sfac"] = jnp.zeros(
                (self.cap, scoring.n_scores), dtype
            )

    # -- collective migrate closures ------------------------------------
    def _build_collective_fns(self) -> None:
        """(Re)build the collective migrate closures from the CURRENT
        capacity geometry. Called at construction and again by
        ``_escalate_capacity``: the closures bake ``cap_per_block`` and
        ``cap_frontier``, so an escalated engine reusing the old ones
        would ring-scatter against stale slot ranges."""
        if not self.migrate_collective:
            self._collective_migrate = None
            self._collective_frontier = None
            return
        from pumiumtally_tpu.parallel.distributed import (
            make_collective_frontier_migrate,
            make_collective_migrate,
        )

        self._collective_migrate = make_collective_migrate(
            self.device_mesh,
            part_L=self.part.L,
            nparts=self.nparts,
            cap_per_block=self.cap_per_block,
            partition_method=self.partition_method,
        )
        # cap_frontier=0 (forced fallback) and None both migrate at
        # full capacity every round — no slab closure to build.
        self._collective_frontier = (
            None if not self.cap_frontier
            else make_collective_frontier_migrate(
                self.device_mesh,
                part_L=self.part.L,
                nparts=self.nparts,
                cap_per_block=self.cap_per_block,
                cap_frontier=self.cap_frontier,
                partition_method=self.partition_method,
            )
        )

    def modeled_cross_host_bytes(self) -> int:
        """Modeled per-migration-round CROSS-HOST bytes of this
        engine's placement under its host layout (round 19's placement
        diagnostic — deterministic, nothing runs). See
        ``distributed.modeled_cross_host_migration_bytes`` for the
        host-ring model; 0 on single-host layouts and on prebuilt
        partitions without a face census."""
        if self.part.remote_faces is None or len(self.host_chips) < 2:
            return 0
        from pumiumtally_tpu.parallel.distributed import (
            modeled_cross_host_migration_bytes,
            state_pack_columns,
        )

        fcols, icols = state_pack_columns(self.state)
        return modeled_cross_host_migration_bytes(
            self.part.remote_faces,
            self.blocks_per_chip,
            self.host_chips,
            fcols,
            icols,
        )

    # -- staged input routing -------------------------------------------
    def _by_pid(self, arr_n: jnp.ndarray, fill) -> jnp.ndarray:
        """Route a caller-order [n,...] array to current slots via pid."""
        pid = self.state["pid"]
        safe = jnp.clip(pid, 0, self.n - 1)
        v = arr_n[safe]
        mask = (pid >= 0)
        if v.ndim == 2:
            return jnp.where(mask[:, None], v, fill)
        return jnp.where(mask, v, fill)

    # -- phases ----------------------------------------------------------
    def _locate_program(self):
        """Cached jitted sharded point-location: [M,3] replicated points
        → [M] padded global element id (``ndev*L`` = not found)."""
        key = ("locate", self._locate_chunk_size, self.tol, id(self.part))
        if key in self._jit_cache:
            return self._jit_cache[key]
        pp = P(self.axis)
        ax = self.axis
        # A chip's table slice holds blocks_per_chip stacked blocks, so
        # its local row index spans k*L rows and glids are offset by
        # the chip's first block.
        rows_per_chip = self.blocks_per_chip * self.part.L
        sentinel = jnp.asarray(self.nparts * self.part.L, jnp.int32)
        tol = self.tol
        C = self._locate_chunk_size
        # Two-tier partitions locate against the full-precision
        # refinement tier (the operand _locate_points passes).
        chunk_fn = _locate_chunk_hi if self.two_tier else _locate_chunk

        @jax.jit
        @partial(
            shard_map,
            mesh=self.device_mesh,
            in_specs=(pp, pp, P()),
            out_specs=P(),
            **shard_map_check_kwargs(),
        )
        def locate(table, valid, pts):
            le = lax.map(
                lambda p: chunk_fn(table, valid, p, tol),
                pts.reshape(-1, C, 3),
            ).reshape(-1)
            d = lax.axis_index(ax).astype(jnp.int32)
            glid = jnp.where(le >= 0, d * rows_per_chip + le, sentinel)
            # Lowest claiming glid wins (deterministic tie-break on
            # shared partition faces).
            return lax.pmin(glid, ax)

        # Cache the counting wrapper, not the bare jit: compiles are
        # counted per call (retrace tripwire, docs/STATIC_ANALYSIS.md).
        locate = register_entry_point("partition_locate", locate)
        self._jit_cache[key] = locate
        return locate

    @property
    def _locate_chunk_size(self) -> int:
        # Bound the [C, 4·rows] matmul intermediate to ~32M floats per
        # chip (128 MB f32) so point location cannot OOM on meshes
        # whose per-chip row count reaches hundreds of thousands.
        rows = self.blocks_per_chip * self.part.L
        cap = max(8, (1 << 23) // max(rows, 1))
        return min(2048, cap, self.n)

    def _locate_points(self, pts_n: jnp.ndarray) -> jnp.ndarray:
        """[n] padded global element id per point (``ndev*L`` = in no
        element), via the cached sharded point-location program."""
        locate = self._locate_program()
        C = self._locate_chunk_size
        m = -(-self.n // C) * C
        pts = pts_n
        if m > self.n:
            # Far-away pad points: outside every tet, claimed by no one.
            pts = jnp.concatenate(
                [pts, jnp.full((m - self.n, 3), 2e30, pts_n.dtype)]
            )
        tbl = self.part.table_hi if self.two_tier else self.part.table
        return locate(tbl, self._valid, pts)[: self.n]

    def localize(
        self, dest_n: jnp.ndarray, defer_sync: bool = False
    ) -> Tuple[Any, Any]:
        """CopyInitialPosition: sharded point location (module docstring)
        instead of the reference's walk-from-element-0 — same observable
        contract (particle lands in the element containing its source
        point, zero flux). Returns (found_all, n_exited=0); with
        ``defer_sync=True`` (streaming chunk pipelines) the second
        element is instead the LAZY overflow flag and no host sync
        happens here — the caller checks overflow for a whole batch of
        chunks at once.

        Divergence from the single-chip engine, by design: a source
        point inside NO element (out-of-hull, or a non-convex gap) makes
        its particle ``lost`` — excluded from transport, elem id −1 —
        where the single-chip walk clamps it to the hull boundary and
        keeps transporting it. A later two-phase move with a valid
        resampled origin revives the particle (see ``move``); the
        reference requires convex geometry with interior sources
        (reference README.md:112-113), so located particles never hit
        this path.
        """
        glid = self._locate_points(dest_n)
        sentinel = self.nparts * self.part.L
        found = glid < sentinel
        st = dict(self.state)
        st["x"] = self._by_pid(dest_n, jnp.zeros((), dest_n.dtype))
        pend = self._by_pid(jnp.where(found, glid, -1), -1)
        st["pending"] = jnp.where(st["alive"], pend, st["pending"]).astype(
            jnp.int32
        )
        st["lost"] = st["alive"] & (st["pending"] < 0)
        st["done"] = ~st["alive"]
        st["exited"] = jnp.zeros((self.cap,), bool)
        self.state, overflow = migrate(
            part_L=self.part.L, ndev=self.nparts,
            cap_per_chip=self.cap_per_block, state=st,
            partition_method=self.partition_method,
        )
        # Lazy lost count: fetched only when the warning needs it or
        # when a two-phase move engages the revival path.
        self._n_lost_dev = jnp.sum(~found)
        self._n_lost_cache = None
        if defer_sync:
            # Finalize (phase done for everyone) only when the
            # placement actually happened: an overflowing migrate kept
            # the pre-migrate snapshot, whose pending rows the deferred
            # recovery (_recover_localize_overflow, at the caller's
            # batch sync point) still needs. One device select per
            # lane — no host sync here.
            self._finalize_localize(overflow)
            return jnp.all(found), overflow
        if bool(overflow):
            self._recover_localize_overflow()
        else:
            self._finalize_localize()
        if self.check_found_all and self._n_lost:
            print(
                f"[WARNING] {self._n_lost} source points lie in no mesh "
                "element; their particles are excluded from transport"
            )
        return jnp.all(found), 0

    def _finalize_localize(self, overflow=None) -> None:
        """Mark the localization phase finished for all particles —
        conditionally (device select, no sync) when a lazy overflow
        flag is in play."""
        done = jnp.ones((self.cap,), bool)
        pend = jnp.full((self.cap,), -1, jnp.int32)
        if overflow is None:
            self.state["done"] = done
            self.state["pending"] = pend
        else:
            self.state["done"] = jnp.where(
                overflow, self.state["done"], done
            )
            self.state["pending"] = jnp.where(
                overflow, self.state["pending"], pend
            )

    def _recover_localize_overflow(self) -> None:
        """Localization/revival placement overflowed: those paths
        already use the full-capacity migrate (their frontier IS the
        whole population), so the ladder goes straight to the capacity
        escalation, retries the placement over the intact pending
        snapshot, and poisons on a second failure."""
        self._escalate_capacity(self._needed_capacity_growth())
        self.state, ovf = migrate(
            part_L=self.part.L, ndev=self.nparts,
            cap_per_chip=self.cap_per_block, state=self.state,
            partition_method=self.partition_method,
        )
        if bool(ovf):
            self._poison()  # raises
        self._finalize_localize()
        self._note_recovery(escalated=True)

    @property
    def last_walk_rounds(self) -> int:
        """Walk rounds of the most recent phase (== migrations + 1).

        Diagnostic for tuning ``capacity_factor`` and judging partition
        quality: a phase whose rounds approach
        ``TallyConfig.max_migration_rounds`` is migrating too much
        (elongated partitions or long steps). Reading it fetches one
        device scalar (a sync) — do not read it inside a pipelined
        inner loop."""
        if self._last_rounds_cache is None:
            self._last_rounds_cache = (
                0 if self._last_rounds_dev is None
                else int(self._last_rounds_dev)
            )
        return self._last_rounds_cache

    @property
    def last_block_dispatches(self) -> int:
        """Per-block walk dispatches of the most recent phase, summed
        over its rounds and all chips.

        For the gather sub-split this counts OCCUPIED blocks only —
        the empty-block-skip diagnostic: compare against
        ``last_walk_rounds * nparts``, the work a full per-round sweep
        would dispatch (at 45 migration rounds on the lattice smoke
        run, most blocks are empty most rounds). The vmem kernel
        reports rounds × nparts (it sweeps every block); unblocked
        engines report rounds × ndev. Reading it fetches one device
        scalar (a sync), cached after the first read."""
        if self._last_disp_cache is None:
            self._last_disp_cache = (
                0 if self._last_disp_dev is None
                else int(self._last_disp_dev)
            )
        return self._last_disp_cache

    @property
    def last_frontier_max(self) -> int:
        """Largest per-round crossing front (pending particles at a
        migration round) of the most recent phase; 0 for a phase with
        no migrations. Sizes ``TallyConfig.cap_frontier``: a slab at or
        above this value never falls back. Reading fetches one device
        scalar (a sync), cached after the first read."""
        if self._last_frontier_max_cache is None:
            self._last_frontier_max_cache = (
                0 if self._last_frontier_max_dev is None
                else int(self._last_frontier_max_dev)
            )
        return self._last_frontier_max_cache

    @property
    def last_frontier_mean(self) -> float:
        """Mean crossing front over the most recent phase's migration
        rounds (0.0 with no migrations) — with ``last_frontier_max``,
        the frontier-vs-capacity evidence the blocked_profile bench row
        records. Reading fetches device scalars (a sync), cached."""
        if self._last_frontier_sum_cache is None:
            self._last_frontier_sum_cache = (
                0 if self._last_frontier_sum_dev is None
                else int(self._last_frontier_sum_dev)
            )
        migrations = self.last_walk_rounds - 1
        if migrations <= 0:
            return 0.0
        return self._last_frontier_sum_cache / migrations

    @property
    def last_fallback_rounds(self) -> int:
        """Migration rounds of the most recent phase whose crossing
        front overflowed ``cap_frontier`` into the full-capacity
        migrate (always 0 when the slab is unset; == every migration
        round when cap_frontier=0, the forced-fallback testing hook).
        Reading fetches one device scalar (a sync), cached."""
        if self._last_fallback_cache is None:
            self._last_fallback_cache = (
                0 if self._last_fallback_dev is None
                else int(self._last_fallback_dev)
            )
        return self._last_fallback_cache

    @property
    def _n_lost(self) -> int:
        if self._n_lost_cache is None:
            self._n_lost_cache = (
                0 if self._n_lost_dev is None else int(self._n_lost_dev)
            )
        return self._n_lost_cache

    def _fx_in(self, tally: bool):
        """The phase programs' ``fx`` operand: the owned flux, bundled
        with the scoring lane bank as one pytree on scoring-armed
        TALLY phases (non-tally phases never score, like the flux
        lane)."""
        if tally and self.scoring is not None:
            return (self.flux_padded, self.score_padded)
        return self.flux_padded

    def _fx_commit(self, tally: bool, fx) -> None:
        """Commit a phase's ``fx`` result (see ``_fx_in``)."""
        if tally and self.scoring is not None:
            self.flux_padded, self.score_padded = fx
        else:
            self.flux_padded = fx

    def _make_round_sm(self, tally: bool, max_iters: Optional[int] = None):
        """The shard_mapped one-walk-round kernel, shared by the fused
        phase program (``_phase_program``) and the profiled per-round
        driver (``_round_program``) so the two can never drift.
        ``max_iters`` overrides the engine budget (the straggler-retry
        resume phases walk with a multiplied iteration budget)."""
        pp = P(self.axis)
        ax = self.axis
        part_L = self.part.L
        blocks = self.blocks_per_chip
        tol = self.tol
        max_iters = (
            self.max_iters if max_iters is None else int(max_iters)
        )
        cond_every = self.cond_every
        min_window = self.min_window
        has_adj = self.part.adj_int is not None
        pmethod = self.partition_method
        two_tier = self.two_tier
        # Scoring rides TALLYING rounds only (phase A / localization
        # walks never score — exactly like the flux lane).
        score_on = tally and self.scoring is not None
        s_kinds = self.scoring.kinds if score_on else None
        stride = self.score_stride

        use_vmem = self.use_vmem_walk
        use_pallas = self.use_pallas_walk

        def round_kernel(table, *rest):
            rest = list(rest)
            adj = rest.pop(0) if has_adj else None
            hi = rest.pop(0) if two_tier else None
            if score_on:
                (x, lelem, dest, fly, w, done, exited, sbin, sfac, flux,
                 bank, n_act) = rest
            else:
                x, lelem, dest, fly, w, done, exited, flux, n_act = rest
                sbin = sfac = bank = None
            if use_pallas:
                # One-kernel two-tier walk: select/refine/scatter fused
                # per particle tile, block tables streamed by the grid
                # pipeline (ops/pallas_walk.py). Same layout contract
                # as the vmem sub-split; scoring lanes lower in-kernel.
                from pumiumtally_tpu.ops.pallas_walk import (
                    pallas_walk_local,
                )

                sc = (
                    ScoreOps(s_kinds, bank, sbin, sfac) if score_on
                    else None
                )
                res = pallas_walk_local(
                    table, hi, x, lelem, dest, fly, w, done, exited,
                    flux, tally=tally, tol=tol, max_iters=max_iters,
                    blocks=blocks, scoring=sc,
                )
                x, lelem, done, exited, pending, flux = res[:6]
                if score_on:
                    bank = res[7]
                # The Pallas kernel sweeps every block unconditionally.
                n_disp = jnp.sum(jnp.zeros_like(lelem)) + blocks
                n_act = jnp.sum(
                    (~done).reshape(blocks, -1), axis=1, dtype=jnp.int32
                )
            elif use_vmem:
                from pumiumtally_tpu.ops.vmem_walk import vmem_walk_local

                x, lelem, done, exited, pending, flux, _ = vmem_walk_local(
                    table, x, lelem, dest, fly, w, done, exited, flux,
                    tally=tally, tol=tol, max_iters=max_iters,
                    blocks=blocks,
                )
                # The Pallas kernel sweeps every block unconditionally.
                n_disp = jnp.sum(jnp.zeros_like(lelem)) + blocks
                # Occupancy is unused by the sweep, but the carried
                # counts must stay truthful for the migrate step's
                # incremental bookkeeping.
                n_act = jnp.sum(
                    (~done).reshape(blocks, -1), axis=1, dtype=jnp.int32
                )
            elif blocks > 1:
                # Gather sub-split: run walk_local block-by-block,
                # sequentially (NOT vmap — a batched gather over the
                # stacked table would be the monolithic gather again).
                # Each step's [L,20] block table is a loop-invariant
                # few hundred KB, so it stays resident on-chip for
                # that block's whole while_loop — the measured
                # small-table regime (2.2-2.4M moves/s at L<=3k,
                # docs/PERF_NOTES.md round 4). Layout contract
                # identical to the vmem sub-split: slots grouped by
                # block, lelem block-local, flux [blocks*L].
                #
                # The sequential loop visits OCCUPIED blocks only: a
                # lax.while_loop over the compacted list of block ids
                # holding any not-done slot (stable counting-rank
                # partition of the occupancy flags, ops/bucketize.py).
                # Migration rounds beyond the first touch only the
                # frontier blocks — at 45 rounds on the 1M-tet lattice
                # smoke run most blocks are empty most rounds — and an
                # empty block now dispatches NOTHING, not even a
                # skipped lax.map step. A skipped block's state is
                # exactly walk_local on an all-done batch: unchanged
                # carries, fresh all- -1 pending, flux untouched.
                #
                # The occupied list comes from the CARRIED per-block
                # not-done counts (incremental: walked blocks re-count
                # themselves below, migration applies departure/arrival
                # deltas — _update_occupancy), not a per-round
                # full-capacity done scan.
                ncap = x.shape[0]
                cb = ncap // blocks
                twidth = table.shape[-1]
                occ = n_act > 0
                n_occ = jnp.sum(occ.astype(jnp.int32))
                order, _, _ = partition_perm(
                    (~occ).astype(jnp.int32), 2, method=pmethod
                )
                pending = jnp.full_like(lelem, -1)

                def blk_cond(c):
                    return c[0] < n_occ

                def blk_body(c):
                    if score_on:
                        (t, x, lelem, done, exited, pending, flux, bank,
                         n_act) = c
                    else:
                        t, x, lelem, done, exited, pending, flux, n_act = c
                        bank = None
                    b = order[t]
                    po = b * cb  # first particle slot of block b
                    eo = b * part_L  # first element row of block b
                    z = jnp.zeros((), b.dtype)  # col index, same dtype
                    a_b = (
                        lax.dynamic_slice(adj, (eo, z), (part_L, 4))
                        if has_adj else None
                    )
                    hi_b = (
                        lax.dynamic_slice(
                            hi, (eo * 4, z), (part_L * 4, WALK_PLANE_WIDTH)
                        )
                        if two_tier else None
                    )
                    sc_b = None
                    if score_on:
                        # Block b's lane rows sit at [eo·stride,
                        # (eo+part_L)·stride) — the same contiguous-
                        # per-element layout as the flux slice.
                        sc_b = ScoreOps(
                            s_kinds,
                            lax.dynamic_slice(
                                bank, (eo * stride,), (part_L * stride,)
                            ),
                            lax.dynamic_slice(sbin, (po,), (cb,)),
                            lax.dynamic_slice(
                                sfac, (po, z), (cb, len(s_kinds))
                            ),
                        )
                    res = walk_local(
                        lax.dynamic_slice(
                            table, (eo, z), (part_L, twidth)
                        ),
                        lax.dynamic_slice(x, (po, z), (cb, 3)),
                        lax.dynamic_slice(lelem, (po,), (cb,)),
                        lax.dynamic_slice(dest, (po, z), (cb, 3)),
                        lax.dynamic_slice(fly, (po,), (cb,)),
                        lax.dynamic_slice(w, (po,), (cb,)),
                        lax.dynamic_slice(done, (po,), (cb,)),
                        lax.dynamic_slice(exited, (po,), (cb,)),
                        lax.dynamic_slice(flux, (eo,), (part_L,)),
                        tally=tally, tol=tol, max_iters=max_iters,
                        adj_int=a_b, cond_every=cond_every,
                        min_window=min_window, partition_method=pmethod,
                        table_hi=hi_b, scoring=sc_b,
                    )
                    xb, leb, dnb, exb, pb, fxb = res[:6]
                    if score_on:
                        bank = lax.dynamic_update_slice(
                            bank, res[7], (eo * stride,)
                        )
                    n_act = n_act.at[b].set(
                        jnp.sum(~dnb, dtype=jnp.int32)
                    )
                    out = (
                        t + 1,
                        lax.dynamic_update_slice(x, xb, (po, z)),
                        lax.dynamic_update_slice(lelem, leb, (po,)),
                        lax.dynamic_update_slice(done, dnb, (po,)),
                        lax.dynamic_update_slice(exited, exb, (po,)),
                        lax.dynamic_update_slice(pending, pb, (po,)),
                        lax.dynamic_update_slice(flux, fxb, (eo,)),
                    )
                    if score_on:
                        return out + (bank, n_act)
                    return out + (n_act,)

                carry0 = (jnp.sum(jnp.zeros_like(lelem)), x, lelem, done,
                          exited, pending, flux)
                if score_on:
                    (_, x, lelem, done, exited, pending, flux, bank,
                     n_act) = lax.while_loop(
                        blk_cond, blk_body, carry0 + (bank, n_act)
                    )
                else:
                    (_, x, lelem, done, exited, pending, flux,
                     n_act) = lax.while_loop(
                        blk_cond, blk_body, carry0 + (n_act,)
                    )
                n_disp = n_occ
            else:
                sc = (
                    ScoreOps(s_kinds, bank, sbin, sfac) if score_on
                    else None
                )
                res = walk_local(
                    table, x, lelem, dest, fly, w, done, exited, flux,
                    tally=tally, tol=tol, max_iters=max_iters, adj_int=adj,
                    cond_every=cond_every, min_window=min_window,
                    partition_method=pmethod, table_hi=hi, scoring=sc,
                )
                x, lelem, done, exited, pending, flux = res[:6]
                if score_on:
                    bank = res[7]
                # One whole-partition walk per chip per round.
                n_disp = jnp.sum(jnp.zeros_like(lelem)) + 1
                n_act = jnp.sum(~done, dtype=jnp.int32).reshape(1)
            # Global round status computed in-program (one psum each) so
            # the while_loop can branch on them without leaving the
            # device. n_disp: per-block walk dispatches this round, all
            # chips — the empty-block-skip diagnostic for the gather
            # sub-split (occupied blocks only).
            n_pending = lax.psum(jnp.sum(pending >= 0), ax)
            n_not_done = lax.psum(jnp.sum(~done), ax)
            n_disp = lax.psum(n_disp, ax)
            if score_on:
                return (x, lelem, done, exited, pending, flux, bank,
                        n_act, n_pending, n_not_done, n_disp)
            return (x, lelem, done, exited, pending, flux, n_act,
                    n_pending, n_not_done, n_disp)

        n_in = 10 + int(has_adj) + int(two_tier) + 3 * int(score_on)
        n_out_pp = 8 if score_on else 7
        # Output-type checking (check_vma on current jax, check_rep on
        # jax 0.4.x — shard_map_check_kwargs resolves the spelling) is
        # disabled ONLY for the vmem-kernel variant: the pallas
        # interpret path re-traces the kernel with physical types that
        # drop the varying-axis tags, so the vma checker rejects any
        # pallas_call under shard_map (its own error message recommends
        # exactly this workaround). The gather variant keeps full
        # checking; result parity between the two engines is pinned by
        # tests/test_vmem_walk.py.
        return shard_map(
            round_kernel,
            mesh=self.device_mesh,
            in_specs=(pp,) * n_in,
            out_specs=(pp,) * n_out_pp + (P(), P(), P()),
            **shard_map_check_kwargs(not (use_vmem or use_pallas)),
        )

    def _phase_key(self, kind: str, tally: bool, variant: tuple = ()
                   ) -> tuple:
        """Shared cache-key components of the phase-family programs.
        The closures bake in EVERY per-engine parameter they capture —
        capacity, round/iteration budgets, tolerance, the frontier
        slab, and the partition itself — so the key must carry all of
        them: engines sharing a cache reuse a compiled program only
        for a fully identical configuration (chunked engines differ in
        the last, smaller chunk's capacity). ``variant`` carries the
        recovery-family extras (resume flag, budget multipliers,
        forced-full-migrate)."""
        return (kind, tally, self.cap_per_chip, self.max_rounds,
                self.max_iters, self.tol, self.cond_every,
                self.min_window, self.use_vmem_walk, self.use_pallas_walk,
                self.blocks_per_chip,
                self.partition_method, self.cap_frontier,
                self.migrate_collective, self.placement, id(self.part),
                None if self.scoring is None else self.scoring.static_key(),
                variant)

    def _phase_program(self, tally: bool, resume: bool = False,
                       iters_mult: int = 1, rounds_mult: int = 1,
                       force_full_migrate: bool = False):
        """Cached jitted FULL phase: initial walk round plus as many
        migrate→walk rounds as needed, all inside one ``lax.while_loop``
        — zero per-round host syncs (the reference's search loop pays an
        MPI rendezvous per migration instead).

        The recovery family (round 9): ``resume=True`` skips the
        done/exited/dest re-derivation at phase entry and continues
        EXACTLY the committed mid-phase state — finished particles stay
        done (their committed positions are never re-derived, which
        would not be bitwise-stable), stragglers walk on from their
        tallied partial positions, and stale paused rows re-derive
        their partition crossing geometrically. ``iters_mult``/
        ``rounds_mult`` multiply the walk/round budgets (the straggler
        retry rung); ``force_full_migrate`` disables the frontier slab
        for this program (the overflow ladder's defragmenting
        full-capacity retry)."""
        variant = (resume, iters_mult, rounds_mult, force_full_migrate)
        key = self._phase_key("phase", tally, variant)
        if key in self._jit_cache:
            return self._jit_cache[key]
        part_L = self.part.L
        nparts, cap_b = self.nparts, self.cap_per_block
        max_rounds = self.max_rounds * int(rounds_mult)
        has_adj = self.part.adj_int is not None
        pmethod = self.partition_method
        two_tier = self.two_tier
        # Scoring-armed TALLY phases carry ``fx`` as a (flux, bank)
        # pytree through the round loop — the loop/cond/overflow
        # machinery below is pytree-agnostic, so the scoring-off trace
        # is byte-identical to pre-scoring builds.
        score_on = tally and self.scoring is not None
        cap_frontier = (
            None if force_full_migrate else self.cap_frontier
        )
        collective_fn = self._collective_migrate
        frontier_collective_fn = (
            None if force_full_migrate else self._collective_frontier
        )
        round_sm = self._make_round_sm(
            tally, max_iters=self.max_iters * int(iters_mult)
        )

        @jax.jit
        def phase(table, adj, hi, state, flux):
            st = dict(state)
            if not resume:
                st["done"] = ~st["alive"] | (st["fly"] == 0)
                # Per-walk flag, like the single-chip engine's fresh
                # exited mask each walk() call: a particle that left
                # the domain last move but was re-flown must not carry
                # a stale True (it would dodge the
                # commit-dest-bit-exactly path).
                st["exited"] = jnp.zeros_like(st["exited"])
                # Non-flying particles hold position: dest <- x.
                st["dest"] = jnp.where(
                    (st["fly"] == 1)[:, None], st["dest"], st["x"]
                )

            def call_round(st, fx, n_act):
                if score_on:
                    flux_i, bank_i = fx
                    tail = (st["sbin"], st["sfac"], flux_i, bank_i, n_act)
                else:
                    tail = (fx, n_act)
                args = (
                    (table,)
                    + ((adj,) if has_adj else ())
                    + ((hi,) if two_tier else ())
                    + (
                        st["x"], st["lelem"], st["dest"], st["fly"],
                        st["w"], st["done"], st["exited"],
                    )
                    + tail
                )
                if score_on:
                    (x, lelem, done, exited, pending, flux_o, bank_o,
                     n_act, n_p, n_nd, n_disp) = round_sm(*args)
                    fx = (flux_o, bank_o)
                else:
                    (x, lelem, done, exited, pending, fx, n_act, n_p,
                     n_nd, n_disp) = round_sm(*args)
                return (
                    dict(st, x=x, lelem=lelem, done=done, exited=exited,
                         pending=pending),
                    fx, n_act, n_p, n_nd, n_disp,
                )

            n_act0 = _occupancy_counts(st["done"], nparts)
            st, fx, n_act, n_p, n_nd, disp = call_round(st, flux, n_act0)
            zero = jnp.zeros_like(n_p)

            def cond(c):
                it, _st, _fx, _na, n_p, _n_nd, _disp, ovf = c[:8]
                return (n_p > 0) & (it < max_rounds) & ~ovf

            def body(c):
                (it, st, fx, n_act, n_p, n_nd, disp, ovf, fmax, fsum,
                 nfb) = c
                st2, ovf2, n_act2, fellback = _inloop_migrate_step(
                    part_L, nparts, cap_b, cap_frontier, pmethod, st,
                    n_act, n_p, collective_fn, frontier_collective_fn,
                )
                # An overflowing migrate scatters colliding slots: do
                # NOT walk (and tally) from that corrupted state — the
                # loop cond exits on ovf and the host raises.
                st3, fx3, n_act3, n_p3, n_nd3, d3 = lax.cond(
                    ovf2,
                    lambda op: (op[0], op[1], op[2], n_p, n_nd,
                                jnp.zeros_like(disp)),
                    lambda op: call_round(*op),
                    (st2, fx, n_act2),
                )
                # Frontier diagnostics ride the carry: the crossing
                # front this round (n_p), its running max/sum, and the
                # slab-overflow fallback count (always 0 when the slab
                # is off — static python branch keeps the carry clean).
                nfb2 = (
                    nfb + fellback.astype(nfb.dtype)
                    if cap_frontier is not None else nfb
                )
                return (it + 1, st3, fx3, n_act3, n_p3, n_nd3,
                        disp + d3, ovf | ovf2,
                        jnp.maximum(fmax, n_p), fsum + n_p, nfb2)

            (it, st, fx, _n_act, n_p, n_nd, disp, ovf, fmax, fsum,
             nfb) = lax.while_loop(
                cond, body,
                (jnp.asarray(1, jnp.int32), st, fx, n_act, n_p, n_nd,
                 disp, jnp.asarray(False), zero, zero, zero),
            )
            found_all = (n_nd == 0) & (n_p == 0)
            # `it` counts walk rounds (== migrations + 1); `disp` the
            # per-block walk dispatches summed over rounds — cheap
            # diagnostics for capacity_factor / partition quality and
            # the gather sub-split's empty-block skip. fmax/fsum/nfb:
            # frontier-size max/sum over migrations and the number of
            # slab-overflow fallback rounds.
            return st, fx, found_all, ovf, it, disp, fmax, fsum, nfb

        # The cascade entry point: walk+migrate rounds compile as ONE
        # program per (engine, config-key) — tests sweeping several
        # engine configs accumulate under the one "cascade_phase"
        # budget in config.RETRACE_BUDGETS. Cache the counting wrapper
        # so every call is counted (retrace tripwire).
        phase = register_entry_point("cascade_phase", phase)
        self._jit_cache[key] = phase
        return phase

    # -- profiled phase programs (component-budget instrumentation) ------
    def _round_program(self, tally: bool):
        """Cached jitted SINGLE walk round — the profiled driver's walk
        section (the fused phase runs the identical round_sm inside its
        while_loop)."""
        key = self._phase_key("round", tally)
        if key in self._jit_cache:
            return self._jit_cache[key]
        has_adj = self.part.adj_int is not None
        two_tier = self.two_tier
        score_on = tally and self.scoring is not None
        round_sm = self._make_round_sm(tally)

        @jax.jit
        def round1(table, adj, hi, state, flux, n_act):
            st = dict(state)
            if score_on:
                flux_i, bank_i = flux
                tail = (st["sbin"], st["sfac"], flux_i, bank_i, n_act)
            else:
                tail = (flux, n_act)
            args = (
                (table,)
                + ((adj,) if has_adj else ())
                + ((hi,) if two_tier else ())
                + (
                    st["x"], st["lelem"], st["dest"], st["fly"],
                    st["w"], st["done"], st["exited"],
                )
                + tail
            )
            if score_on:
                (x, lelem, done, exited, pending, flux_o, bank_o, n_act,
                 n_p, n_nd, n_disp) = round_sm(*args)
                fx = (flux_o, bank_o)
            else:
                (x, lelem, done, exited, pending, fx, n_act, n_p, n_nd,
                 n_disp) = round_sm(*args)
            return (
                dict(st, x=x, lelem=lelem, done=done, exited=exited,
                     pending=pending),
                fx, n_act, n_p, n_nd, n_disp,
            )

        round1 = register_entry_point("partition_round", round1)
        self._jit_cache[key] = round1
        return round1

    def _migrate_program(self):
        """Cached jitted in-loop migration round (frontier slab or
        full-capacity fallback) — the profiled driver's migrate
        section."""
        key = self._phase_key("migrate_step", False)
        if key in self._jit_cache:
            return self._jit_cache[key]
        part_L = self.part.L
        nparts, cap_b = self.nparts, self.cap_per_block
        pmethod = self.partition_method
        cap_frontier = self.cap_frontier
        collective_fn = self._collective_migrate
        frontier_collective_fn = self._collective_frontier

        @jax.jit
        def mig(state, n_pending):
            return _migrate_round(part_L, nparts, cap_b, cap_frontier,
                                  pmethod, state, n_pending,
                                  collective_fn, frontier_collective_fn)

        mig = register_entry_point("partition_migrate", mig)
        self._jit_cache[key] = mig
        return mig

    def _occupancy_program(self):
        """Cached jitted occupied-block bookkeeping — the profiled
        driver's occupancy section (also produces the initial counts:
        pass ``fellback=True`` to force the full scan)."""
        key = self._phase_key("occupancy", False)
        if key in self._jit_cache:
            return self._jit_cache[key]
        nparts = self.nparts
        cap_frontier = self.cap_frontier

        @jax.jit
        def occ(state, n_act, dep, arr, fellback):
            return _update_occupancy(nparts, cap_frontier, state, n_act,
                                     dep, arr, fellback)

        occ = register_entry_point("partition_occupancy", occ)
        self._jit_cache[key] = occ
        return occ

    def _run_phase_profiled(self, tally: bool, prof: PhaseProfile):
        """One walk+migrate phase driven round-by-round with a fenced
        ``phase_timer`` section per component (walk / migrate /
        occupancy / bookkeeping), accumulating into ``prof``.

        Runs the SAME round/migrate/occupancy programs the fused phase
        inlines (``_make_round_sm``, ``_migrate_round``,
        ``_update_occupancy``), so physics — flux included — is
        bitwise-identical to an unprofiled phase of the same engine
        configuration; what changes is dispatch granularity: one host
        sync per section per round, which is the price of attributing
        time to components (the reason this is a measurement mode and
        the fused while_loop stays the throughput path)."""
        prof.cap_frontier = self.cap_frontier
        round1 = self._round_program(tally)
        mig = self._migrate_program()
        occp = self._occupancy_program()
        nparts = self.nparts
        with phase_timer(prof, "bookkeeping_s"):
            st = dict(self.state)
            st["done"] = ~st["alive"] | (st["fly"] == 0)
            st["exited"] = jnp.zeros_like(st["exited"])
            st["dest"] = jnp.where(
                (st["fly"] == 1)[:, None], st["dest"], st["x"]
            )
        zero_counts = jnp.zeros((nparts,), jnp.int32)
        with phase_timer(prof, "occupancy_s"):
            n_act = occp(st, zero_counts, zero_counts, zero_counts,
                         jnp.asarray(True))
            jax.block_until_ready(n_act)
        fx = self._fx_in(tally)
        tbl, adj, hi = self.part.table, self.part.adj_int, self.part.table_hi
        with phase_timer(prof, "walk_s"):
            st, fx, n_act, n_p, n_nd, disp = round1(
                tbl, adj, hi, st, fx, n_act
            )
            n_p_h = int(n_p)  # the fetch is the fence
        rounds = 1
        disp_total = int(disp)
        phase_fronts: list = []
        phase_fallbacks = 0
        prof.rounds += 1
        prof.dispatches += disp_total
        while n_p_h > 0 and rounds < self.max_rounds:
            prof.frontier_sizes.append(n_p_h)
            phase_fronts.append(n_p_h)
            with phase_timer(prof, "migrate_s"):
                st, ovf, dep, arr, fb = mig(st, n_p)
                ovf_h = bool(ovf)  # fence; also gates the next walk
            if ovf_h:
                # Overflow-safe migrate kept the pre-migrate snapshot;
                # commit it and hand the phase to the recovery ladder
                # (mirrors _run_phase's fused path).
                self.state = st
                self._fx_commit(tally, fx)
                return self._recover_overflow(tally)
            if self.cap_frontier is not None and bool(fb):
                prof.fallback_rounds += 1
                phase_fallbacks += 1
            with phase_timer(prof, "occupancy_s"):
                n_act = occp(st, n_act, dep, arr, fb)
                jax.block_until_ready(n_act)
            with phase_timer(prof, "walk_s"):
                st, fx, n_act, n_p, n_nd, disp = round1(
                    tbl, adj, hi, st, fx, n_act
                )
                n_p_h = int(n_p)
            rounds += 1
            prof.rounds += 1
            prof.dispatches += int(disp)
            disp_total += int(disp)
        with phase_timer(prof, "bookkeeping_s"):
            found_all = (int(n_nd) == 0) and n_p_h == 0
            self.state = st
            self._fx_commit(tally, fx)
            # The last_* diagnostics keep their "most recent phase"
            # contract under profiling: the profiled driver already
            # holds the host values, so the caches are set directly
            # (no lazy device scalar to fetch).
            self._last_rounds_dev = None
            self._last_rounds_cache = rounds
            self._last_disp_dev = None
            self._last_disp_cache = disp_total
            self._last_frontier_max_dev = None
            self._last_frontier_max_cache = max(phase_fronts, default=0)
            self._last_frontier_sum_dev = None
            self._last_frontier_sum_cache = sum(phase_fronts)
            self._last_fallback_dev = None
            self._last_fallback_cache = phase_fallbacks
        return bool(found_all)

    def _run_phase(self, tally: bool, defer_sync: bool = False,
                   profile: Optional[PhaseProfile] = None):
        """One jitted walk+migrate phase.

        Default: a single host sync at the end; returns found_all
        (False if the round budget ran out), raising on overflow BEFORE
        committing so the engine keeps its pre-phase state.

        ``defer_sync=True`` (the streaming pipeline: chunk k+1's
        staging must overlap chunk k's walk) returns the LAZY
        (found_all, overflow) scalars and commits unconditionally — the
        caller syncs a whole batch of chunks at once and raises then;
        on overflow the state is corrupt, which is acceptable because
        the raise abandons the run.

        ``profile`` (a ``PhaseProfile``) switches to the round-by-round
        profiled driver — per-component fenced timing, one sync per
        section per round (``_run_phase_profiled``); incompatible with
        ``defer_sync``."""
        if profile is not None:
            if defer_sync:
                raise ValueError(
                    "profile= and defer_sync=True are mutually "
                    "exclusive (profiling syncs every round)"
                )
            return self._run_phase_profiled(tally, profile)
        self._last_phase_tally = tally  # defer-mode recovery context
        phase = self._phase_program(tally)
        st, fx, found_all, ovf, rounds, disp, fmax, fsum, nfb = phase(
            self.part.table, self.part.adj_int, self.part.table_hi,
            self.state, self._fx_in(tally),
        )
        # Lazy device scalars; fetched only if someone reads the
        # last_walk_rounds / last_block_dispatches diagnostics (a fetch
        # is a sync; the host int is cached after the first read, like
        # _n_lost).
        self._last_rounds_dev = rounds
        self._last_rounds_cache = None
        self._last_disp_dev = disp
        self._last_disp_cache = None
        self._last_frontier_max_dev = fmax
        self._last_frontier_max_cache = None
        self._last_frontier_sum_dev = fsum
        self._last_frontier_sum_cache = None
        self._last_fallback_dev = nfb
        self._last_fallback_cache = None
        if defer_sync:
            self.state = st
            self._fx_commit(tally, fx)
            return found_all, ovf
        ovf_v, found_v = jax.device_get((ovf, found_all))
        # Overflow-safe migrate: the committed state on overflow is the
        # intact pre-migrate snapshot of the failed round — safe to
        # commit, then recover instead of raise.
        self.state = st
        self._fx_commit(tally, fx)
        if bool(ovf_v):
            return self._recover_overflow(tally)
        return bool(found_v)

    # -- overflow recovery + straggler escalation (round 9) --------------
    def _resume_phase(self, tally: bool, iters_mult: int = 1,
                      rounds_mult: int = 1,
                      force_full_migrate: bool = False):
        """Run a recovery-family phase program over the COMMITTED
        mid-phase state and commit the result. Returns
        ``(found_all, overflowed)`` as host bools — recovery paths are
        rare and synchronous by design."""
        phase = self._phase_program(
            tally, resume=True, iters_mult=iters_mult,
            rounds_mult=rounds_mult,
            force_full_migrate=force_full_migrate,
        )
        st, fx, found_all, ovf, rounds, disp, fmax, fsum, nfb = phase(
            self.part.table, self.part.adj_int, self.part.table_hi,
            self.state, self._fx_in(tally),
        )
        ovf_v, found_v = jax.device_get((ovf, found_all))
        self.state = st
        self._fx_commit(tally, fx)
        self._last_rounds_dev = rounds
        self._last_rounds_cache = None
        self._last_disp_dev = disp
        self._last_disp_cache = None
        return bool(found_v), bool(ovf_v)

    def _note_recovery(self, escalated: bool) -> None:
        self.overflow_recoveries += 1
        if self.on_overflow_recovered is not None:
            self.on_overflow_recovered(escalated)

    def _poison(self) -> None:
        """Latch the poisoned flag and fire the safety-save hook (a
        facade with a resilience policy writes one last generation of
        the still-intact pre-overflow state before the raise)."""
        self.poisoned = True
        if self.on_poisoned is not None:
            try:
                self.on_poisoned()
            except Exception as e:  # noqa: BLE001 — best-effort save
                warnings.warn(f"overflow safety save failed: {e}")
        raise RuntimeError(LADDER_EXHAUSTED_MESSAGE)

    def _recover_overflow(self, tally: bool) -> bool:
        """The overflow-recovery ladder, from a committed intact
        mid-phase snapshot (overflow-safe migrate):

        1. resume the phase through the FULL-CAPACITY migrate path —
           ``_migrate_impl`` re-compacts every part, so the retry
           doubles as a defragmenter (and bypasses the frontier slab
           when one is configured);
        2. escalate once to the demand the committed snapshot shows
           (``_needed_capacity_growth``): grow every part's slot
           capacity host-side (``_grow_state`` — a pure slot
           relabeling, particle state bitwise-preserved) and resume;
        3. the resumed phase can still overflow — mid-phase demand
           accrues over FUTURE migration rounds the snapshot cannot
           see — so the terminal rung escalates to the mathematical
           bound (every part can host the whole population:
           ``cap_per_block > n`` makes overflow impossible) and
           resumes once more;
        4. an overflow past that is an internal invariant violation →
           safety-save hook, poison, raise.
        """
        ok, ovf = self._resume_phase(tally, force_full_migrate=True)
        if not ovf:
            self._note_recovery(escalated=False)
            return ok
        self._escalate_capacity(self._needed_capacity_growth())
        ok, ovf = self._resume_phase(tally, force_full_migrate=True)
        if not ovf:
            self._note_recovery(escalated=True)
            return ok
        terminal = 1.05 * (self.n + 2) / max(self.cap_per_block, 1)
        if terminal > 1.0:
            self._escalate_capacity(terminal)
            ok, ovf = self._resume_phase(tally, force_full_migrate=True)
            if not ovf:
                self._note_recovery(escalated=True)
                return ok
        self._poison()  # raises
        return False  # pragma: no cover — _poison always raises

    def _needed_capacity_growth(self) -> float:
        """Size the ONE capacity escalation from the actual demand:
        the committed snapshot's per-part population (stayers +
        pending arrivals, from the intact pending rows) tells exactly
        how many slots the worst part needs — a blind 2x would leave a
        pathological concentration still overflowing and burn the
        ladder's only escalation. Host fetch of two slot lanes; a
        recovery event, not a hot path."""
        pending = np.asarray(self.state["pending"])
        alive = np.asarray(self.state["alive"])
        slot_chip = np.arange(self.cap) // self.cap_per_block
        target = np.where(
            pending >= 0, pending // self.part.L, slot_chip
        )
        counts = np.bincount(target[alive], minlength=self.nparts)
        needed = int(counts.max()) + 1
        return max(2.0, 1.1 * needed / max(self.cap_per_block, 1))

    def _escalate_capacity(self, factor: float = 2.0) -> None:
        """Host-side rebuild at a larger per-block capacity: slot
        arrays grow in place (``_grow_state``), the padded flux and the
        partition are untouched (capacity is a slot-side quantity), and
        the phase/locate programs recompile for the new geometry (the
        jit-cache keys carry ``cap_per_chip``)."""
        old_cb = self.cap_per_block
        new_cb = int(old_cb * float(factor)) + 1
        if self.blocks_per_chip > 1 and self.block_kernel in (
            "vmem", "pallas"
        ):
            from pumiumtally_tpu.ops.vmem_walk import W_TILE_DEFAULT

            new_cb = -(-new_cb // W_TILE_DEFAULT) * W_TILE_DEFAULT
        self.capacity_factor *= float(factor)
        self.capacity_escalations += 1
        self.state = _grow_state(
            self.state, old_cb, new_cb, self.nparts
        )
        self.cap_per_block = new_cb
        self.cap_per_chip = self.blocks_per_chip * new_cb
        self.cap = self.nparts * new_cb
        if self.cap_frontier is not None:
            self.cap_frontier = min(self.cap_frontier, self.cap)
        # The collective closures bake the OLD cap_per_block (ring slot
        # ranges, slab geometry) — rebuild them for the grown engine.
        self._build_collective_fns()

    def retry_stragglers(self, iters_factor: int = 2) -> bool:
        """Straggler rung for the partitioned engine: resume the
        interrupted phase over the committed state with multiplied
        iteration AND round budgets — the compaction is inherent (done
        particles never re-walk under ``resume=True``, and the gather
        sub-split dispatches occupied blocks only). The multipliers
        floor the effective budgets at the mesh-derived safe bounds
        (a deliberately tiny engine budget — the truncation scenario
        this ladder exists for — must not starve its own cure; both
        loops exit early, so generosity costs nothing). Returns
        found_all; an overflow during the retry goes through the same
        recovery ladder."""
        f = int(iters_factor)
        need_iters = max(self.max_iters * f, 64 + self.part.L)
        need_rounds = max(self.max_rounds * f, 64)
        ok, ovf = self._resume_phase(
            True,
            iters_mult=-(-need_iters // self.max_iters),
            rounds_mult=-(-need_rounds // self.max_rounds),
        )
        if ovf:
            return self._recover_overflow(True)
        return ok

    def declare_lost_stragglers(self) -> int:
        """Ladder exhausted: fold the still-unfinished particles into
        the ``lost`` flag — excluded from transport (their committed
        position is a mid-flight partial point the caller does not
        know about), counted by ``lost_particles``, revivable by a
        re-located source exactly like localization losses. Returns
        how many were declared (a host fetch; the quarantine path
        needs their records anyway)."""
        st = dict(self.state)
        strag = st["alive"] & ~st["done"] & ~st["lost"]
        n = int(jnp.sum(strag))
        if n == 0:
            return 0
        st["lost"] = st["lost"] | strag
        st["fly"] = jnp.where(strag, jnp.asarray(0, st["fly"].dtype),
                              st["fly"])
        st["done"] = st["done"] | strag
        st["pending"] = jnp.where(strag, -1, st["pending"]).astype(
            jnp.int32
        )
        self.state = st
        self._n_lost_dev = jnp.sum(st["lost"])
        self._n_lost_cache = None
        return n

    def caller_order_view(self, keys=("x", "lelem", "done")) -> dict:
        """Caller-order device views of slot-state rows (sentinel
        audit / quarantine, and the ``elem_ids`` output path): one
        stable argsort by pid, then row gathers — [n]-shaped, original
        particle order. ``elem_orig`` maps local elements to original
        ids with lost rows masked to −1 (their lelem is meaningless
        and must not read as a real element — same contract as
        ``elem_ids``)."""
        o = self._order()
        out = {}
        for k in keys:
            if k == "elem_orig":
                glid = (
                    (jnp.cumsum(jnp.ones_like(self.state["pid"])) - 1)
                    // self.cap_per_block
                ) * self.part.L + self.state["lelem"]
                out[k] = jnp.where(
                    self.state["lost"][o], -1,
                    self.part.orig_of_glid[glid[o]],
                )
            else:
                out[k] = self.state[k][o]
        return out

    def move(
        self,
        origins_n: Optional[jnp.ndarray],
        dests_n: jnp.ndarray,
        fly_n: jnp.ndarray,
        w_n: jnp.ndarray,
        defer_sync: bool = False,
        profile: Optional[PhaseProfile] = None,
        sbin_n: Optional[jnp.ndarray] = None,
        sfac_n: Optional[jnp.ndarray] = None,
    ):
        """Full (or continue-mode) tallied move.

        Returns found_all (bool), or with ``defer_sync=True`` the lazy
        (found_all, overflow) pair — see ``_run_phase``. ``profile``
        accumulates a per-component budget of every phase this move
        runs into the given ``PhaseProfile`` (measurement mode — one
        sync per section per round). ``sbin_n``/``sfac_n`` (scoring-
        armed engines only) are the move's caller-order bin-lane
        offsets and factor rows (scoring.ScoringRuntime.resolve) —
        routed to slots by pid like fly/w, then MIGRATED with their
        particles through every round."""
        if self.scoring is not None and (sbin_n is None or sfac_n is None):
            raise ValueError(
                "scoring-armed engine needs sbin_n/sfac_n each move "
                "(scoring.ScoringRuntime.resolve)"
            )
        if origins_n is not None and self._n_lost:
            # Revival: a resampled origin inside the mesh re-locates a
            # lost particle (mirrors the single-chip engine, where
            # phase A walks the reincarnated particle to its new
            # origin, PumiTallyImpl.cpp:88-109).
            self._revive_lost(origins_n)
        st = self.state
        st["fly"] = self._by_pid(fly_n, jnp.asarray(0, jnp.int8)).astype(jnp.int8)
        # Lost particles (no containing element at localization) never
        # fly: an undefined start element must not produce tallies.
        st["fly"] = jnp.where(st["lost"], jnp.asarray(0, jnp.int8), st["fly"])
        st["w"] = self._by_pid(w_n, jnp.asarray(0.0, st["w"].dtype))
        if self.scoring is not None:
            # Dead-slot fill is irrelevant (done slots never cross);
            # zeros keep the rows cheap to compare in tests.
            st["sbin"] = self._by_pid(
                jnp.asarray(sbin_n, jnp.int32), jnp.asarray(0, jnp.int32)
            )
            st["sfac"] = self._by_pid(
                sfac_n, jnp.asarray(0.0, st["sfac"].dtype)
            )
        ok_a = True
        ovf_a = None
        if origins_n is not None:
            # Phase A: relocate to origins, weights zeroed (cpp:105).
            st["dest"] = self._by_pid(origins_n, jnp.asarray(0.0, st["x"].dtype))
            st["w"] = jnp.zeros_like(st["w"])
            self.state = st
            ra = self._run_phase(tally=False, defer_sync=defer_sync,
                                 profile=profile)
            if defer_sync:
                ok_a, ovf_a = ra
            else:
                ok_a = ra
            st = self.state
            # Re-route the real weights by pid: phase-A migrations may
            # have permuted every slot, so a saved pre-phase copy would
            # assign particle Q's weight to particle P.
            st["w"] = self._by_pid(w_n, jnp.asarray(0.0, st["w"].dtype))
        st["dest"] = self._by_pid(dests_n, jnp.asarray(0.0, st["x"].dtype))
        self.state = st
        rb = self._run_phase(tally=True, defer_sync=defer_sync,
                             profile=profile)
        if defer_sync:
            ok_b, ovf_b = rb
            ovf = ovf_b if ovf_a is None else (ovf_a | ovf_b)
            # Per-phase lazy flags for the deferred recovery: a
            # phase-B-only overflow resumes through the ladder at the
            # caller's sync point; a phase-A overflow that phase B has
            # already walked over is unrecoverable (poison).
            self._last_defer_flags = (ovf_a, ovf_b)
            return ok_a & ok_b, ovf
        return ok_a and rb

    def _revive_lost(self, origins_n: jnp.ndarray) -> None:
        """Re-locate lost particles whose resampled origin lies inside
        the mesh; they rejoin transport from that origin."""
        glid = self._locate_points(origins_n)
        sentinel = self.nparts * self.part.L
        st = dict(self.state)
        pend = self._by_pid(jnp.where(glid < sentinel, glid, -1), -1)
        revive = st["lost"] & (pend >= 0)
        st["x"] = jnp.where(
            revive[:, None],
            self._by_pid(origins_n, jnp.zeros((), st["x"].dtype)),
            st["x"],
        )
        st["pending"] = jnp.where(revive, pend, -1).astype(jnp.int32)
        st["lost"] = st["lost"] & ~revive
        self.state, overflow = migrate(
            part_L=self.part.L, ndev=self.nparts,
            cap_per_chip=self.cap_per_block, state=st,
            partition_method=self.partition_method,
        )
        if bool(overflow):
            # Same ladder as localization: one demand-sized escalation,
            # retry the placement over the intact snapshot, poison on
            # failure.
            self._escalate_capacity(self._needed_capacity_growth())
            self.state, overflow = migrate(
                part_L=self.part.L, ndev=self.nparts,
                cap_per_chip=self.cap_per_block, state=self.state,
                partition_method=self.partition_method,
            )
            if bool(overflow):
                self._poison()  # raises
            self._note_recovery(escalated=True)
        self.state["pending"] = jnp.full((self.cap,), -1, jnp.int32)
        self._n_lost_dev = jnp.sum(self.state["lost"])
        self._n_lost_cache = None

    # -- outputs ---------------------------------------------------------
    def _check_overflow(self, overflow) -> None:
        if bool(overflow):
            raise RuntimeError(OVERFLOW_MESSAGE)

    def _order(self) -> jnp.ndarray:
        """Slot order returning caller-visible particle order."""
        pid = self.state["pid"]
        key = jnp.where(pid >= 0, pid, self.cap + 1)
        return jnp.argsort(key, stable=True)[: self.n]

    def positions(self) -> np.ndarray:
        return np.asarray(self.state["x"][self._order()])

    def elem_ids(self) -> np.ndarray:
        """Original (caller-visible) element ids per particle; −1 for
        lost particles (``caller_order_view`` holds the one mapping +
        masking definition)."""
        return np.asarray(
            self.caller_order_view(("elem_orig",))["elem_orig"]
        )

    def flux_original(self) -> jnp.ndarray:
        return self.part.flux_to_original(self.flux_padded)

    def score_original(self) -> jnp.ndarray:
        """Owned scoring lanes reordered into the CANONICAL flattened
        ``[E·B·S]`` layout (original element order) — the same
        per-element row gather as ``flux_to_original``, over ``B·S``
        lanes per element."""
        if self.score_padded is None:
            raise RuntimeError("engine has no scoring lanes configured")
        rows = self.score_padded.reshape(
            self.nparts * self.part.L, self.score_stride
        )
        return rows[self.part.glid_of_orig].reshape(-1)
