"""Partitioned-mesh mode: element ownership + particle migration.

The TPU-native form of the reference's mesh-partition parallelism
(SURVEY.md §2.3): PUMIPic's ``picparts`` assigns every element an owner
rank and ``search(migrate)`` ships particles that crossed a partition
boundary to the owning rank, rebuilding the particle structure
(reference PumiTallyImpl.cpp:530-539 builds the partition — with
all-zeros owners as shipped — and cpp:111,145 set the migration cadence).
Here:

- **Ownership** comes from a recursive coordinate bisection (RCB) over
  element centroids — balanced contiguous blocks per chip, computed once
  on the host (replaces EnGPar/owner files).
- **Per-chip mesh shard**: elements are renumbered so each chip's block
  is contiguous and padded to a common length L; the packed walk table
  (mesh/tetmesh.py) is rebuilt per chip with LOCAL adjacency: a face
  entry is a local element id, ``-1`` for the domain boundary (vacuum
  BC), or ``-(glid+2)`` for a neighbor owned by another chip, where
  ``glid = owner·L + local_id`` is the padded global id.
- **Local walk** (`walk_local`): the same masked lock-step ray/tet walk
  as ops/walk.py, but a particle whose exit face is remote PAUSES at
  the partition face (its partial track length is already tallied) and
  records the target glid in ``pending``.
- **Migration** (`migrate`): a global stable-sort-by-target scatter that
  moves paused particles to their owning chip's slot range — under jit
  over a sharded mesh this lowers to the all-to-all/collective-permute
  the reference gets from MPI. Slots are over-provisioned by
  ``capacity_factor``; overflow raises rather than silently dropping.
- **Flux** is owned: each chip accumulates only elements it owns, so no
  cross-chip reduction is needed at all (the ICI traffic is particle
  migration) and the result is deterministic by construction.

The first localization (CopyInitialPosition) walks particles over the
full replicated mesh — all particles start in element 0 (reference
semantics, PumiTallyImpl.cpp:492-528), which one chip owns, so an
ownership-restricted first walk would funnel the whole batch through
one chip. After localization, one migration distributes particles to
their owners and the replicated table is no longer used by the move
path.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pumiumtally_tpu.mesh.tetmesh import (
    TetMesh,
    WALK_TABLE_ADJ,
    WALK_TABLE_NORMALS,
    WALK_TABLE_OFFSETS,
)
from pumiumtally_tpu.parallel.sharded import _axis_name

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


# ---------------------------------------------------------------------------
# Host-side partition build
# ---------------------------------------------------------------------------

def rcb_partition(centroids: np.ndarray, nparts: int) -> np.ndarray:
    """owner[E] via recursive coordinate bisection of element centroids.

    Splits along the longest axis into two parts whose target sizes are
    proportional to the number of leaves on each side, so any nparts
    (not just powers of two) comes out balanced to ±1.
    """
    ne = centroids.shape[0]
    owner = np.zeros(ne, dtype=np.int32)

    def rec(idx: np.ndarray, first_part: int, nparts: int) -> None:
        if nparts == 1:
            owner[idx] = first_part
            return
        nl = nparts // 2
        nr = nparts - nl
        c = centroids[idx]
        axis = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, axis], kind="stable")
        split = int(round(len(idx) * nl / nparts))
        rec(idx[order[:split]], first_part, nl)
        rec(idx[order[split:]], first_part + nl, nr)

    rec(np.arange(ne), 0, nparts)
    return owner


@dataclasses.dataclass(frozen=True)
class MeshPartition:
    """Per-chip mesh shards + id mappings (host-built, device-resident)."""

    ndev: int
    nelems: int  # original element count E
    L: int  # padded per-chip element count
    owner: np.ndarray  # [E] original elem -> chip
    glid_of_orig: Any  # [E] int32, original elem -> padded global id
    orig_of_glid: Any  # [ndev*L] int32, padded global id -> orig elem (-1 pad)
    table: Any  # [ndev*L, 20] local walk rows (adj local-encoded)

    def flux_to_original(self, flux_padded: jnp.ndarray) -> jnp.ndarray:
        """Reorder an owned [ndev*L] flux into original element order."""
        return flux_padded[self.glid_of_orig]


def build_partition(
    mesh: TetMesh, ndev: int, dtype: Optional[Any] = None
) -> MeshPartition:
    """Partition ``mesh`` into ``ndev`` contiguous padded element blocks."""
    if dtype is None:
        dtype = mesh.coords.dtype
    coords = np.asarray(mesh.coords, dtype=np.float64)
    tet2vert = np.asarray(mesh.tet2vert)
    face_adj = np.asarray(mesh.face_adj)
    normals = np.asarray(mesh.face_normals, dtype=np.float64)
    offsets = np.asarray(mesh.face_offsets, dtype=np.float64)
    ne = tet2vert.shape[0]
    centroids = coords[tet2vert].mean(axis=1)

    owner = rcb_partition(centroids, ndev)
    counts = np.bincount(owner, minlength=ndev)
    L = int(counts.max())
    # Remote faces encode -(glid+2) with glid < ndev*L, so THAT is the
    # magnitude that must survive the float walk-table round-trip.
    if ndev * L + 2 >= 2 ** (np.finfo(np.dtype(dtype)).nmant + 1):
        raise ValueError(
            f"padded global id range {ndev * L + 2} not exactly "
            f"representable in {np.dtype(dtype).name} walk-table ids"
        )

    # Renumber: elements of chip d occupy glids [d*L, d*L+counts[d]).
    order = np.argsort(owner, kind="stable")  # orig elems grouped by owner
    rank_in_chip = np.empty(ne, dtype=np.int64)
    start = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank_in_chip[order] = np.arange(ne) - start[owner[order]]
    glid_of_orig = owner.astype(np.int64) * L + rank_in_chip
    orig_of_glid = np.full(ndev * L, -1, dtype=np.int32)
    orig_of_glid[glid_of_orig] = np.arange(ne, dtype=np.int32)

    # Local adjacency encoding per face.
    nb = face_adj  # [E,4] original ids, -1 boundary
    nb_owner = np.where(nb >= 0, owner[np.clip(nb, 0, ne - 1)], -1)
    nb_glid = np.where(nb >= 0, glid_of_orig[np.clip(nb, 0, ne - 1)], -1)
    same = nb_owner == owner[:, None]
    local_adj = np.where(
        nb < 0,
        -1,
        np.where(same, nb_glid - owner[:, None].astype(np.int64) * L,
                 -(nb_glid + 2)),
    ).astype(np.float64)

    # Padded per-chip walk table; padding rows have no crossing faces
    # (zero normals -> t_exit=inf -> 'reached') and are never entered.
    table = np.zeros((ndev * L, 20), dtype=np.float64)
    table[glid_of_orig, WALK_TABLE_NORMALS] = normals.reshape(ne, 12)
    table[glid_of_orig, WALK_TABLE_OFFSETS] = offsets
    table[glid_of_orig, WALK_TABLE_ADJ] = local_adj
    table[:, WALK_TABLE_ADJ][orig_of_glid < 0] = -1.0

    return MeshPartition(
        ndev=ndev,
        nelems=ne,
        L=L,
        owner=owner,
        glid_of_orig=jnp.asarray(glid_of_orig, jnp.int32),
        orig_of_glid=jnp.asarray(orig_of_glid),
        table=jnp.asarray(table, dtype=dtype),
    )


# ---------------------------------------------------------------------------
# Device-side local walk (per chip, inside shard_map)
# ---------------------------------------------------------------------------

def walk_local(
    table: jnp.ndarray,  # [L,20] this chip's walk rows
    x: jnp.ndarray,  # [S,3]
    lelem: jnp.ndarray,  # [S] local element ids
    dest: jnp.ndarray,  # [S,3]
    flying: jnp.ndarray,  # [S] int8
    weight: jnp.ndarray,  # [S]
    done: jnp.ndarray,  # [S] bool — finished this phase
    exited: jnp.ndarray,  # [S] bool
    flux: jnp.ndarray,  # [L] owned flux
    *,
    tally: bool,
    tol: float,
    max_iters: int,
) -> Tuple[jnp.ndarray, ...]:
    """Ownership-restricted walk: like ops.walk.walk but pauses (sets
    ``pending = glid``) when the exit face's neighbor lives on another
    chip. Returns (x, lelem, done, exited, pending, flux, iters)."""
    fdtype = x.dtype
    one = jnp.asarray(1.0, fdtype)
    flying_b = flying.astype(bool)
    # Derived from an input so it carries the varying type under
    # shard_map (a literal constant would break the while carry).
    pending0 = (lelem - lelem) - 1

    def cond(state):
        it, _x, _lelem, done, _exited, pending, _flux = state
        return (it < max_iters) & jnp.any(~done & (pending < 0))

    def body(state):
        it, x, lelem, done, exited, pending, flux = state
        active = ~done & (pending < 0)
        d = dest - x
        row = table[lelem]
        n = row.shape[0]
        fn = row[:, WALK_TABLE_NORMALS].reshape(n, 4, 3)
        fo = row[:, WALK_TABLE_OFFSETS]
        adj = row[:, WALK_TABLE_ADJ].astype(jnp.int32)
        denom = jnp.einsum("nfc,nc->nf", fn, d)
        numer = fo - jnp.einsum("nfc,nc->nf", fn, x)
        crossing = denom > tol
        t = jnp.where(crossing, numer / jnp.where(crossing, denom, one), jnp.inf)
        t = jnp.maximum(t, 0.0)
        t_exit = jnp.min(t, axis=1)
        f_exit = jnp.argmin(t, axis=1)
        reached = t_exit >= one
        t_step = jnp.where(reached, one, t_exit)
        x_new = x + t_step[:, None] * d
        nxt = jnp.take_along_axis(adj, f_exit[:, None], axis=1)[:, 0]
        hit_boundary = (~reached) & (nxt == -1)
        goes_remote = (~reached) & (nxt <= -2)

        if tally:
            seg = t_step * jnp.linalg.norm(d, axis=1)
            contrib = jnp.where(active & flying_b, seg * weight, 0.0)
            flux = flux.at[lelem].add(contrib, mode="drop")

        advance = active & ~reached & ~hit_boundary & ~goes_remote
        lelem = jnp.where(advance, nxt, lelem)
        x = jnp.where(active[:, None], x_new, x)
        pending = jnp.where(active & goes_remote, -nxt - 2, pending)
        done = done | (active & (reached | hit_boundary))
        exited = exited | (active & hit_boundary)
        return it + 1, x, lelem, done, exited, pending, flux

    it0 = jnp.asarray(0, jnp.int32)
    it, x, lelem, done, exited, pending, flux = lax.while_loop(
        cond, body, (it0, x, lelem, done, exited, pending0, flux)
    )
    return x, lelem, done, exited, pending, flux, it


# ---------------------------------------------------------------------------
# Global migration (jit-level; XLA inserts the collectives)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("part_L", "ndev", "cap_per_chip"))
def migrate(part_L: int, ndev: int, cap_per_chip: int, state: dict):
    """Ship paused particles (pending >= 0) to the chip owning their
    target element; everything else stays in its chip's slot range.

    ``state`` is a dict of [cap]-shaped arrays that must travel with the
    particle (x, lelem, pending, done, exited, alive, pid, dest, fly, w).
    Returns (new_state, overflowed) — overflow means some chip received
    more particles than its slot capacity.

    Jitted as ONE program: the sort/scatter over device-sharded arrays
    lowers to a single XLA module (one set of collectives), which both
    performs better and avoids flooding the runtime with per-op
    rendezvous (observed to trip XLA:CPU's 40s collective timeout when
    issued eagerly op-by-op on 8 virtual devices).
    """
    cap = state["pid"].shape[0]
    slot_chip = (jnp.cumsum(jnp.ones_like(state["pid"])) - 1) // cap_per_chip
    pending = state["pending"]
    alive = state["alive"]
    target = jnp.where(pending >= 0, pending // part_L, slot_chip)
    # Dead slots sort after every real group so they never consume a
    # real slot; their state is reset to defaults on the way out.
    key = jnp.where(alive, target, ndev)
    perm = jnp.argsort(key, stable=True)
    key_s = key[perm]
    counts = jnp.bincount(key, length=ndev + 1)
    starts = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                              jnp.cumsum(counts)[:-1]])
    pos = jnp.cumsum(jnp.ones_like(key_s)) - 1
    rank = pos - starts[key_s]
    overflow = jnp.any((key_s < ndev) & (rank >= cap_per_chip))
    dest_slot = jnp.where(
        key_s < ndev, key_s * cap_per_chip + rank, cap
    )  # dead -> out of bounds, dropped by the scatter

    new_state = {}
    defaults = _default_state(cap, state)
    for k, v in state.items():
        moved = v[perm]
        new_state[k] = defaults[k].at[dest_slot].set(moved, mode="drop")
    # Migrated particles resume inside their new chip's local mesh.
    arrived = new_state["pending"] >= 0
    new_state["lelem"] = jnp.where(
        arrived, new_state["pending"] % part_L, new_state["lelem"]
    )
    new_state["pending"] = jnp.where(arrived, -1, new_state["pending"])
    return new_state, overflow


def _default_state(cap: int, like: dict) -> dict:
    d = {}
    for k, v in like.items():
        if k == "alive":
            d[k] = jnp.zeros((cap,), bool)
        elif k == "done":
            d[k] = jnp.ones((cap,), bool)
        elif k in ("pending", "pid"):
            d[k] = jnp.full((cap,), -1, v.dtype)
        else:
            d[k] = jnp.zeros((cap,) + v.shape[1:], v.dtype)
    return d


# ---------------------------------------------------------------------------
# Round-driving engine
# ---------------------------------------------------------------------------

class PartitionedEngine:
    """Owns the partitioned particle state and drives walk/migrate rounds.

    Slots: ``cap = ndev * cap_per_chip`` particle slots; chip d owns
    slots [d*cap_per_chip, (d+1)*cap_per_chip). A particle's slot moves
    between chips only via ``migrate``; ``pid`` tracks its external
    (caller-visible) index.
    """

    def __init__(
        self,
        mesh: TetMesh,
        device_mesh: Mesh,
        num_particles: int,
        *,
        capacity_factor: float = 1.5,
        tol: float,
        max_iters: int,
        max_rounds: int = 64,
    ):
        self.mesh = mesh
        self.device_mesh = device_mesh
        self.axis = _axis_name(device_mesh)
        self.ndev = int(device_mesh.devices.size)
        self.n = int(num_particles)
        self.part = build_partition(mesh, self.ndev)
        self.cap_per_chip = int(
            -(-self.n // self.ndev) * capacity_factor + 1
        )
        self.cap = self.ndev * self.cap_per_chip
        self.tol = tol
        self.max_iters = max_iters
        self.max_rounds = max_rounds
        dtype = mesh.coords.dtype
        self.flux_padded = jnp.zeros((self.ndev * self.part.L,), dtype)
        # Initial layout: particle pid occupies slot pid (chips get
        # contiguous pid blocks); lelem/pending meaningless until the
        # first localization.
        pid = np.full(self.cap, -1, np.int32)
        pid[: self.n] = np.arange(self.n, dtype=np.int32)
        alive = pid >= 0
        self._round_fns: dict = {}
        self.state = {
            "x": jnp.zeros((self.cap, 3), dtype),
            "lelem": jnp.zeros((self.cap,), jnp.int32),
            "pending": jnp.full((self.cap,), -1, jnp.int32),
            "pid": jnp.asarray(pid),
            "alive": jnp.asarray(alive),
            "done": jnp.asarray(~alive),
            "exited": jnp.zeros((self.cap,), bool),
            "dest": jnp.zeros((self.cap, 3), dtype),
            "fly": jnp.zeros((self.cap,), jnp.int8),
            "w": jnp.zeros((self.cap,), dtype),
        }

    # -- staged input routing -------------------------------------------
    def _by_pid(self, arr_n: jnp.ndarray, fill) -> jnp.ndarray:
        """Route a caller-order [n,...] array to current slots via pid."""
        pid = self.state["pid"]
        safe = jnp.clip(pid, 0, self.n - 1)
        v = arr_n[safe]
        mask = (pid >= 0)
        if v.ndim == 2:
            return jnp.where(mask[:, None], v, fill)
        return jnp.where(mask, v, fill)

    # -- phases ----------------------------------------------------------
    def localize(self, dest_n: jnp.ndarray) -> Tuple[bool, bool]:
        """CopyInitialPosition: walk over the FULL mesh from element 0's
        centroid (reference cpp:492-528), then distribute to owners.
        Returns (found_all, any_exited)."""
        from pumiumtally_tpu.api.tally import _localize_step

        c0 = jnp.mean(
            self.mesh.coords[self.mesh.tet2vert[0]], axis=0
        ).astype(self.mesh.coords.dtype)
        x0 = jnp.broadcast_to(c0, (self.n, 3))
        e0 = jnp.zeros((self.n,), jnp.int32)
        x1, elem1, done, exited = _localize_step(
            self.mesh, x0, e0, dest_n, tol=self.tol, max_iters=self.max_iters
        )
        glid = self.part.glid_of_orig[elem1]
        st = self.state
        st = dict(st)
        st["x"] = self._by_pid(x1, jnp.zeros((), x1.dtype))
        st["pending"] = jnp.where(
            st["alive"], self._by_pid(glid, -1), st["pending"]
        ).astype(jnp.int32)
        st["done"] = ~st["alive"]
        st["exited"] = jnp.zeros((self.cap,), bool)
        self.state, overflow = migrate(
            part_L=self.part.L, ndev=self.ndev,
            cap_per_chip=self.cap_per_chip, state=st,
        )
        self._check_overflow(overflow)
        # Mark the phase finished for all particles.
        self.state["done"] = jnp.ones((self.cap,), bool)
        self.state["pending"] = jnp.full((self.cap,), -1, jnp.int32)
        return bool(jnp.all(done)), int(jnp.sum(exited))

    def _sharded_walk_round(self, tally: bool):
        """One shard_map'd local-walk pass over all chips (cached per
        tally flag so each is traced/compiled once per engine)."""
        if tally in self._round_fns:
            return self._round_fns[tally]
        pp = P(self.axis)
        ax = self.axis

        @jax.jit
        @partial(
            shard_map,
            mesh=self.device_mesh,
            in_specs=(pp, pp, pp, pp, pp, pp, pp, pp, pp),
            out_specs=(pp, pp, pp, pp, pp, pp, P(), P()),
        )
        def round_fn(table, x, lelem, dest, fly, w, done, exited, flux):
            x, lelem, done, exited, pending, flux, _ = walk_local(
                table, x, lelem, dest, fly, w, done, exited, flux,
                tally=tally, tol=self.tol, max_iters=self.max_iters,
            )
            # Global round status computed in-program (one psum) so the
            # host does a single scalar fetch per round instead of
            # issuing eager cross-device reductions.
            n_pending = lax.psum(jnp.sum(pending >= 0), ax)
            n_not_done = lax.psum(jnp.sum(~done), ax)
            return x, lelem, done, exited, pending, flux, n_pending, n_not_done

        self._round_fns[tally] = round_fn
        return round_fn

    def _run_phase(self, tally: bool) -> bool:
        """Walk+migrate rounds until no particle is active or pending.
        Returns found_all (False if the round budget ran out)."""
        st = self.state
        st["done"] = ~st["alive"] | (st["fly"] == 0)
        # Non-flying particles hold position: dest <- x.
        st["dest"] = jnp.where((st["fly"] == 1)[:, None], st["dest"], st["x"])
        round_fn = self._sharded_walk_round(tally)
        for _ in range(self.max_rounds):
            x, lelem, done, exited, pending, flux, n_pending, n_not_done = (
                round_fn(
                    self.part.table, st["x"], st["lelem"], st["dest"],
                    st["fly"], st["w"], st["done"], st["exited"],
                    self.flux_padded,
                )
            )
            st.update(x=x, lelem=lelem, done=done, exited=exited,
                      pending=pending)
            self.flux_padded = flux
            if int(n_pending) == 0:
                self.state = st
                return int(n_not_done) == 0
            st, overflow = migrate(
                part_L=self.part.L, ndev=self.ndev,
                cap_per_chip=self.cap_per_chip, state=st,
            )
            self._check_overflow(overflow)
        self.state = st
        return False

    def move(
        self,
        origins_n: Optional[jnp.ndarray],
        dests_n: jnp.ndarray,
        fly_n: jnp.ndarray,
        w_n: jnp.ndarray,
    ) -> bool:
        """Full (or continue-mode) tallied move. Returns found_all."""
        st = self.state
        st["fly"] = self._by_pid(fly_n, jnp.asarray(0, jnp.int8)).astype(jnp.int8)
        st["w"] = self._by_pid(w_n, jnp.asarray(0.0, st["w"].dtype))
        ok_a = True
        if origins_n is not None:
            # Phase A: relocate to origins, weights zeroed (cpp:105).
            st["dest"] = self._by_pid(origins_n, jnp.asarray(0.0, st["x"].dtype))
            st["w"] = jnp.zeros_like(st["w"])
            self.state = st
            ok_a = self._run_phase(tally=False)
            st = self.state
            # Re-route the real weights by pid: phase-A migrations may
            # have permuted every slot, so a saved pre-phase copy would
            # assign particle Q's weight to particle P.
            st["w"] = self._by_pid(w_n, jnp.asarray(0.0, st["w"].dtype))
        st["dest"] = self._by_pid(dests_n, jnp.asarray(0.0, st["x"].dtype))
        self.state = st
        ok_b = self._run_phase(tally=True)
        return ok_a and ok_b

    # -- outputs ---------------------------------------------------------
    def _check_overflow(self, overflow) -> None:
        if bool(overflow):
            raise RuntimeError(
                "partitioned-mode chip capacity exceeded during particle "
                "migration; raise TallyConfig.capacity_factor"
            )

    def _order(self) -> jnp.ndarray:
        """Slot order returning caller-visible particle order."""
        pid = self.state["pid"]
        key = jnp.where(pid >= 0, pid, self.cap + 1)
        return jnp.argsort(key, stable=True)[: self.n]

    def positions(self) -> np.ndarray:
        return np.asarray(self.state["x"][self._order()])

    def elem_ids(self) -> np.ndarray:
        """Original (caller-visible) element ids per particle."""
        o = self._order()
        glid = (
            (jnp.cumsum(jnp.ones_like(self.state["pid"])) - 1)
            // self.cap_per_chip
        ) * self.part.L + self.state["lelem"]
        return np.asarray(self.part.orig_of_glid[glid[o]])

    def flux_original(self) -> jnp.ndarray:
        return self.part.flux_to_original(self.flux_padded)
