"""SPMD parallelism over a ``jax.sharding.Mesh`` of TPU chips.

TPU-native replacement for the reference's MPI rank parallelism
(SURVEY.md §2.3): where the reference reaches MPI through
``pumipic::Library`` and the ``search(migrate)`` flag (reference
PumiTallyImpl.cpp:111,145,454), we shard the particle batch over a
``dp`` device-mesh axis with ``shard_map``, keep the tet mesh replicated
per chip (exactly the reference's all-elements-on-rank-0 partition,
PumiTallyImpl.cpp:530-539, generalized to every chip), and reduce the
per-element flux with ``psum`` over ICI.
"""

from pumiumtally_tpu.parallel.device import (
    initialize_distributed,
    make_device_mesh,
)
from pumiumtally_tpu.parallel.sharded import (
    sharded_localize_step,
    sharded_move_step,
    sharded_move_step_continue,
)
from pumiumtally_tpu.parallel.partition import (
    MeshPartition,
    PartitionedEngine,
    build_partition,
    rcb_partition,
)
from pumiumtally_tpu.parallel.distributed import (
    DistributedUnavailableError,
    assert_collectives_available,
    fetch_global,
    global_device_mesh,
    init_distributed,
    make_collective_migrate,
)

__all__ = [
    "initialize_distributed",
    "make_device_mesh",
    "sharded_localize_step",
    "sharded_move_step",
    "sharded_move_step_continue",
    "MeshPartition",
    "PartitionedEngine",
    "build_partition",
    "rcb_partition",
    "DistributedUnavailableError",
    "assert_collectives_available",
    "fetch_global",
    "global_device_mesh",
    "init_distributed",
    "make_collective_migrate",
]
