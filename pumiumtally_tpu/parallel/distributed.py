"""Pod-scale distributed campaigns: multi-process global mesh +
collective particle migration (round 13).

The reference reaches multi-node only through MPI inside
``pumipic::Library`` (reference PumiTallyImpl.cpp:238-241) and never
tests it; here the TPU-native equivalent is first-class. Three layers:

- ``init_distributed`` / ``global_device_mesh``: a robust front door
  over ``jax.distributed.initialize`` — argument validation with
  actionable errors, an idempotence guard (a second init in one
  process is a hard jax error with an unhelpful message), and a
  startup timeout — returning the 1-D global mesh over EVERY chip in
  the job. Engines built on that mesh shard element blocks, flux
  lanes, and (when armed) scoring banks across all processes' devices
  with no further code changes: the phase programs' shard_map spans
  the global axis and XLA routes the collectives over ICI/DCN (CPU
  test rigs: gloo, when the installed jaxlib carries it).

- ``make_collective_migrate``: cross-host particle migration as ONE
  explicit collective program. The global-scatter migrate
  (``partition._migrate_impl``) moves rows through a full-capacity
  scatter that GSPMD lowers to opaque resharding; this lowers the SAME
  redistribution to named collectives inside a shard_map — an
  ``all_gather`` of the counting-rank keys (PR 1's sort-free stable
  partition, recomputed bit-identically at global shape on every
  shard) and a ``ppermute`` ring that hands each shard's packed state
  slab around the axis, every shard keeping exactly the rows whose
  destination slot it owns. Destinations are globally unique (stable
  within-target ranks), so arrival order cannot matter and the result
  is BITWISE equal to the global scatter — pinned by
  tests/test_distributed.py. A particle leaving a host-owned block
  lands on the owning host in one launch, and the per-hop traffic is
  explicit (``modeled_migration_collective_bytes``) instead of
  whatever GSPMD chose this jaxlib.

- ``fetch_global``: host fetch of a possibly multi-process-sharded
  array (a plain ``np.asarray`` raises on non-addressable shards).

``assert_collectives_available`` is the runtime probe behind the
"skip, don't fail" contract for CPU multi-process tests: jaxlib builds
without cross-process CPU collectives (no gloo — e.g. jaxlib 0.4.x)
raise ``DistributedUnavailableError`` from one tiny psum instead of
failing deep inside the first real phase program.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pumiumtally_tpu.parallel.device import make_device_mesh
from pumiumtally_tpu.parallel.sharded import (
    axis_name,
    shard_map_check_kwargs,
)

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


#: Subprocess exit code meaning "distributed backend unavailable on
#: this jaxlib — skip, don't fail" (the automake SKIP convention).
#: Worker drivers exit with it; test launchers map it to pytest.skip.
UNAVAILABLE_EXIT_CODE = 77

#: Stdout marker the workers print next to the exit code, so launchers
#: (and humans reading CI logs) see WHY the run skipped.
UNAVAILABLE_MARKER = "DISTRIBUTED-UNAVAILABLE"


class DistributedUnavailableError(RuntimeError):
    """The installed jaxlib cannot execute cross-process collectives on
    this backend (e.g. a CPU jaxlib without gloo). Environmental, not a
    code bug: callers should SKIP multi-process work, not fail it."""


def global_device_mesh(axis_name: str = "dp") -> Mesh:
    """1-D mesh over every device in the job — all processes' chips
    after ``init_distributed``, the local devices otherwise."""
    return make_device_mesh(axis_name=axis_name)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    axis_name: str = "dp",
    initialization_timeout: Optional[float] = None,
) -> Mesh:
    """Join (or create) the ``jax.distributed`` job and return the 1-D
    global mesh; the robust replacement for calling
    ``jax.distributed.initialize`` directly.

    On Cloud TPU pods all three identifiers are inferred from the
    environment (pass nothing). Elsewhere, pass all three. Adds what
    the raw call lacks:

    - argument validation with actionable errors (a partial identifier
      set otherwise dies inside the coordinator handshake with a
      timeout whose message names none of the missing pieces);
    - idempotence: a process that already joined a matching job gets
      the global mesh back instead of jax's "already initialized"
      RuntimeError (service workers re-entering setup paths);
    - ``initialization_timeout`` (seconds) for the coordinator
      handshake, defaulting to the ``PUMIUMTALLY_COORD_TIMEOUT``
      environment variable when set — subprocess test rigs bound the
      worst case (a peer that never starts) well under the suite
      timeout instead of hanging for jax's 300 s default.
    """
    explicit = (coordinator_address, num_processes, process_id)
    if any(v is not None for v in explicit) and None in explicit:
        missing = [
            n for n, v in zip(
                ("coordinator_address", "num_processes", "process_id"),
                explicit,
            ) if v is None
        ]
        raise ValueError(
            "init_distributed needs coordinator_address, num_processes "
            "AND process_id together (or none of them, on a platform "
            f"where jax infers all three); missing {missing}"
        )
    if num_processes is not None:
        num_processes = int(num_processes)
        process_id = int(process_id)
        if num_processes < 1:
            raise ValueError(
                f"num_processes must be >= 1, got {num_processes}"
            )
        if not 0 <= process_id < num_processes:
            raise ValueError(
                f"process_id must be in [0, {num_processes}), "
                f"got {process_id}"
            )
    if _already_initialized():
        return make_device_mesh(axis_name=axis_name)
    if initialization_timeout is None:
        env = os.environ.get("PUMIUMTALLY_COORD_TIMEOUT")
        initialization_timeout = float(env) if env else None
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = int(initialization_timeout)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    return make_device_mesh(axis_name=axis_name)


def _already_initialized() -> bool:
    """Whether this process already joined a jax.distributed job (the
    client object jax.distributed.shutdown tears down)."""
    state = getattr(jax.distributed, "global_state", None)
    if state is None:  # pragma: no cover — future jax relocation
        from jax._src import distributed as _dist

        state = _dist.global_state
    return getattr(state, "client", None) is not None


def assert_collectives_available(device_mesh: Mesh) -> None:
    """Probe that this jaxlib can EXECUTE a cross-process collective on
    ``device_mesh`` — one int psum, caught at the probe instead of deep
    inside the first phase program.

    Single-process meshes trivially pass (virtual-device collectives
    always work). Multi-process CPU without gloo (jaxlib 0.4.x:
    "Multiprocess computations aren't implemented on the CPU backend")
    raises ``DistributedUnavailableError`` — the environmental
    skip-don't-fail signal for test launchers and A/B tools."""
    if jax.process_count() == 1:
        return
    ax = axis_name(device_mesh)
    ndev = int(device_mesh.devices.size)
    probe = shard_map(
        lambda v: lax.psum(jnp.sum(v), ax),
        mesh=device_mesh,
        in_specs=P(ax),
        out_specs=P(),
        **shard_map_check_kwargs(),
    )
    try:
        got = int(jax.jit(probe)(jnp.ones((ndev,), jnp.int32)))
    except Exception as e:  # noqa: BLE001 — classifying a backend error
        msg = str(e)
        if ("Multiprocess computations aren't implemented" in msg
                or "gloo" in msg.lower()
                or "cross-host" in msg.lower()):
            raise DistributedUnavailableError(
                f"{UNAVAILABLE_MARKER}: this jaxlib cannot run "
                f"cross-process collectives on the "
                f"{device_mesh.devices.flat[0].platform} backend "
                f"({msg.splitlines()[0]})"
            ) from e
        raise
    if got != ndev:  # pragma: no cover — a silently wrong collective
        raise RuntimeError(
            f"collective probe psum returned {got}, expected {ndev}"
        )


def fetch_global(x) -> np.ndarray:
    """Host numpy copy of a (possibly multi-process-sharded) array.

    ``np.asarray`` raises on arrays with non-addressable shards (every
    globally-sharded array outside process 0's slice); the multihost
    allgather assembles the global value on every process instead.
    Single-process (and replicated) arrays take the direct path, so
    tier-1 callers pay nothing new."""
    if isinstance(x, np.ndarray):
        return x
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


# -- collective migration ---------------------------------------------------


def state_pack_columns(state: dict) -> tuple:
    """(float_cols, int_cols) of the packed particle-state matrices —
    the per-row width the migration collective ships (the cost-model
    input of ``modeled_migration_collective_bytes``)."""
    fcols = icols = 0
    for v in state.values():
        cols = 1
        for s in v.shape[1:]:
            cols *= int(s)
        if jnp.issubdtype(v.dtype, jnp.floating):
            fcols += cols
        else:
            icols += cols
    return fcols, icols


def modeled_migration_collective_bytes(
    cap: int,
    ndev: int,
    float_cols: int,
    int_cols: int,
    float_bytes: int = 8,
) -> int:
    """Bytes each process SENDS per collective migration round.

    Two collectives: the [cap] int32 key all_gather (each shard sends
    its ``cap/ndev`` tile to the other ``ndev-1`` shards) and the
    ``ndev-1`` ppermute hops of the packed local slab (float pack +
    int32 pack + the int32 destination lane). Deterministic from the
    shapes — reported by tools/exp_distributed_ab.py next to the
    measured rates so interconnect regressions are attributable."""
    n_loc = cap // ndev
    keys = (ndev - 1) * n_loc * 4
    slab = n_loc * (float_cols * float_bytes + int_cols * 4 + 4)
    return keys + (ndev - 1) * slab


def derive_host_counts(device_mesh: Mesh) -> tuple:
    """Chips per host (jax process), in mesh device order — the host
    geometry ``placement="pod_rcb"`` aligns element ownership to.

    The pod placement contract rests on hosts owning CONTIGUOUS device
    ranges (so contiguous part ranges): a mesh whose device order
    interleaves processes is refused rather than silently mis-modeled.
    Single-process meshes (tier-1's 8 virtual devices) answer
    ``(ndev,)`` — one "host" owning everything; virtual multi-host
    layouts come from ``TallyConfig.placement_hosts`` instead."""
    procs = [int(d.process_index) for d in device_mesh.devices.flat]
    counts: list = []
    order: list = []
    for p in procs:
        if order and p == order[-1]:
            counts[-1] += 1
            continue
        if p in order:
            raise ValueError(
                f"device mesh interleaves process {p}'s devices — "
                "pod_rcb placement needs hosts contiguous in mesh "
                f"device order (process sequence {procs})"
            )
        order.append(p)
        counts.append(1)
    return tuple(counts)


def modeled_cross_host_migration_bytes(
    remote_faces,
    blocks_per_chip: int,
    host_counts,
    float_cols: int,
    int_cols: int,
    float_bytes: int = 8,
) -> int:
    """Modeled per-round CROSS-HOST migration bytes of a partition
    under its host layout — the placement-quality diagnostic
    ``placement="pod_rcb"`` exists to minimize.

    Each directed cross-part face (``MeshPartition.remote_faces``: part
    a exposes ``n`` element faces to part b) is one potential migrating
    row per round; a row bound from a's device to b's device rides the
    host-level ring, paying one packed-row transfer
    (``state_pack_columns`` widths + the int32 destination lane, the
    same row the collective actually ships) per host-boundary hop —
    ``(host_b - host_a) mod nhosts`` crossings. Faces between parts on
    one host cost zero DCN; single-host layouts answer 0. Deterministic
    from the partition + host geometry — compare ``placement`` arms
    without running anything (tools/exp_placement_ab.py)."""
    host_counts = [int(h) for h in host_counts]
    host_of_dev = np.repeat(np.arange(len(host_counts)), host_counts)
    nhosts = len(host_counts)
    row_bytes = float_cols * float_bytes + int_cols * 4 + 4
    total = 0
    for a, b, n in np.asarray(remote_faces):
        ha = int(host_of_dev[int(a) // int(blocks_per_chip)])
        hb = int(host_of_dev[int(b) // int(blocks_per_chip)])
        total += int(n) * ((hb - ha) % nhosts) * row_bytes
    return int(total)


def _defaults_like(state: dict) -> dict:
    """Dead-slot defaults with the SAME values as
    ``partition._default_state`` (alive False, done True, pending/pid
    -1, zeros elsewhere), built with *_like constructors from the local
    shard so the values carry the operands' types under shard_map."""
    d = {}
    for k, v in state.items():
        if k == "alive":
            d[k] = jnp.zeros_like(v)
        elif k == "done":
            d[k] = jnp.ones_like(v)
        elif k in ("pending", "pid"):
            d[k] = jnp.full_like(v, -1)
        else:
            d[k] = jnp.zeros_like(v)
    return d


def make_collective_migrate(
    device_mesh: Mesh,
    *,
    part_L: int,
    nparts: int,
    cap_per_block: int,
    partition_method: str = "rank",
):
    """Build the shard_map'd collective migration:
    ``fn(state) -> (new_state, overflow)``, bitwise equal to
    ``partition._migrate_impl(part_L, nparts, cap_per_block, state)``.

    ``state`` is the partitioned engine's dict of [cap, ...] arrays
    (cap = nparts * cap_per_block), sharded — or reshardable — over the
    mesh axis in slot order, so each of the ``ndev`` shards owns
    ``cap/ndev`` consecutive slots (= ``blocks_per_chip`` element
    blocks). Per shard:

    1. local counting-rank keys (``nparts`` = dead sentinel), exactly
       the global impl's ``where(alive, target, nparts)``;
    2. ``all_gather(tiled)`` reassembles the [cap] key array in global
       slot order; ``counting_ranks`` over it is integer math on
       identical input, hence bit-identical ranks — each shard slices
       its own range back out;
    3. destination slots ``key * cap_per_block + rank`` are globally
       unique (stable ranks), dead rows out of range;
    4. the local state packs into one float + one int32 matrix
       (``partition._pack_state`` — the exact pack the global scatter
       moves) and rides a ``ppermute`` ring: ``ndev`` scatter steps,
       each shard keeping the visiting rows whose destination falls in
       its slot range (everything else drops). Unique destinations ⇒
       arrival order cannot matter ⇒ the assembled shard equals the
       global scatter's slice bitwise;
    5. overflow (any target bucket past ``cap_per_block``) reduces with
       an int psum; on overflow the PRE-migrate state commits verbatim
       — the same overflow-safe contract as the global impl, so the
       host recovery ladder works unchanged.

    The returned fn is jit-traceable (the phase while_loop inlines it
    exactly where it inlines ``_migrate_impl``).
    """
    # Deferred import: partition.py imports this module at load time
    # (the engine wires the collective path), so the pack helpers —
    # shared so the two migrate forms can never drift — resolve lazily.
    from pumiumtally_tpu.parallel.partition import (
        _pack_state,
        _unpack_state,
    )
    from pumiumtally_tpu.ops.bucketize import counting_ranks

    ax = axis_name(device_mesh)
    ndev = int(device_mesh.devices.size)
    cap = nparts * cap_per_block
    if cap % ndev:
        raise ValueError(
            f"capacity {cap} is not divisible by the {ndev}-device mesh"
        )
    n_loc = cap // ndev
    ring = [(i, (i + 1) % ndev) for i in range(ndev)]

    def shard_body(state):
        pending = state["pending"]
        alive = state["alive"]
        iota = jnp.cumsum(jnp.ones_like(pending)) - 1  # varying local iota
        my_base = lax.axis_index(ax).astype(iota.dtype) * n_loc
        slot_part = (my_base + iota) // cap_per_block
        target = jnp.where(pending >= 0, pending // part_L, slot_part)
        key = jnp.where(alive, target, nparts).astype(jnp.int32)
        # Global rank, recomputed bit-identically on every shard from
        # the gathered global key array (integer math — no float
        # reduction order anywhere in the rank).
        keys_g = lax.all_gather(key, ax, tiled=True)
        rank_g = counting_ranks(keys_g, nparts + 1,
                                method=partition_method)
        rank = lax.dynamic_slice(rank_g, (my_base,), (n_loc,))
        ovf_mine = jnp.sum(
            ((key < nparts) & (rank >= cap_per_block)).astype(jnp.int32)
        )
        overflow = lax.psum(ovf_mine, ax) > 0
        dest = jnp.where(
            key < nparts, key * cap_per_block + rank, cap
        ).astype(iota.dtype)

        fpack, ipack, fdef, idef, layout = _pack_state(
            state, _defaults_like(state)
        )

        def hop(_s, carry):
            acc_f, acc_i, vis_f, vis_i, vis_d = carry
            # Keep the visiting rows this shard owns; everything else
            # drops past the local range (sentinel n_loc).
            mine = (vis_d >= my_base) & (vis_d < my_base + n_loc)
            idx = jnp.where(mine, vis_d - my_base, n_loc)
            acc_f = acc_f.at[idx].set(vis_f, mode="drop")
            acc_i = acc_i.at[idx].set(vis_i, mode="drop")
            return (
                acc_f,
                acc_i,
                lax.ppermute(vis_f, ax, ring),
                lax.ppermute(vis_i, ax, ring),
                lax.ppermute(vis_d, ax, ring),
            )

        acc_f, acc_i, _vf, _vi, _vd = lax.fori_loop(
            0, ndev, hop, (fdef, idef, fpack, ipack, dest)
        )
        new_state = _unpack_state(acc_f, acc_i, layout)
        # Arrived particles resume inside their new block's local mesh
        # — elementwise, identical to the global impl's fixup.
        arrived = new_state["pending"] >= 0
        new_state["lelem"] = jnp.where(
            arrived, new_state["pending"] % part_L, new_state["lelem"]
        )
        new_state["pending"] = jnp.where(
            arrived, -1, new_state["pending"]
        )
        # Overflow-safe commit: a colliding scatter never lands — the
        # pre-migrate shard survives verbatim for the recovery ladder.
        new_state = {
            k: jnp.where(overflow, state[k], v)
            for k, v in new_state.items()
        }
        return new_state, overflow

    def collective_migrate(state):
        return shard_map(
            shard_body,
            mesh=device_mesh,
            in_specs=(P(ax),),
            out_specs=({k: P(ax) for k in state}, P()),
            **shard_map_check_kwargs(),
        )(state)

    return collective_migrate


def make_collective_frontier_migrate(
    device_mesh: Mesh,
    *,
    part_L: int,
    nparts: int,
    cap_per_block: int,
    cap_frontier: int,
    partition_method: str = "rank",
):
    """Frontier-slab migration as the SAME 5-step collective program —
    ``fn(state) -> (new_state, overflow, departures, arrivals)``,
    bitwise equal to ``partition._frontier_migrate_impl`` (round 18's
    composition of the two migrate optimizations: PR 4's slab, PR 12's
    ring).

    ``make_collective_migrate``'s ppermute ring hands FULL-CAPACITY
    packed slabs around the axis every round; here the ring carries
    ``cap_frontier`` rows — the crossing front — so cross-host traffic
    scales with the front like the on-chip slab path does. Per shard:

    1. ``all_gather(tiled)`` reassembles the [cap] ``pending``/``alive``
       lanes (int32/bool bookkeeping — a few bytes per slot, the same
       O(cap) lane the impl keeps on chip);
    2. every shard replays the impl's GLOBAL machinery on those
       identical inputs — stable binary-partition compaction, the
       stayer-fixed free-slot prefix sums, the slab-sized counting rank
       — integer math, hence bit-identical src/dest/overflow on every
       shard;
    3. each shard clears ITS departing slots to default rows and builds
       a ``cap_frontier``-row outgoing slab from its local packs
       (arrival fixups — ``lelem = pending % part_L``, ``pending = -1``
       — applied to the packed int columns; rows it does not own get
       the drop sentinel ``cap``);
    4. the slab rides the ``ndev``-hop ppermute ring; every shard keeps
       the visiting rows whose destination slot it owns (destinations
       unique ⇒ arrival order cannot matter);
    5. overflow (an arrival rank reaching its part's free-slot count)
       latches with an int psum, committing the pre-migrate shards
       verbatim — the recovery ladder's contract; departure/arrival
       counts psum from per-shard partial bincounts over owned slab
       rows, feeding the incremental occupancy bookkeeping unchanged.

    The caller guarantees ``n_pending <= cap_frontier`` exactly as for
    the impl (``_inloop_migrate_step``'s slab-overflow cond falls back
    to the full-capacity collective).
    """
    from pumiumtally_tpu.parallel.partition import (
        _pack_state,
        _unpack_state,
    )
    from pumiumtally_tpu.ops.bucketize import (
        counting_ranks,
        partition_perm,
    )

    ax = axis_name(device_mesh)
    ndev = int(device_mesh.devices.size)
    cap = nparts * cap_per_block
    if cap % ndev:
        raise ValueError(
            f"capacity {cap} is not divisible by the {ndev}-device mesh"
        )
    n_loc = cap // ndev
    cf = int(cap_frontier)
    if not 0 < cf <= cap:
        raise ValueError(
            f"cap_frontier {cf} must be in 1..{cap} for the collective "
            "slab (0 dispatches to the full-capacity collective "
            "upstream)"
        )
    ring = [(i, (i + 1) % ndev) for i in range(ndev)]

    def shard_body(state):
        # -- steps 1+2: global bookkeeping lanes, replayed bit-
        # identically on every shard from the gathered inputs.
        pend_g = lax.all_gather(state["pending"], ax, tiled=True)
        alive_g = lax.all_gather(state["alive"], ax, tiled=True)
        moving = pend_g >= 0
        iota = jnp.cumsum(jnp.ones_like(pend_g)) - 1
        my_base = lax.axis_index(ax).astype(iota.dtype) * n_loc
        slot_part = iota // cap_per_block
        perm, counts, _ = partition_perm(
            (~moving).astype(jnp.int32), 2, method=partition_method
        )
        n_move = counts[0]
        src = perm[:cf]
        slab_iota = jnp.cumsum(jnp.ones_like(src)) - 1
        valid = slab_iota < n_move
        fint = ((~alive_g) | moving).astype(jnp.int32)
        excl = jnp.cumsum(fint) - fint
        part_base = excl.reshape(nparts, cap_per_block)[:, 0]
        free_rank = excl - part_base[slot_part]
        n_free = jnp.sum(fint.reshape(nparts, cap_per_block), axis=1)
        fdest = jnp.where(
            fint == 1, slot_part * cap_per_block + free_rank, cap
        )
        free_list = jnp.full((cap,), cap, iota.dtype).at[fdest].set(
            iota, mode="drop"
        )
        pend_slab = pend_g[src]
        tgt = jnp.clip(pend_slab // part_L, 0, nparts - 1)
        key = jnp.where(valid, tgt, nparts)
        rank = counting_ranks(key, nparts + 1, method=partition_method)
        ovf_any = jnp.any(valid & (rank >= n_free[tgt]))
        overflow = lax.psum(ovf_any.astype(jnp.int32), ax) > 0
        ridx = tgt * cap_per_block + jnp.minimum(rank, cap_per_block - 1)
        dest = jnp.where(valid, free_list[ridx], cap).astype(iota.dtype)

        # -- step 3: local clear + owned outgoing slab.
        fpack, ipack, fdef, idef, layout = _pack_state(
            state, _defaults_like(state)
        )
        lelem_off = pend_off = None
        for k, _kind, start, _ncols, _dtype, _tail in layout:
            if k == "lelem":
                lelem_off = start
            elif k == "pending":
                pend_off = start
        own = valid & (src >= my_base) & (src < my_base + n_loc)
        gidx = jnp.clip(src - my_base, 0, n_loc - 1)
        slab_f = fpack[gidx]
        slab_i = ipack[gidx]
        lelem_rows = jnp.where(
            valid, pend_slab % part_L, jnp.zeros_like(pend_slab)
        )
        slab_i = slab_i.at[:, lelem_off].set(
            lelem_rows.astype(slab_i.dtype)
        )
        slab_i = slab_i.at[:, pend_off].set(
            jnp.where(
                valid,
                jnp.asarray(-1, slab_i.dtype),
                slab_i[:, pend_off],
            )
        )
        slab_d = jnp.where(own, dest, cap).astype(iota.dtype)
        clear_idx = jnp.where(own, src - my_base, n_loc)
        def_f = jnp.broadcast_to(fdef[:1], (cf,) + fdef.shape[1:])
        def_i = jnp.broadcast_to(idef[:1], (cf,) + idef.shape[1:])
        acc_f = fpack.at[clear_idx].set(def_f, mode="drop")
        acc_i = ipack.at[clear_idx].set(def_i, mode="drop")

        # -- step 4: the slab-sized ring scatter (clear-before-place:
        # an arrival's destination may be a vacated slot).
        def hop(_s, carry):
            acc_f, acc_i, vis_f, vis_i, vis_d = carry
            mine = (vis_d >= my_base) & (vis_d < my_base + n_loc)
            idx = jnp.where(mine, vis_d - my_base, n_loc)
            acc_f = acc_f.at[idx].set(vis_f, mode="drop")
            acc_i = acc_i.at[idx].set(vis_i, mode="drop")
            return (
                acc_f,
                acc_i,
                lax.ppermute(vis_f, ax, ring),
                lax.ppermute(vis_i, ax, ring),
                lax.ppermute(vis_d, ax, ring),
            )

        acc_f, acc_i, _vf, _vi, _vd = lax.fori_loop(
            0, ndev, hop, (acc_f, acc_i, slab_f, slab_i, slab_d)
        )
        new_state = _unpack_state(acc_f, acc_i, layout)

        # -- step 5: occupancy deltas + the overflow-safe commit.
        dep = lax.psum(
            jnp.bincount(
                jnp.where(own, src // cap_per_block, nparts),
                length=nparts + 1,
            )[:nparts],
            ax,
        ).astype(jnp.int32)
        arr = lax.psum(
            jnp.bincount(
                jnp.where(own, key, nparts), length=nparts + 1
            )[:nparts],
            ax,
        ).astype(jnp.int32)
        new_state = {
            k: jnp.where(overflow, state[k], v)
            for k, v in new_state.items()
        }
        return new_state, overflow, dep, arr

    def collective_frontier_migrate(state):
        return shard_map(
            shard_body,
            mesh=device_mesh,
            in_specs=(P(ax),),
            out_specs=({k: P(ax) for k in state}, P(), P(), P()),
            **shard_map_check_kwargs(),
        )(state)

    return collective_frontier_migrate
