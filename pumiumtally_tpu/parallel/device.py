"""Device-mesh construction.

The reference's process topology is implicit in MPI_COMM_WORLD (owned by
``pumipic::Library``, reference PumiTallyImpl.cpp:238-241); here it is
an explicit 1-D ``jax.sharding.Mesh`` whose ``dp`` axis shards the
particle batch. Multi-host pods extend the same mesh over DCN via
``jax.distributed.initialize()`` — no code change in the kernels.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_device_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = "dp",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))
