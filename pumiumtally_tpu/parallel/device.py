"""Device-mesh construction.

The reference's process topology is implicit in MPI_COMM_WORLD (owned by
``pumipic::Library``, reference PumiTallyImpl.cpp:238-241); here it is
an explicit 1-D ``jax.sharding.Mesh`` whose ``dp`` axis shards the
particle batch. Multi-host pods extend the same mesh over DCN via
``jax.distributed.initialize()`` — no code change in the kernels.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_device_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = "dp",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    axis_name: str = "dp",
) -> Mesh:
    """Multi-host setup: join the jax.distributed job and return a 1-D
    mesh over EVERY chip in the pod (local + remote over DCN).

    The reference reaches multi-node through ``pumipic::Library``'s
    MPI_Init (reference PumiTallyImpl.cpp:238-241); the TPU-native
    equivalent is ``jax.distributed.initialize`` — afterwards
    ``jax.devices()`` spans all hosts, XLA routes the particle-migration
    collectives and flux psums over ICI within a slice and DCN across
    slices, and nothing in the engine changes. On Cloud TPU pods all
    three arguments are inferred from the environment.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return make_device_mesh(axis_name=axis_name)
