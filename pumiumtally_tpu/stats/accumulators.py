"""On-device (sum, sum-of-squares) batch accumulation.

One ``BatchAccumulator`` rides on each stats-enabled facade. It owns
two extra ``[E]`` device lanes in original element order — the
caller-visible layout every engine's ``flux`` property already
produces, so the partitioned engines' block-local flux reduces through
the exact scatter-order class already pinned for flux before it ever
reaches these lanes — plus the host-side batch counter.

A batch's contribution is the CHANGE in accumulated flux across the
batch: ``open`` snapshots the engine flux, ``close`` computes
``delta = flux_now - flux_open`` and folds ``(delta, delta^2)`` into
the lanes with one jitted elementwise update (entry point
``close_batch``: one compile per (E, dtype), retrace-budgeted like
every engine entry point). No device->host transfer happens here at
all — the only per-close D2H in the subsystem is the trigger
evaluation's single scalar (see ``triggers``).

An empty batch (zero moves since open) is NOT a sample: closing it
leaves the lanes and counter untouched. Counting it would fold a
structural zero into the variance and silently bias the relative
error low.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from pumiumtally_tpu.utils.profiling import register_entry_point


@jax.jit
def _close_batch_update(flux_sum, flux_sq_sum, flux_now, flux_open):
    delta = flux_now - flux_open
    return flux_sum + delta, flux_sq_sum + delta * delta


# Rebind, not a bare call: only calls through the counting wrapper are
# counted (utils/profiling.register_entry_point).
_close_batch_update = register_entry_point(
    "close_batch", _close_batch_update
)


class BatchAccumulator:
    """Streaming per-batch (sum, sum-of-squares) over the ``[E]`` flux.

    Lifecycle: ``close(flux, reopen=True)`` at every batch boundary —
    ``CopyInitialPosition`` and the facade's ``close_batch()`` both
    roll batches through it; ``finalize`` passes ``reopen=False``. The
    lanes live in the engine's working dtype (mixing dtypes would
    force a cast per close; an f32 engine accepts the f32 rounding in
    its statistics exactly as it does in its flux).
    """

    def __init__(self, nelems: int, dtype: Any):
        self.nelems = int(nelems)
        self.dtype = dtype
        self.flux_sum = jnp.zeros((self.nelems,), dtype)
        self.flux_sq_sum = jnp.zeros((self.nelems,), dtype)
        self.num_batches = 0
        self.moves_in_batch = 0
        # Engine flux at batch open; None = no batch open (fresh
        # accumulator, or after finalize).
        self.open_flux: Optional[jnp.ndarray] = None

    @property
    def batch_open(self) -> bool:
        return self.open_flux is not None

    def note_move(self) -> None:
        if self.open_flux is not None:
            self.moves_in_batch += 1

    def close(self, flux: jnp.ndarray, reopen: bool = True) -> None:
        """Fold the open batch's flux delta into the lanes (no-op when
        no batch is open or no move landed in it), then open the next
        batch at ``flux`` (``reopen=True``) or leave none open."""
        if self.open_flux is not None and self.moves_in_batch > 0:
            self.flux_sum, self.flux_sq_sum = _close_batch_update(
                self.flux_sum, self.flux_sq_sum, flux, self.open_flux
            )
            self.num_batches += 1
        self.open_flux = flux if reopen else None
        self.moves_in_batch = 0

    # -- checkpoint surface (utils/checkpoint.py) ------------------------
    def reset(self, open_flux: Optional[jnp.ndarray]) -> None:
        """Zero the lanes and counters; open a batch at ``open_flux``
        (the restored engine flux) so a resumed run's next close
        measures the right delta. The pre-stats-checkpoint restore
        path."""
        self.flux_sum = jnp.zeros((self.nelems,), self.dtype)
        self.flux_sq_sum = jnp.zeros((self.nelems,), self.dtype)
        self.num_batches = 0
        self.moves_in_batch = 0
        self.open_flux = open_flux

    def restore(
        self,
        flux_sum,
        flux_sq_sum,
        num_batches: int,
        moves_in_batch: int,
        open_flux,
    ) -> None:
        """Exact state restore (stats-carrying checkpoint)."""
        self.flux_sum = jnp.asarray(flux_sum, self.dtype)
        self.flux_sq_sum = jnp.asarray(flux_sq_sum, self.dtype)
        self.num_batches = int(num_batches)
        self.moves_in_batch = int(moves_in_batch)
        self.open_flux = (
            None if open_flux is None else jnp.asarray(open_flux, self.dtype)
        )
