"""Convergence triggers: threshold tests over the batch statistics.

A ``TriggerSpec`` names a per-element metric ("rel_err" or "std_err"
— both fall as 1/sqrt(N) for a healthy estimator, both measure the
mean's uncertainty; "std_err" is the standard error of the mean, NOT
``BatchStatistics.std_dev``, which is the sqrt(N)-larger sample std
dev of the batch values), a threshold, and a quantile over the SCORED
elements (mean != 0): ``quantile=1.0`` (the default) is the strictest
form — the worst scored element must converge — matching OpenMC's
default tally-trigger semantics; lower quantiles ignore the slowest
tail (e.g. 0.95 converges when 95% of scored elements are under the
threshold).

Evaluation cost contract (the reason this lives in its own jitted
reduction): one compile per (E, dtype, metric, quantile) — entry
point ``trigger_eval``, retrace-budgeted — and exactly ONE scalar
device->host transfer per evaluation. Everything else (threshold
compare, the 1/sqrt(N) batches-remaining projection) is host
arithmetic on that one scalar.

Batches-remaining estimate: with value v at N batches and v ~ c/sqrt(N),
reaching threshold T needs N* = N * (v/T)^2 total batches, i.e.
``ceil(N * ((v/T)^2 - 1))`` more. It is a projection, not a promise —
the facade re-evaluates at every close.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from pumiumtally_tpu.utils.profiling import register_entry_point

_METRICS = ("rel_err", "std_err")


@dataclass(frozen=True)
class TriggerSpec:
    """Convergence criterion evaluated at batch close.

    Attributes:
      threshold: converge when the metric's quantile is <= this.
      metric: "rel_err" (relative error of the mean — dimensionless)
        or "std_err" (standard error of the mean — absolute, in flux
        units; deliberately NOT named "std_dev", which is the
        estimator surface's sample standard deviation, sqrt(N)
        larger).
      quantile: which quantile of the per-element metric over SCORED
        elements must pass; 1.0 = the maximum (every scored element).
    """

    threshold: float
    metric: str = "rel_err"
    quantile: float = 1.0

    def __post_init__(self) -> None:
        if self.metric not in _METRICS:
            raise ValueError(
                f"metric must be one of {_METRICS}, got {self.metric!r}"
            )
        if not (float(self.threshold) > 0.0):
            raise ValueError(
                f"threshold must be > 0, got {self.threshold!r}"
            )
        if not (0.0 < float(self.quantile) <= 1.0):
            raise ValueError(
                f"quantile must be in (0, 1], got {self.quantile!r}"
            )


@dataclass(frozen=True)
class TriggerResult:
    """One trigger evaluation: the fetched metric value, the verdict,
    and the 1/sqrt(N) projection of additional batches needed
    (0 when converged; None when no projection exists yet — fewer
    than 2 closed batches, or a non-finite value)."""

    converged: bool
    value: float
    threshold: float
    metric: str
    quantile: float
    num_batches: int
    batches_remaining: Optional[int]


@partial(jax.jit, static_argnames=("metric", "quantile"))
def _trigger_reduction(flux_sum, flux_sq_sum, num_batches, *, metric,
                       quantile):
    """[E] lanes -> ONE scalar: the requested quantile of the
    per-element metric over scored elements (+inf when none are
    scored, so an all-unscored tally can never read as converged).

    ``num_batches`` is a TRACED scalar — it changes every close, and
    baking it static would recompile per batch (jaxlint JL004's
    runtime shadow, the exact failure the retrace tripwire exists
    for)."""
    n = jnp.asarray(num_batches, flux_sum.dtype)
    mean = flux_sum / n
    # Unbiased sample variance of the batch values, clamped (see
    # estimators.sample_variance — duplicated here so the reduction
    # stays one fused jit with no host-int N).
    var = jnp.maximum(flux_sq_sum / n - mean * mean, 0.0) * (
        n / jnp.maximum(n - 1.0, 1.0)
    )
    sem = jnp.sqrt(var / n)
    # mean != 0, not > 0: net-negative elements (negative-weight
    # workloads) are scored via |mean|, exactly like the estimator
    # surface — only an exactly-zero mean is "unscored".
    scored = flux_sum != 0
    if metric == "rel_err":
        vals = sem / jnp.where(scored, jnp.abs(mean), 1.0)
    else:  # "std_err" — validated by TriggerSpec
        vals = sem
    vals = jnp.where(scored, vals, jnp.inf)
    # Quantile over the scored subset with static shapes: unscored
    # elements sort to the top as +inf, so the k scored values occupy
    # the first k ascending slots and the q-quantile is rank
    # ceil(q*k)-1.
    k = jnp.sum(scored)
    svals = jnp.sort(vals)
    idx = jnp.clip(
        jnp.ceil(quantile * k).astype(jnp.int32) - 1, 0, vals.shape[0] - 1
    )
    return svals[idx]


_trigger_reduction = register_entry_point(
    "trigger_eval", _trigger_reduction
)


def evaluate_trigger(accumulator, spec: TriggerSpec) -> TriggerResult:
    """Evaluate ``spec`` against a ``BatchAccumulator``'s lanes.

    With fewer than 2 closed batches the variance is undefined: the
    result is unconverged with ``value=inf`` and no projection, and
    NO device work or transfer happens.
    """
    nb = accumulator.num_batches
    if nb < 2:
        return TriggerResult(
            converged=False, value=math.inf,
            threshold=float(spec.threshold), metric=spec.metric,
            quantile=float(spec.quantile), num_batches=nb,
            batches_remaining=None,
        )
    # THE one scalar D2H of a batch close.
    value = float(
        _trigger_reduction(
            accumulator.flux_sum, accumulator.flux_sq_sum, float(nb),
            metric=spec.metric, quantile=float(spec.quantile),
        )
    )
    threshold = float(spec.threshold)
    converged = value <= threshold
    if converged:
        remaining: Optional[int] = 0
    elif math.isfinite(value) and value > 0:
        remaining = max(
            1, math.ceil(nb * ((value / threshold) ** 2 - 1.0))
        )
    else:
        remaining = None
    return TriggerResult(
        converged=converged, value=value, threshold=threshold,
        metric=spec.metric, quantile=float(spec.quantile),
        num_batches=nb, batches_remaining=remaining,
    )
