"""Batch statistics: on-device accumulators, estimators, triggers.

The reference tallies a single mean-flux lane and stops there —
``WriteTallyResults`` normalizes by element volume and writes one
scalar field (reference PumiTallyImpl.cpp:411-416), so a user cannot
tell a converged tally from noise. Production MC codes (OpenMC, the
host app this library's protocol serves) treat per-batch sum /
sum-of-squares accumulation, relative error, and trigger-based
stopping as core tally capability. This package adds that layer ON TOP
of every engine facade, without touching the transport hot path:

- ``accumulators.BatchAccumulator`` — two extra ``[E]`` device lanes
  (``flux_sum``, ``flux_sq_sum``) updated at batch close from the
  engine's in-flight flux lane (one jitted elementwise update, entry
  point ``close_batch``);
- ``estimators`` — per-element mean, sample standard deviation,
  relative error of the mean, figure of merit;
- ``triggers`` — ``TriggerSpec`` evaluated at batch close as one
  jitted reduction (entry point ``trigger_eval``) + a single scalar
  D2H, returning converged/not plus a 1/sqrt(N)-law estimate of the
  batches remaining.

Batch boundaries: each ``CopyInitialPosition`` call opens a new source
batch (closing the previous one, if any moves landed in it); the
facade's ``close_batch()`` / ``finalize()`` close one explicitly.
With statistics disabled (the default) the facades never construct any
of this and every engine is bitwise identical to a stats-less build —
pinned by tests/test_stats.py.
"""

from pumiumtally_tpu.stats.accumulators import BatchAccumulator
from pumiumtally_tpu.stats.estimators import BatchStatistics
from pumiumtally_tpu.stats.triggers import (
    TriggerResult,
    TriggerSpec,
    evaluate_trigger,
)

__all__ = [
    "BatchAccumulator",
    "BatchStatistics",
    "TriggerResult",
    "TriggerSpec",
    "evaluate_trigger",
]
