"""Derived per-element statistics from the (sum, sum-of-squares) lanes.

The estimators follow the standard MC tally conventions (OpenMC's
tally arithmetic, which feeds this library its particles): with x_i the
per-element flux contribution of batch i and N closed batches,

  mean       = (1/N) sum x_i
  sample var = (sum x_i^2 / N - mean^2) * N / (N - 1)
  rel_err    = sqrt(var / N) / |mean|      (std error of the mean,
                                            relative)
  FOM        = 1 / (rel_err^2 * t)         (figure of merit; t =
                                            transport seconds)

Elements with exactly-zero mean ("unscored": no track ever crossed
them, or exact cancellation) have no defined relative error; these
report ``inf`` so a threshold comparison can never mistake them for
converged. Net-NEGATIVE elements (negative-weight workloads) are
scored normally via |mean|. The VTK output path maps the infs to 0.0
(a file full of infs renders as garbage).

These functions run on the OUTPUT path (reading statistics, writing
VTK), so they are plain eager jnp — no jit cache to manage. The hot
per-batch-close update and the trigger reduction live in
``accumulators`` / ``triggers`` as registered jit entry points.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp


def batch_mean(flux_sum: jnp.ndarray, num_batches: int) -> jnp.ndarray:
    """Per-element mean of the per-batch flux contributions."""
    if num_batches < 1:
        raise ValueError("mean needs at least 1 closed batch")
    return flux_sum / jnp.asarray(float(num_batches), flux_sum.dtype)


def sample_variance(
    flux_sum: jnp.ndarray, flux_sq_sum: jnp.ndarray, num_batches: int
) -> jnp.ndarray:
    """Unbiased per-element sample variance of the batch values.

    Clamped at zero: the textbook ``sq_sum/N - mean^2`` form can go
    epsilon-negative in floating point when the batch values are
    (near-)identical, and a negative variance would NaN every
    downstream sqrt.
    """
    if num_batches < 2:
        raise ValueError("sample variance needs at least 2 closed batches")
    n = jnp.asarray(float(num_batches), flux_sum.dtype)
    mean = flux_sum / n
    return jnp.maximum(flux_sq_sum / n - mean * mean, 0.0) * (n / (n - 1.0))


def std_dev(
    flux_sum: jnp.ndarray, flux_sq_sum: jnp.ndarray, num_batches: int
) -> jnp.ndarray:
    """Per-element sample standard deviation of the batch values."""
    return jnp.sqrt(sample_variance(flux_sum, flux_sq_sum, num_batches))


def rel_err(
    flux_sum: jnp.ndarray, flux_sq_sum: jnp.ndarray, num_batches: int
) -> jnp.ndarray:
    """Relative error of the mean, sem/|mean|; ``inf`` where the mean
    is exactly zero. |mean|, not mean: negative-weight (variance
    reduction) workloads can leave net-negative elements, which are
    still SCORED — only a zero mean has no defined relative error."""
    n = jnp.asarray(float(num_batches), flux_sum.dtype)
    sem = jnp.sqrt(
        sample_variance(flux_sum, flux_sq_sum, num_batches) / n
    )
    scored = flux_sum != 0
    return jnp.where(
        scored, sem / jnp.where(scored, jnp.abs(flux_sum) / n, 1.0),
        jnp.inf,
    )


def figure_of_merit(
    rel_err_arr: jnp.ndarray, elapsed_seconds: float
) -> jnp.ndarray:
    """FOM = 1/(RE^2 * t): constant over a run for a healthy estimator
    (RE^2 falls as 1/N while t grows as N), so a FALLING FOM flags an
    estimator or implementation problem. ``inf``-RE (unscored)
    elements report 0."""
    if elapsed_seconds <= 0.0:
        raise ValueError(
            f"figure of merit needs elapsed_seconds > 0, got "
            f"{elapsed_seconds!r}"
        )
    re2 = rel_err_arr * rel_err_arr
    return jnp.where(
        jnp.isfinite(re2) & (re2 > 0),
        1.0 / (re2 * elapsed_seconds),
        0.0,
    )


@dataclass(frozen=True)
class BatchStatistics:
    """Read-only view of one accumulator state (facade
    ``batch_statistics()``): the raw lanes plus lazily computed
    estimator fields. Device arrays — ``np.asarray`` them to fetch."""

    flux_sum: jnp.ndarray
    flux_sq_sum: jnp.ndarray
    num_batches: int
    elapsed_seconds: Optional[float] = None

    @property
    def mean(self) -> jnp.ndarray:
        return batch_mean(self.flux_sum, self.num_batches)

    @property
    def std_dev(self) -> jnp.ndarray:
        return std_dev(self.flux_sum, self.flux_sq_sum, self.num_batches)

    @property
    def rel_err(self) -> jnp.ndarray:
        return rel_err(self.flux_sum, self.flux_sq_sum, self.num_batches)

    @property
    def figure_of_merit(self) -> jnp.ndarray:
        if self.elapsed_seconds is None:
            raise ValueError(
                "figure of merit needs elapsed_seconds (the facade "
                "passes its TallyTimes transport total)"
            )
        return figure_of_merit(self.rel_err, self.elapsed_seconds)
