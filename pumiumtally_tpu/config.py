"""Runtime configuration.

The reference has no runtime config system — everything is hard-coded:
tolerance 1e-8 (reference PumiTallyImpl.cpp:51), migration period 100
(PumiTallyImpl.cpp:111), output name "fluxresult.vtk"
(PumiTallyImpl.cpp:153), default num_particles 1e5 (PumiTallyImpl.h:155).
Here those become fields of a small dataclass, per SURVEY.md §5.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


def default_float_dtype() -> Any:
    """f64 when x64 mode is on (parity suites), else f32 (TPU fast path)."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# Per-entry-point retrace budgets: the maximum NEW jit-cache entries
# (== compiles, one per distinct (shape, static-args) key) any single
# tier-1 test may create in each engine entry point registered via
# utils.profiling.register_entry_point. tests/conftest.py wraps every
# test in a retrace_guard over this table and fails the test on a
# breach; bench.py records the same counters per measured workload.
#
# A budget of B does NOT mean "B compiles per shape" — cache-size
# counting gives exactly one entry per distinct key, so B bounds how
# many distinct keys one test may touch. Every entry point below keeps
# the one-compile-per-(shape, static-args) contract; budgets above 1
# exist only because single tests legitimately drive several distinct
# keys:
#
# Budgets = the measured tier-1 per-test maximum + 1 headroom
# (calibrated with PUMIUMTALLY_RETRACE_RECORD over the full suite):
#
# - "walk"/"walk_continue" (api/tally.py): measured max 2 — the
#   equivalence suites legitimately drive two particle/mesh shapes in
#   one test (e.g. streaming-vs-monolithic compiles the chunk shape
#   AND the monolithic shape; packed-vs-unpacked walk tables are two
#   static keys).
# - "localize" (api/tally.py): measured max 3 — the robustness suite
#   localizes three distinct batch shapes in one test.
# - "cascade_phase"/"partition_locate" (parallel/partition.py): one
#   jitted phase per (engine, config-key), summed under one name;
#   measured max 4 — blocked-vs-unblocked multichip comparisons build
#   four engine configs back to back — raised to a measured max 6 in
#   r7: the batch-statistics parity tests (tests/test_stats.py) drive
#   a stats-on and a stats-off partitioned engine back to back (no
#   shared jit cache across facades; 2 engines x 3 phase programs).
# - "sharded_*" (parallel/sharded.py): measured max 2 (device-count +
#   chunk-shape sweeps).
#
# This table is machine-audited (round 20): `python -m
# pumiumtally_tpu.analysis --trace-keys` cross-checks it against every
# register_entry_point site and fails CI on a dead budget (JL402) or
# an unbudgeted entry point (JL403). The round-20 audit found the
# table exactly bijective — 19 budgets, 19 registered entry points,
# nothing pruned, nothing added — so every key below is live.
# Recalibrate with tools/retrace_calibrate.py over a
# PUMIUMTALLY_RETRACE_RECORD run instead of hand-editing. Keep the
# dict a LITERAL: the auditor reads it with ast.literal_eval (no jax
# import), so computed values would blind it.
RETRACE_BUDGETS: dict = {
    "walk": 3,
    "walk_continue": 3,
    "locate": 2,
    "localize": 4,
    # partition_locate raised 3→5 in r9: the sentinel recovery suites
    # build reference + sentinel streaming-partitioned facades back to
    # back (two chunk engines each, no shared cache across facades).
    "partition_locate": 5,
    # cascade_phase raised 7→12 in r9: the straggler-retry and
    # overflow-recovery RESUME phases are separate static keys
    # (resume flag + budget multipliers + forced-full-migrate ride the
    # phase cache key), and the recovery tests drive reference +
    # sentinel engines back to back — measured max 11 + 1 headroom
    # (PUMIUMTALLY_RETRACE_RECORD over the full r9 tier-1).
    # Re-measured over the full r13 tier-1 after migrate_collective
    # joined the phase cache key: still max 11 (the collective-vs-
    # scatter parity tests peak at 6 — one extra phase variant per
    # engine pair, compiled once), so the budget holds unchanged.
    # Re-measured in r17 after use_pallas_walk joined the phase key
    # (PUMIUMTALLY_RETRACE_RECORD over tests/test_pallas_walk.py +
    # the bench pallas_walk row): max 6 — the pallas-vs-gather parity
    # tests drive three engines back to back but the pallas round
    # program is one phase variant per engine, compiled once — so the
    # budget holds unchanged again.
    # Re-measured in r19 after placement and the composed
    # cap_frontier x migrate_collective pair joined the phase key
    # (PUMIUMTALLY_RETRACE_RECORD over tests/test_placement.py +
    # tests/test_distributed.py): max 6 — the frontier-collective
    # parity tests drive on/off engine pairs across four perm modes
    # and the placement tests linear/pod_rcb pairs, but every
    # composed program is one phase variant per engine, compiled
    # once — so the budget holds unchanged again.
    "cascade_phase": 12,
    # Profiled-phase programs (parallel/partition.py component-budget
    # instrumentation): one jitted single-round program per
    # (engine, tally) — a profiled two-phase move drives both tally
    # keys — plus one migrate and one occupancy program per engine;
    # measured max 2 each (profiled-vs-fused parity tests run one
    # profiled engine, the A/B tool two).
    "partition_round": 3,
    "partition_migrate": 3,
    "partition_occupancy": 3,
    "sharded_walk": 2,
    # Measured max 3 in r8: the mid-batch-restore bitwise test drives
    # an uninterrupted engine (1 key) plus a restored engine whose
    # FIRST move consumes replicated state arrays (checkpoint restore
    # materializes on one device; jit keys on input shardings) before
    # the steady sharded-layout key — a one-off per resume, not a
    # per-call leak. Measured max 4 in r9 (the sharded straggler
    # recovery test adds a shape) + 1 headroom.
    "sharded_walk_continue": 5,
    "sharded_locate": 2,
    "sharded_localize": 3,
    # Batch-statistics entry points (pumiumtally_tpu/stats): one
    # compile per (E, dtype) for the close-batch lane update and one
    # per (E, dtype, metric, quantile) for the trigger reduction —
    # num_batches is a TRACED scalar precisely so the per-batch count
    # never enters the cache key. Measured tier-1 max 2 each (the
    # cross-engine equivalence tests drive two mesh shapes; the
    # trigger tests sweep two metric/quantile keys) + 1 headroom.
    "close_batch": 3,
    "trigger_eval": 3,
    # Sentinel entry points (r9, pumiumtally_tpu/sentinel):
    # - "audit_pack": ONE cache key per audited particle shape (the
    #   threshold and every carried scalar are traced). Measured
    #   tier-1 max 2 (cross-facade audit tests drive two particle
    #   shapes in one test) + 1 headroom.
    # - "straggler_retry": one key per (padded straggler shape,
    #   iteration budget, walk_kw, s_init-or-not) — shapes quantize
    #   to powers of two (sentinel/straggler.py padded_size) precisely
    #   so this stays bounded; the bf16 rung adds the forced-f32
    #   walk_kw key and the localization ladder the s-less variant.
    #   Measured tier-1 max 3 + 1 headroom.
    "audit_pack": 3,
    "straggler_retry": 4,
    # Filtered-scoring bin resolution (r10, pumiumtally_tpu/scoring
    # "score_bins"): ONE cache key per (n, dtype, spec static key) —
    # filter-edge VALUES are operands, never keys. Measured tier-1 max
    # 2 (the overflow-policy tests drive a drop and a clamp spec in
    # one test; facade suites add chunk-shape keys) + 1 headroom
    # (PUMIUMTALLY_RETRACE_RECORD over tests/test_scoring.py). The
    # scoring-ARMED walk/phase variants ride the existing walk_*/
    # cascade_phase budgets: re-measured maxima (cascade_phase 6,
    # sharded_walk_continue 4, walk_continue 2) all stay inside the
    # r9 budgets, so none were raised.
    "score_bins": 3,
    # The resilience subsystem (r8, pumiumtally_tpu/resilience) is
    # deliberately host-side only — checkpoint serialization, autosave
    # cadence, signal handling, and fault injection never touch the
    # jit cache, so it registers NO entry points here; the bench row's
    # compiles.timed == 0 contract (tools/exp_resilience_ab.py) pins
    # that an autosave-armed engine compiles exactly what a bare one
    # does.
    #
    # The multi-session service (r11, pumiumtally_tpu/service) holds
    # the same contract: threads, queues, and prepacked numpy buffers
    # only — every device program a served session runs is its
    # facade's own entry point, keyed exactly as a direct call would
    # key it (sessions share the process jit cache, so N same-shaped
    # sessions compile ONCE, not N times). No new entry points, no
    # budget changes; re-measured over the r11 tier-1 with
    # PUMIUMTALLY_RETRACE_RECORD — every per-test maximum stayed
    # inside the r10 budgets — and pinned by the service bench row's
    # compiles.timed == 0 (tools/exp_service_ab.py).
    #
    # Cross-session fusion (r12, service/fusion.py): the service's ONE
    # jitted program — K compatible sessions' head moves in one padded
    # slab launch. One cache key per group COMPOSITION (the spans
    # tuple, padding, continue-vs-origins pattern, and the walk/
    # scoring statics the fusion key already pinned equal), so a
    # steady serving mix compiles once and then every fused dispatch
    # hits the cache (the fusion A/B's timed window pins
    # compiles.timed == 0). Measured tier-1 max 2
    # (PUMIUMTALLY_RETRACE_RECORD over the full r12 tier-1: the
    # fusion A/B schema row and the bitwise suites drive two group
    # compositions in one test — e.g. continue-mode AND
    # origin-passing 3-session slabs) + 1 headroom.
    # Re-measured in r20 after streaming chunk-wise fusion joined the
    # entry point (one spans=(chunk,)*K key per group size K): the
    # service_load bench row's warmup ladder deliberately compiles
    # every composition K=2..max_fuse=8 in one test (7 keys,
    # PUMIUMTALLY_RETRACE_RECORD over tests/test_bench.py +
    # tests/test_traffic.py + tests/test_fusion.py), and the
    # service_fusion row's 32-session point adds a DRR-desync
    # straggler composition on top of its 4/8-way mono + stream keys
    # (measured 5). Max 7 + 1 headroom.
    "walk_fused": 8,
}


@dataclasses.dataclass
class TallyConfig:
    """Knobs for the tally engine.

    Attributes:
      tolerance: geometric comparison tolerance for the face-exit test in
        the walk kernel. ``None`` → 1e-8 in f64 (reference
        PumiTallyImpl.cpp:51) or 1e-6 in f32.
      max_iters: hard bound on walk iterations (the reference's search
        loop bound, whose exhaustion prints "Not all particles are
        found", PumiTallyImpl.cpp:455-458). ``None`` → heuristic from
        mesh size at first use.
      dtype: float dtype for coordinates/flux. ``None`` → f64 if JAX x64
        is enabled, else f32.
      check_found_all: if True, device→host sync after each search to
        warn when particles did not converge (costs a sync; disable for
        max throughput).
      device_mesh: optional ``jax.sharding.Mesh`` with a ``dp`` axis.
        When set, particle batches are sharded over it and per-element
        flux is psum-reduced across it (the TPU-native replacement for
        the reference's MPI rank parallelism, SURVEY.md §2.3).
      capacity_factor: partitioned mode only — per-chip particle-slot
        over-provisioning relative to a perfectly balanced load, so
        migration bursts do not overflow a chip (the analogue of
        PUMIPic's capacity() ≥ nPtcls() slack).
      max_migration_rounds: partitioned mode only — bound on
        walk/migrate rounds per phase (reference bounds its search loop
        the same way and prints "Not all particles are found",
        PumiTallyImpl.cpp:455-458).
      output_filename: default VTK output path (reference hard-codes
        "fluxresult.vtk", PumiTallyImpl.cpp:153).
      auto_continue: if True (default), ``MoveToNextLocation`` detects
        on the host when the staged origins echo the previous move's
        destinations bit-for-bit in the working dtype — the physics
        host's common case (no resampling since the last move; the
        reference's protocol echoes committed positions back as
        origins, PumiTallyImpl.cpp:66-149) — and substitutes the
        device array that staged those destinations instead of
        uploading the identical bytes again. Bit-exact: phase A still
        executes on device against values equal to the caller's
        origins (and its walk is skipped by the device-side trivial
        check when every particle committed its destination). Saves
        one [N,3] host→device transfer per echoing move, with no added
        synchronization. Applies to every facade: the streaming ones
        detect the echo on the flat caller buffer and reuse their
        per-chunk device arrays (the weights/flying caches below them
        are monolithic/sharded/partitioned only). Detector lifecycle:
        after 8 consecutive misses the facade stops snapshotting
        destinations (a never-echoing driver then pays ~nothing for
        the feature); while disarmed one snapshot is retried every 64
        moves, so a driver that echoes intermittently (e.g. periodic
        resampling phases) regains the upload skip within a period —
        and ``CopyInitialPosition`` always re-arms fully.
      fenced_timing: if True (default), each API call blocks until its
        device work finishes so ``TallyTimes`` measures real per-phase
        wall time (the fence the reference intended via
        ``Kokkos::fence``, SURVEY.md §5). Set False to let moves
        PIPELINE: calls return after dispatch, the next move's host
        staging overlaps the previous move's device compute, and
        ``TallyTimes`` attributes only dispatch time (a final
        result/flux read still synchronizes everything). Pipelining
        additionally needs ``check_found_all=False`` — the convergence
        warning reads a device scalar back every call, which is itself
        a sync. For plain ``StreamingTally`` (whose within-move overlap
        is chunk-wise double buffering) unfencing additionally lets
        move m+1's first chunks stage while move m's last chunks
        compute; ``StreamingPartitionedTally`` still synchronizes its
        deferred overflow safety check once per call, so this knob
        does not buy cross-move pipelining there.
    """

    tolerance: Optional[float] = None
    max_iters: Optional[int] = None
    dtype: Any = None
    check_found_all: bool = True
    auto_continue: bool = True
    fenced_timing: bool = True
    # Host-side np.isfinite check on staged positions and weights: a
    # single NaN/Inf destination otherwise poisons the ENTIRE
    # accumulated flux silently (scatter-add of nan — the reference's
    # atomic_add has the same hole). ~1-2 ms per 500k-particle move on
    # the host path; turn off only for maximum-rate trusted drivers.
    validate_inputs: bool = True
    # "walk" reproduces the reference's localization exactly (walk from
    # the committed state — initially element 0's centroid,
    # PumiTallyImpl.cpp:195-221 — including the clamp-to-hull for
    # out-of-domain sources). "locate" runs the MXU-shaped half-space
    # point-location first (one [C,3]x[3,4E] matmul per chunk — the
    # same kernel the partitioned engine always uses); located points
    # enter the follow-up masked walk already at their destination (it
    # retires them immediately), while unlocated points walk from the
    # committed state and clamp exactly as "walk" mode would. Net:
    # O(mesh diameter) walk iterations become one matmul pass. Applies
    # to the monolithic and sharded engines and (chunk-wise) the plain
    # streaming facade; the partitioned facades already locate.
    # NOTE: the N·4E half-space test is MXU-shaped — on an accelerator
    # it is a few ms; on the CPU backend it is orders of magnitude
    # slower than the walk (use "walk" for CPU runs at scale).
    localization: str = "walk"
    # NOTE: the reference's migration cadence (``iter_count % 100``,
    # PumiTallyImpl.cpp:111) has no equivalent knob here: the TPU
    # partitioned engine migrates a particle exactly when it pauses at a
    # partition face, because an un-migrated paused particle would idle
    # its slot for the rest of the round anyway (MPI ranks can keep
    # walking other particles; lock-step SPMD chips cannot).
    device_mesh: Optional[jax.sharding.Mesh] = None
    capacity_factor: float = 1.5
    max_migration_rounds: int = 64
    # Partitioned engines only: per-round migration frontier slab.
    # When set, each in-loop walk/migrate round moves ONLY the
    # particles that actually paused at a partition/block face —
    # compacted into a static slab of this many slots — instead of
    # re-bucketing every one of the nparts × cap_per_chip slots
    # (parallel/partition.py _frontier_migrate_impl): per-round
    # migrate cost then scales with the crossing front, not the
    # capacity. A round whose front exceeds the slab falls back to the
    # full-capacity migrate (today's semantics, bitwise — shapes stay
    # static either way), so the knob is a pure performance lever:
    # conservation and per-particle observables are unchanged; only
    # the slot layout (hence flux scatter-add rounding order, the same
    # documented class as walk_perm_mode="sorted") differs from the
    # unset default. None (default) keeps the historical full-capacity
    # migrate every round; 0 forces the fallback every round (testing
    # hook). Size it from PartitionedEngine.last_frontier_max — a slab
    # at or above the workload's largest front never falls back.
    cap_frontier: Optional[int] = None
    # Partitioned engines only (round 13): lower in-loop particle
    # migration to explicit named collectives — an all_gather of the
    # counting-rank keys plus a ppermute ring of the packed state
    # slabs inside a shard_map over the engine mesh
    # (parallel/distributed.py make_collective_migrate) — instead of
    # the GSPMD-partitioned full-capacity global scatter. Same
    # redistribution, BITWISE-equal result (destinations are globally
    # unique stable ranks, so arrival order cannot matter; pinned by
    # tests/test_distributed.py): on a multi-process global mesh a
    # particle leaving a host-owned block lands on the owning host in
    # one launch with the traffic explicit per hop, where the GSPMD
    # scatter lowering is whatever this jaxlib chose. Composes with
    # cap_frontier since round 19: frontier rounds ride the ring at
    # cap_frontier rows (make_collective_frontier_migrate), slab
    # overflows fall back to the full-capacity collective, and
    # cap_frontier=0 forces the full-capacity collective every round
    # bit-for-bit. False (default) keeps the historical scatter —
    # bitwise and allocation-identical to pre-round-13 builds.
    migrate_collective: bool = False
    # Partitioned engines only (round 19): element-block placement
    # strategy. "linear" (default) is the flat coordinate-RCB in block
    # order — byte-identical to pre-round-19 builds. "pod_rcb" builds
    # ownership by HOST-hierarchical RCB (parallel/partition.py
    # pod_rcb_partition): the domain splits across hosts first
    # (process boundaries on the global mesh, or placement_hosts for
    # virtual layouts), then across each host's chips — so migration
    # traffic crosses hosts only where the mesh geometry does.
    # placement_hosts: per-HOST chip counts in mesh device order
    # (e.g. (3, 5) carves an 8-device mesh into two virtual hosts);
    # None derives them from the mesh's process boundaries
    # (distributed.derive_host_counts). The layout describes the
    # MACHINE, not the strategy: "linear" ignores it for ownership but
    # the cross-host diagnostic still evaluates under it (the A/B's
    # baseline arm). Same scatter-order equivalence
    # class as cap_frontier: conservation and per-particle observables
    # unchanged, slot layout differs. The modeled cross-host bytes of
    # a placement are deterministic diagnostics
    # (PartitionedEngine.modeled_cross_host_bytes,
    # tools/exp_placement_ab.py).
    placement: str = "linear"
    placement_hosts: Optional[tuple] = None
    # Walk-kernel tuning knobs (ops/walk.py) — exposed so a deployment
    # can adopt the best measured configuration for its chip without
    # code changes. Defaults = the kernel's own defaults (None = leave
    # the kernel default in place, keeping jit cache keys identical to
    # an untuned config). cond_every: unrolled iterations per while
    # step; perm_mode: cascade stage-boundary permutation strategy
    # ("arrays"/"packed"/"indirect" are the sort-free binary-partition
    # forms; "sorted" restores the element-locality argsort; "auto"
    # resolves via PUMIUMTALLY_WALK_PERM); window_factor: cascade
    # shrink ratio; min_window: smallest compaction window. The
    # partitioned engines' ownership-restricted walk runs its own
    # in-round cascade (indirect form, parallel/partition.py
    # walk_local) and consumes cond_every and min_window;
    # perm_mode/window_factor apply to the monolithic/sharded/streaming
    # walks only.
    walk_cond_every: Optional[int] = None
    walk_perm_mode: Optional[str] = None
    walk_window_factor: Optional[int] = None
    walk_min_window: Optional[int] = None
    # Walk-table precision tier (ops/walk.py TABLE_DTYPES,
    # docs/PERF_NOTES.md "Table precision tiers"):
    #   None / "auto" — resolve via PUMIUMTALLY_WALK_TABLE_DTYPE
    #                   (default "float32").
    #   "float32"     — the packed single-tier row table (historical
    #                   layout; bitwise-identical to pre-knob builds).
    #   "bfloat16"    — two-tier: a half-width bf16 SELECT row picks
    #                   the exit face (32 B gathered vs 80 B), then ONE
    #                   full-precision refinement gather of the winning
    #                   face's (plane, neighbor) row (20 B) recomputes
    #                   the crossing exactly before committing — 52 B
    #                   per crossing vs 80. Track lengths and committed
    #                   positions carry full working-dtype accuracy;
    #                   wrong-face selection needs two crossings tying
    #                   within ~bf16 epsilon and lands in the
    #                   documented benign divergence class
    #                   (docs/DESIGN.md select-in-bf16/commit-in-f32
    #                   invariant). NOT bitwise vs "float32"; the
    #                   engines' conservation gates apply unchanged.
    #                   Neighbor ids live in the refinement rows'
    #                   float lane — exact below 2^24 elements (f32),
    #                   the same ceiling as the packed layout; builds
    #                   past it refuse.
    # Resolved at config time (like walk_perm_mode) so the tier lands
    # in the engines' static jit keys; facades convert their mesh /
    # partition tables accordingly. Partitioned engines with the bf16
    # tier route blocked walks through the GATHER block kernel (the
    # vmem one-hot kernel has no two-tier lowering yet — ops/vmem_walk
    # ceiling notes) with block tables at 2x the f32 element bound
    # (same resident bytes).
    walk_table_dtype: Optional[str] = None
    # How every redistribution site (cascade stage boundaries, the
    # partitioned walk's in-round compaction, particle migration)
    # computes its stable partition permutation: "rank" (counting ranks
    # over the small key alphabet — sort-free, the default) or
    # "argsort" (the seed's full stable sort). Both produce the
    # IDENTICAL permutation, hence bitwise-identical physics
    # (ops/bucketize.py, pinned by tests/test_partition_rank.py); the
    # knob exists for measurement — tools/exp_partition_ab.py A/Bs the
    # two on any backend. Applies to every engine.
    walk_partition_method: Optional[str] = None
    # Partitioned engines only: when set and a chip's owned element
    # count L is <= this bound (and local adjacency fits the float
    # table), the per-chip local walk runs as the VMEM-resident one-hot
    # MXU Pallas kernel (ops/vmem_walk.py) instead of the HBM row
    # gather. Wins when partitions are small enough that the [L,32]
    # table lives in VMEM (~<= a few thousand tets — see the module's
    # cost model); larger partitions silently keep the gather walk.
    # Not bitwise vs the gather walk (documented rounding-level
    # divergence); conservation gates apply unchanged.
    # Compile feasibility (measured via chipless AOT,
    # tools/aot_vmem_compile.py, corrected in r5): at the production
    # 1024-lane particle tile, block lengths through ~8192 compile —
    # the binding constraint is Mosaic's scoped-VMEM STACK limit, a
    # compiler constant driven by the particle tile (w_tile=2048 is
    # rejected at ~20.8 MB vs the 16.00M limit on v5e AND v5p alike),
    # not the block length or physical VMEM. Engines clamp bounds
    # above the measured ceiling (ops/vmem_walk.py
    # effective_vmem_bound); the PERF sweet spot is still small blocks
    # per the module's cost model.
    walk_vmem_max_elems: Optional[int] = None
    # Which kernel runs the per-block local walk when
    # walk_vmem_max_elems sub-splits a chip's partition into
    # blocks_per_chip > 1 blocks:
    #   "vmem"   — the one-hot MXU Pallas kernel (above); requires the
    #              float-table adjacency encoding and the Mosaic
    #              scoped-VMEM ceiling (the bound clamps to <= 2048).
    #   "gather" — the ownership-restricted HBM gather walk
    #              (parallel/partition.py walk_local) run block-by-block
    #              with lax.map: each step's [L,20] block table is small
    #              enough to stay resident on-chip, capturing the
    #              measured small-table gather speedup
    #              (docs/PERF_NOTES.md round-4: 2.2-2.4M moves/s at
    #              L<=3k vs ~1.1M on the monolithic 48k table) without
    #              Pallas. No Mosaic ceiling, adjacency-sidecar meshes
    #              supported, bitwise-comparable semantics to the
    #              unblocked partitioned walk.
    walk_block_kernel: str = "vmem"
    # Which kernel family runs the partitioned local walk (round 17,
    # ops/pallas_walk.py; supersedes walk_block_kernel as the primary
    # selector while keeping it as the legacy escape hatch):
    #   "gather" — the status-quo resolution (default): defer to
    #              walk_block_kernel exactly as before this knob
    #              existed, so an untuned config's traces stay
    #              byte-identical (walk_block_kernel="vmem" is inert
    #              without walk_vmem_max_elems).
    #   "vmem"   — force the f32 one-hot VMEM kernel family
    #              (equivalent to walk_block_kernel="vmem").
    #   "pallas" — the one-kernel two-tier Pallas walk: bf16 select +
    #              f32 single-face refine + deterministic flux (and
    #              scoring-lane) scatter fused into ONE kernel per
    #              particle tile, with the block tables double-buffered
    #              by the grid pipeline past the fits-in-VMEM case
    #              (52 B/crossing streamed vs the 80 B f32 gather —
    #              ops/pallas_walk.py modeled_walk_bytes). Requires
    #              walk_table_dtype="bfloat16" (validated below);
    #              walk_vmem_max_elems sizes the streamed blocks
    #              (unset = one resident block per chip).
    walk_kernel: str = "gather"
    # Batch statistics (pumiumtally_tpu/stats, docs/DESIGN.md "Batch
    # statistics"): when True, every facade keeps two extra [E] device
    # lanes (per-batch flux sum and sum of squares, original element
    # order) updated at batch close, exposes per-element mean / sample
    # std dev / relative error / figure of merit via
    # ``batch_statistics()``, and evaluates ``batch_stats_trigger``
    # (or a spec passed to ``close_batch``) at each batch close as one
    # jitted reduction + a single scalar D2H. Batch boundaries: each
    # ``CopyInitialPosition`` opens a new source batch (closing the
    # previous one), and ``close_batch()`` / ``finalize()`` close one
    # explicitly. Off (default): no lanes are allocated and every
    # engine is bitwise identical to a stats-less build (pinned by
    # tests/test_stats.py). Statistics lanes ride checkpoints
    # (utils/checkpoint.py format v3), and ``WriteTallyResults`` adds
    # cell arrays beside the flux+volume payload: ``flux_mean`` from
    # 1 closed batch, ``rel_err`` from 2 (the sample variance needs
    # them).
    batch_stats: bool = False
    # Default TriggerSpec (stats.triggers) that ``close_batch()``
    # evaluates when the caller passes none; None = close_batch
    # returns no verdict unless handed a spec.
    batch_stats_trigger: Optional[Any] = None
    # Filtered multi-score tallies (pumiumtally_tpu/scoring,
    # docs/DESIGN.md "Filtered scoring"): a scoring.ScoringSpec arms
    # energy/time-binned scoring lanes on this tally — every facade
    # then allocates a flattened [E·B·S] on-device lane bank, accepts
    # per-particle ``energy=``/``time=`` arrays on MoveToNextLocation
    # (validated with argument-naming errors), resolves each
    # particle's bin ONCE per move (branchless searchsorted over edge
    # arrays passed as device operands — edge VALUES never enter any
    # jit cache key), and scatters every score's segment contribution
    # at the same commit point as the flux lane with ONE fused
    # deterministic scatter-add. ``score_bank`` / ``score_array()``
    # read the lanes; WriteTallyResults adds ``<score>_bin<k>`` cell
    # arrays; checkpoints round-trip the bank; with batch_stats=True
    # the bank gets its own per-batch statistics lanes. None
    # (default): no scoring code runs anywhere and every engine is
    # bitwise- and allocation-identical to a scoring-less build;
    # scoring-ON leaves flux/positions/elements bitwise too (the flux
    # scatter is untouched) — both pinned in tests/test_scoring.py.
    scoring: Optional[Any] = None
    # Fault tolerance (pumiumtally_tpu/resilience, docs/DESIGN.md
    # "Fault tolerance"): a resilience.CheckpointPolicy arms autosave +
    # graceful drain on this tally. Every facade then writes atomic,
    # digest-sealed checkpoint GENERATIONS into policy.dir at the
    # policy's cadence (every N closed source batches and/or every S
    # wall seconds, checked at batch close and move end — off the
    # critical path), keeps the last `keep` generations, and installs a
    # SIGTERM/SIGINT handler that finishes the in-flight particle
    # batch, saves, and exits cleanly (preemption safety). A restarted
    # process calls resilience.resume_latest(tally) to restore the
    # newest intact generation — falling back past corrupt files with a
    # warning — and continue exactly where the dead run stopped
    # (bit-for-bit into a same-configured engine; the checkpoint
    # carries the engine's exact slot/chunk layout). None (default):
    # no autosave code runs anywhere, no handlers are installed.
    checkpoint: Optional[Any] = None
    # Runtime sentinels (pumiumtally_tpu/sentinel, docs/DESIGN.md
    # "Failure taxonomy"): a sentinel.SentinelPolicy arms in-flight
    # health monitoring and graceful degradation on this tally. Every
    # audited move then runs ONE extra jitted reduction — unfinished
    # count, tallied-vs-straight-line conservation residual, and a
    # non-finite-flux probe, packed into one scalar fetch — and
    # particles that exhaust the walk iteration budget go through the
    # straggler-escalation ladder (2x-budget retry on the compacted
    # residue -> exact-f32 retry for bf16 tiers -> quarantine +
    # lost_particles) instead of being silently truncated mid-flight.
    # Partitioned engines additionally recover capacity overflows
    # (full-migrate retry -> one host-side capacity escalation ->
    # safety save + poisoned refusal) instead of raising with a
    # half-migrated round. None (default): no sentinel code runs
    # anywhere, every engine is bitwise-identical and allocation-free
    # vs a sentinel-less build (same contract as stats-off).
    sentinel: Optional[Any] = None
    # Debug surface (reference getIntersectionPoints(),
    # PumiTallyImpl.h:177-178): when True the monolithic facade keeps
    # the staged inputs of the last move so
    # ``PumiTally.intersection_points()`` can replay the transport and
    # return each particle's last face-intersection point. Off by
    # default: the stash pins ~4 extra [n]-shaped device arrays and the
    # accessor's replay walk is an uncompacted inspection pass.
    record_xpoints: bool = False
    # StreamingPartitionedTally only: split the device mesh into this
    # many disjoint groups — chunks round-robin across them, so G
    # chunks transport concurrently (particle data parallelism across
    # groups) while each group shards the mesh over its ndev/G chips
    # (mesh partitioning within a group). The dp × part hybrid; each
    # chip then holds tables for E/(ndev/G) owned elements.
    device_groups: int = 1
    output_filename: str = "fluxresult.vtk"

    def __post_init__(self) -> None:
        if self.localization not in ("walk", "locate"):
            raise ValueError(
                "localization must be 'walk' or 'locate', "
                f"got {self.localization!r}"
            )
        if int(self.device_groups) < 1:
            raise ValueError(
                f"device_groups must be >= 1, got {self.device_groups!r}"
            )
        if self.walk_perm_mode is not None and self.walk_perm_mode not in (
            "auto", "arrays", "packed", "indirect", "sorted"
        ):
            raise ValueError(
                "walk_perm_mode must be auto/arrays/packed/indirect/"
                f"sorted, got {self.walk_perm_mode!r}"
            )
        if self.walk_table_dtype is not None and self.walk_table_dtype not in (
            "auto", "float32", "bfloat16"
        ):
            raise ValueError(
                "walk_table_dtype must be auto/float32/bfloat16, "
                f"got {self.walk_table_dtype!r}"
            )
        if self.walk_partition_method is not None and (
            self.walk_partition_method not in ("rank", "argsort")
        ):
            raise ValueError(
                "walk_partition_method must be 'rank' or 'argsort', "
                f"got {self.walk_partition_method!r}"
            )
        if self.walk_window_factor is not None and int(
            self.walk_window_factor
        ) < 2:
            raise ValueError(
                f"walk_window_factor must be >= 2, "
                f"got {self.walk_window_factor!r}"
            )
        if self.walk_cond_every is not None and int(self.walk_cond_every) < 1:
            raise ValueError(
                f"walk_cond_every must be >= 1, got {self.walk_cond_every!r}"
            )
        if self.walk_min_window is not None and int(self.walk_min_window) < 1:
            raise ValueError(
                f"walk_min_window must be >= 1, got {self.walk_min_window!r}"
            )
        if self.walk_vmem_max_elems is not None and int(
            self.walk_vmem_max_elems
        ) < 1:
            raise ValueError(
                f"walk_vmem_max_elems must be >= 1, "
                f"got {self.walk_vmem_max_elems!r}"
            )
        if self.walk_block_kernel not in ("vmem", "gather"):
            raise ValueError(
                "walk_block_kernel must be 'vmem' or 'gather', "
                f"got {self.walk_block_kernel!r}"
            )
        if self.walk_kernel not in ("gather", "vmem", "pallas"):
            raise ValueError(
                "walk_kernel must be 'gather', 'vmem' or 'pallas', "
                f"got {self.walk_kernel!r}"
            )
        if (
            self.walk_kernel == "pallas"
            and self.resolved_table_dtype() != "bfloat16"
        ):
            raise ValueError(
                "walk_kernel='pallas' is the two-tier streaming kernel "
                "and needs the bf16 select tier — set "
                "walk_table_dtype='bfloat16' (got "
                f"{self.resolved_table_dtype()!r})"
            )
        if self.batch_stats_trigger is not None:
            from pumiumtally_tpu.stats.triggers import TriggerSpec

            if not isinstance(self.batch_stats_trigger, TriggerSpec):
                raise ValueError(
                    "batch_stats_trigger must be a stats.TriggerSpec, "
                    f"got {self.batch_stats_trigger!r}"
                )
            if not self.batch_stats:
                raise ValueError(
                    "batch_stats_trigger needs batch_stats=True (no "
                    "lanes are accumulated otherwise)"
                )
        if self.scoring is not None:
            from pumiumtally_tpu.scoring.binding import ScoringSpec

            if not isinstance(self.scoring, ScoringSpec):
                raise ValueError(
                    "scoring must be a scoring.ScoringSpec, "
                    f"got {self.scoring!r}"
                )
        if self.checkpoint is not None:
            from pumiumtally_tpu.resilience.policy import CheckpointPolicy

            if not isinstance(self.checkpoint, CheckpointPolicy):
                raise ValueError(
                    "checkpoint must be a resilience.CheckpointPolicy, "
                    f"got {self.checkpoint!r}"
                )
        if self.sentinel is not None:
            from pumiumtally_tpu.sentinel.policy import SentinelPolicy

            if not isinstance(self.sentinel, SentinelPolicy):
                raise ValueError(
                    "sentinel must be a sentinel.SentinelPolicy, "
                    f"got {self.sentinel!r}"
                )
        if self.cap_frontier is not None and int(self.cap_frontier) < 0:
            raise ValueError(
                f"cap_frontier must be >= 0 (0 = forced full-capacity "
                f"fallback) or None, got {self.cap_frontier!r}"
            )
        if self.placement not in ("linear", "pod_rcb"):
            raise ValueError(
                f"placement must be 'linear' or 'pod_rcb', "
                f"got {self.placement!r}"
            )
        if self.placement_hosts is not None:
            hosts = tuple(self.placement_hosts)
            if not hosts or any(
                not isinstance(h, int) or h < 1 for h in hosts
            ):
                raise ValueError(
                    "placement_hosts must be a non-empty tuple of "
                    f"positive per-host chip counts, "
                    f"got {self.placement_hosts!r}"
                )

    def resolved_min_window(self) -> int:
        """min_window with the kernel default applied (consumed, with
        cond_every, by the partitioned engines)."""
        from pumiumtally_tpu.ops.walk import _MIN_WINDOW

        return (
            _MIN_WINDOW
            if self.walk_min_window is None
            else int(self.walk_min_window)
        )

    def resolved_cond_every(self) -> int:
        """cond_every with the kernel default applied (the one knob the
        partitioned engines consume directly)."""
        from pumiumtally_tpu.ops.walk import COND_EVERY_DEFAULT

        return (
            COND_EVERY_DEFAULT
            if self.walk_cond_every is None
            else int(self.walk_cond_every)
        )

    def resolved_table_dtype(self) -> str:
        """Walk-table precision tier with env resolution applied
        (consumed by every facade to decide whether the mesh/partition
        carries the two-tier tables; the monolithic walks also get it
        through walk_kwargs so it is part of the static jit key)."""
        from pumiumtally_tpu.ops.walk import _resolve_table_dtype

        return _resolve_table_dtype(self.walk_table_dtype or "auto")

    def resolved_walk_kernel(self) -> str:
        """The block-kernel selector the partitioned engines receive.
        ``walk_kernel="gather"`` (the default) is the STATUS-QUO
        resolution: defer to the legacy ``walk_block_kernel`` knob so
        untuned configs build byte-identical engines (that knob's
        "vmem" default is inert without ``walk_vmem_max_elems``);
        anything else names the kernel family outright."""
        if self.walk_kernel == "gather":
            return self.walk_block_kernel
        return self.walk_kernel

    def resolved_partition_method(self) -> str:
        """Partition-permutation method with the default applied
        (consumed by the partitioned engines; the monolithic walks get
        it through walk_kwargs)."""
        return (
            "rank"
            if self.walk_partition_method is None
            else self.walk_partition_method
        )

    def walk_kwargs(self) -> tuple:
        """The non-default walk-kernel knobs as a hashable tuple of
        (name, value) pairs — passed as a STATIC argument through the
        jitted step functions (an untuned config yields ``()``, so its
        jit cache keys match pre-knob builds)."""
        from pumiumtally_tpu.ops.walk import (
            PERM_MODE_DEFAULT,
            TABLE_DTYPE_DEFAULT,
            _resolve_perm_mode,
            _resolve_table_dtype,
        )

        out = []
        if self.walk_cond_every is not None:
            out.append(("cond_every", int(self.walk_cond_every)))
        # "auto"/None resolve HERE (env var included) rather than at
        # trace time inside the kernel: the resolved mode must be part
        # of the static jit key, or flipping PUMIUMTALLY_WALK_PERM in a
        # running process would silently reuse the stale compiled mode
        # (bitwise-identical output, but it would invalidate perf A/Bs).
        # Default-equal modes are still dropped to keep cache-key parity
        # with untuned configs.
        mode = _resolve_perm_mode(self.walk_perm_mode or "auto")
        # Drop the knob only when it is BOTH the kernel default and
        # what a trace-time "auto" would resolve to right now — an
        # explicit "packed" under a contrary env var must still be
        # emitted, or the kernel's trace-time fallback would override
        # the explicit choice.
        if mode != PERM_MODE_DEFAULT or mode != _resolve_perm_mode("auto"):
            out.append(("perm_mode", mode))
        # Same resolution + emission rule for the table-precision tier:
        # resolved here so the tier is a static jit key (env flip ⇒
        # recompile); default-equal dropped for cache-key parity; an
        # explicit "float32" under a contrary env var still emitted.
        td = _resolve_table_dtype(self.walk_table_dtype or "auto")
        if td != TABLE_DTYPE_DEFAULT or td != _resolve_table_dtype("auto"):
            out.append(("table_dtype", td))
        if self.walk_window_factor is not None:
            out.append(("window_factor", int(self.walk_window_factor)))
        if self.walk_min_window is not None:
            out.append(("min_window", int(self.walk_min_window)))
        # Default-equal ("rank") is dropped for cache-key parity, like
        # the other knobs.
        if self.resolved_partition_method() != "rank":
            out.append(
                ("partition_method", self.resolved_partition_method())
            )
        return tuple(out)

    def resolved_dtype(self) -> Any:
        return self.dtype if self.dtype is not None else default_float_dtype()

    def resolved_tolerance(self, dtype: Any = None) -> float:
        """Geometric tolerance; keyed to the WORKING dtype (pass the
        adopted dtype when a prebuilt mesh fixed it — an f32 walk must
        not run with the 1e-8 f64 threshold, f32 noise is ~1e-7)."""
        if self.tolerance is not None:
            return float(self.tolerance)
        if dtype is None:
            dtype = self.resolved_dtype()
        return 1e-8 if jnp.dtype(dtype) == jnp.float64 else 1e-6

    def resolved_max_iters(self, nelems: int) -> int:
        if self.max_iters is not None:
            return int(self.max_iters)
        # Safety cap only: the walk's while_loop exits as soon as every
        # particle is done, so a generous bound costs nothing at runtime.
        # A straight segment can cross up to O(E) tets on a degenerate /
        # highly anisotropic mesh, so cap at the element count rather
        # than an isotropic O(E^(1/3)) guess.
        return 64 + int(nelems)
