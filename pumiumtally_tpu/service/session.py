"""Per-client session state: one facade, one queue, one lifecycle.

A ``TallySession`` wraps ONE engine facade (any of the five kinds) on
behalf of one client. Everything campaign-scoped already lives on the
facade — flux, scoring lane banks, batch-statistics accumulators, the
sentinel's health record and quarantine stream, the autosave runner
and its generation store — so wrapping a facade per session is exactly
what keeps those PER-SESSION: two clients sharing a service share the
device and the jit cache (compiled code is value-free) and nothing
else. That is also the root of the service's determinism contract: a
session's campaign output is bitwise the solo run of the same
campaign, however its ops interleave with other sessions'.

Lifecycle: OPEN → DRAINING → CLOSED.

- OPEN accepts submissions into the bounded FIFO queue (admission
  control: ``ServiceBusyError`` when full — the client retries after
  its oldest future resolves; the refused op was never queued and the
  session's state is untouched);
- DRAINING (client close, or service-wide SIGTERM drain) rejects new
  work with ``SessionClosedError`` while queued ops finish;
- CLOSED: queue empty, drain checkpoint written (when autosave is
  armed), facade released from the scheduler ring.

The queue bound defaults to 2 — the double buffer: one op staged
ahead while one executes (staging.py). Deeper queues buy more
pipeline slack at the price of staler backpressure.
"""

from __future__ import annotations

import enum
import time
import warnings
from collections import deque
from typing import Any, Dict, Optional, Tuple

from pumiumtally_tpu.service.scheduler import Priority
from pumiumtally_tpu.service.staging import StagedOp

DEFAULT_QUEUE_DEPTH = 2

# Completed-op latency samples retained per session for the p50/p99
# quantiles in TallyService.stats() / the ping reply. A bounded window,
# not a full history: load telemetry should describe CURRENT service
# behaviour, and an unbounded list would grow with campaign length.
LATENCY_WINDOW = 512


class SessionState(enum.Enum):
    OPEN = "open"
    DRAINING = "draining"
    CLOSED = "closed"


class ServiceBusyError(RuntimeError):
    """The session's move queue is full (admission control): the op was
    NOT enqueued. Retry after one of the session's outstanding futures
    resolves — per-session state is untouched by the refusal."""


class SessionClosedError(RuntimeError):
    """The session is draining or closed and accepts no new work."""


class ServiceOverloadedError(RuntimeError):
    """The SERVICE-wide admission budget (total queued + in-flight
    particle cost across every session) is exhausted: the op or
    session open was NOT admitted and no state changed — like
    ``ServiceBusyError``, the refusal leaves caller buffers untouched
    (accept-then-zero contract). Unlike busy, which is one session's
    backpressure, overload is global: retry after outstanding futures
    resolve anywhere, or route to another worker. Carries the numbers
    a load balancer needs: ``budget``, ``admitted`` (cost units
    currently queued or in flight), ``cost`` (the refused op's)."""

    def __init__(self, message: str, *, budget: Optional[int] = None,
                 admitted: Optional[int] = None,
                 cost: Optional[int] = None):
        super().__init__(message)
        self.budget = budget
        self.admitted = admitted
        self.cost = cost


class TallySession:
    """One client's campaign inside the service (built by
    ``server.TallyService.open_session``; all methods are called under
    the service's lock — the session itself is not a thread-safe
    object)."""

    def __init__(self, session_id: str, tally,
                 max_queue: int = DEFAULT_QUEUE_DEPTH,
                 priority: Priority = Priority.NORMAL):
        if int(max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue!r}")
        self.id = str(session_id)
        self.tally = tally
        self.max_queue = int(max_queue)
        self.priority = Priority(priority)
        self.state = SessionState.OPEN
        self._queue: deque = deque()
        self.ops_submitted = 0
        self.ops_completed = 0
        self.moves_completed = 0
        # Transport (source/move) cost units sitting in THIS queue —
        # the queued half of the service's admission ledger, kept as a
        # running counter so head_cost/stats stay O(1).
        self._queued_cost = 0
        self._latencies: deque = deque(maxlen=LATENCY_WINDOW)
        # The close sentinel's future, once a close is issued: a
        # second close() returns it instead of queueing a sentinel the
        # scheduler could never pick after the first one unregisters
        # the session (a hung future, not an error).
        self.close_future = None
        runner = getattr(tally, "_resilience", None)
        if runner is not None and runner.policy.handle_signals:
            # The SERVICE owns the process's drain handler (one
            # dispatcher, resilience/policy.py); a per-session runner
            # that also installed one would shadow it with a handler
            # whose drain flag nothing in the service consumes.
            warnings.warn(
                f"session {self.id!r}: its CheckpointPolicy has "
                "handle_signals=True — inside a service, pass "
                "handle_signals=False and let the service drain every "
                "session on SIGTERM"
            )

    # -- queue (service-lock context) ------------------------------------
    def submit(self, op: StagedOp) -> StagedOp:
        if self.state is not SessionState.OPEN:
            raise SessionClosedError(
                f"session {self.id!r} is {self.state.value}: it accepts "
                "no new work"
            )
        if len(self._queue) >= self.max_queue:
            raise ServiceBusyError(
                f"session {self.id!r} queue is full "
                f"({self.max_queue} staged ops): retry after an "
                "outstanding future resolves"
            )
        self._queue.append(op)
        self.ops_submitted += 1
        if op.kind != "call":
            self._queued_cost += op.cost
        return op

    def submit_final(self, op: StagedOp) -> StagedOp:
        """Enqueue past the DRAINING gate (the session-close sentinel
        op itself; the depth bound is deliberately not applied — a
        close must never be refused for backpressure)."""
        if self.state is SessionState.CLOSED:
            raise SessionClosedError(f"session {self.id!r} is closed")
        self._queue.append(op)
        self.ops_submitted += 1
        if op.kind != "call":
            self._queued_cost += op.cost
        return op

    def head_cost(self) -> Optional[int]:
        return self._queue[0].cost if self._queue else None

    def head(self) -> Optional[StagedOp]:
        """The queued head op WITHOUT popping it (the fusion window
        inspects kinds/keys under the service lock before committing
        to a group)."""
        return self._queue[0] if self._queue else None

    def pop(self) -> StagedOp:
        op = self._queue.popleft()
        if op.kind != "call":
            self._queued_cost -= op.cost
        return op

    def pending(self) -> int:
        return len(self._queue)

    def queued_cost(self) -> int:
        """Transport cost units currently queued (reads excluded —
        they carry no particle buffers and cost 1 only for DRR turn
        accounting)."""
        return self._queued_cost

    def note_completed(self, op: StagedOp) -> None:
        self.ops_completed += 1
        if op.kind == "move":
            self.moves_completed += 1
        if op.t_submit is not None:
            self._latencies.append(time.perf_counter() - op.t_submit)

    def latency_quantiles(self) -> Optional[Tuple[float, float]]:
        """(p50, p99) submit→resolve wall latency in seconds over the
        last ``LATENCY_WINDOW`` completed ops, or None before the
        first completion (nearest-rank on the sorted window — exact,
        no interpolation, cheap at 512 samples)."""
        if not self._latencies:
            return None
        a = sorted(self._latencies)
        hi = len(a) - 1

        def q(p: float) -> float:
            return a[min(hi, int(p * hi + 0.5))]

        return q(0.50), q(0.99)

    # -- lifecycle -------------------------------------------------------
    def begin_drain(self) -> None:
        if self.state is SessionState.OPEN:
            self.state = SessionState.DRAINING

    def mark_closed(self) -> None:
        self.state = SessionState.CLOSED

    # -- drain checkpoint -------------------------------------------------
    def drain_checkpoint(self, reason: str = "service_drain"
                         ) -> Optional[Tuple[int, str]]:
        """Write one generation through the session's own autosave
        runner (None when the facade has no ``TallyConfig.checkpoint``
        armed — drain then simply discards the session's device state,
        exactly like a bare facade's process exit). The generation's
        metadata carries the session id and, with a sentinel armed,
        the session's health summary — a drained fleet leaves one
        self-describing generation per session."""
        runner = getattr(self.tally, "_resilience", None)
        if runner is None:
            return None
        meta: Dict[str, Any] = {"session": self.id}
        if getattr(self.tally, "_sentinel", None) is not None:
            meta["health"] = self.tally.health_report().as_dict()
        return runner.save(self.tally, reason=reason, meta=meta)
