"""Multi-session campaign service (round 11, docs/DESIGN.md
"Multi-session service").

The five facades reproduce the reference's synchronous single-client
protocol; this package is the serving layer over them — one device,
many concurrent client sessions:

- ``TallySession`` (session.py) — one facade per client, so flux /
  scoring banks / statistics / sentinel health / autosave generations
  stay per-session; bounded FIFO queue with ``ServiceBusyError``
  backpressure; OPEN → DRAINING → CLOSED lifecycle.
- ``DeficitRoundRobinScheduler`` (scheduler.py) — fair, work-
  proportional interleaving of ready sessions; one hot client cannot
  starve the rest (O(1) unfairness bound).
- staging (staging.py) — double-buffered host-side prepack +
  validation at submit time: clients get futures, never block on
  device compute, and may recycle their buffers immediately.
- ``TallyService`` / ``SessionHandle`` / ``SocketFrontend``
  (server.py) — the in-process async client API, the NDJSON socket
  front end (``pumiumtally serve``), and the process-wide SIGTERM
  drain that checkpoints every open session through the resilience
  dispatcher.
- cross-session batch fusion (fusion.py, round 12) — backlogged
  sessions grouped by fusion key pack their head moves into one
  padded slab and share ONE device launch (entry point
  ``"walk_fused"``, the service's single jitted program), scattering
  per-session flux/score-bank results back bitwise-equal to solo
  runs; ``TallyService(fuse_sessions=False)`` reproduces the
  one-op-at-a-time round-11 path bit for bit.
- ``SessionRouter`` (server.py, round 13) — pod-scale serving: each
  host runs its own service + ``SocketFrontend`` worker
  (``pumiumtally serve``) against its local devices; the router
  (``pumiumtally route``) pins every session to a home worker at open
  and forwards its NDJSON ops there, so the multi-session machinery
  scales horizontally with the same per-session bitwise contract.
- traffic engineering (round 20) — streaming sessions fuse
  chunk-wise (one shared launch per chunk index, same ``walk_fused``
  program); ``Priority`` lanes over the DRR ring (strict priority
  between lanes, DRR within); a global admission budget that refuses
  with a structured ``ServiceOverloadedError`` before buffers are
  touched; ``TallyService.stats()`` + the ping ``"load"`` reply feed
  the load generator (tools/loadgen.py, ``pumiumtally loadgen``) and
  the router's least-loaded placement.

Core contract — determinism under concurrency: each session's output
is BITWISE the solo run of the same campaign, regardless of how the
scheduler interleaves sessions OR which sessions shared a fused
launch (pinned by tests/test_service.py and tests/test_fusion.py).
Outside fusion.py everything here is host-side Python (threads,
queues, numpy buffers) — the fused entry point is the service's one
addition to config.RETRACE_BUDGETS.
"""

from pumiumtally_tpu.service.scheduler import (
    DeficitRoundRobinScheduler,
    Priority,
)
from pumiumtally_tpu.service.session import (
    DEFAULT_QUEUE_DEPTH,
    ServiceBusyError,
    ServiceOverloadedError,
    SessionClosedError,
    SessionState,
    TallySession,
)
from pumiumtally_tpu.service.server import (
    ServiceDrainingError,
    SessionHandle,
    SessionRouter,
    SocketFrontend,
    TallyService,
)

__all__ = [
    "DEFAULT_QUEUE_DEPTH",
    "DeficitRoundRobinScheduler",
    "Priority",
    "ServiceBusyError",
    "ServiceDrainingError",
    "ServiceOverloadedError",
    "SessionClosedError",
    "SessionHandle",
    "SessionRouter",
    "SessionState",
    "SocketFrontend",
    "TallyService",
    "TallySession",
]
