"""The multi-tenant campaign service: device owner + client front ends.

``TallyService`` owns the device on behalf of any number of concurrent
client sessions. ONE worker thread executes every facade call — the
serialization point that makes multi-tenancy deterministic:

- per-session ops run in strict FIFO order (session.py), so each
  session's campaign is the exact op sequence its client submitted;
- sessions interleave under deficit round robin (scheduler.py), which
  bounds cross-session unfairness by a constant but has NO influence
  on values — sessions share nothing but the device and the jit cache
  (compiled code, no state), so a session's flux is bitwise the solo
  run of its campaign whatever the interleaving;
- reads (flux, health, statistics) ride the same FIFO as transport
  ops, so a read observes exactly the moves submitted before it.

Clients never block on device compute: ``SessionHandle`` methods
prepack + validate on the calling thread (staging.py), enqueue, and
return a ``concurrent.futures.Future``. A full queue refuses with
``ServiceBusyError`` at submit (admission control) — nothing partial
ever enters the pipeline.

Drain: the service registers with the resilience layer's process-wide
signal dispatcher (resilience.install_drain_owner — the SAME
single-owner mechanism a bare autosave-armed facade uses, so a second
SIGTERM still escalates to an immediate kill). The first SIGTERM sets
the drain flag: every session stops accepting work, in-flight and
queued ops finish, and ``shutdown(drain=True)`` writes one checkpoint
generation per autosave-armed session before the process exits 0.
Per-session ``CheckpointPolicy``s should carry
``handle_signals=False`` — the service owns the handler.

The NDJSON socket front end (``SocketFrontend`` / the ``pumiumtally
serve`` CLI verb) lets external host codes attach as independent
sessions: one JSON object per line, arrays as base64 little-endian
raw bytes (f64 positions/weights/energy/time, int8 flying). It trusts
its network: no authentication, mesh-path loading disabled unless
explicitly allowed — deploy it behind the same perimeter as the host
codes it serves.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import socket
import threading
import time
import warnings
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from pumiumtally_tpu.service import staging
from pumiumtally_tpu.service.scheduler import (
    DeficitRoundRobinScheduler,
    Priority,
)
from pumiumtally_tpu.service.session import (
    ServiceBusyError,
    ServiceOverloadedError,
    SessionClosedError,
    SessionState,
    TallySession,
)


class ServiceDrainingError(RuntimeError):
    """The service received a drain request (SIGTERM or shutdown) and
    accepts no new work. Distinct from ``ServiceBusyError`` on
    purpose: busy means retry, draining means finish up and detach."""


class TallyService:
    """Multi-session campaign service (in-process API).

    Args:
      handle_signals: own the process's SIGTERM/SIGINT graceful-drain
        handler via the resilience dispatcher (main thread only).
      quantum: scheduler quantum in cost units (None = auto; see
        scheduler.DeficitRoundRobinScheduler).
      autostart: start the worker thread lazily on the first submit
        (False = the caller starts it explicitly — the backpressure
        tests stage against a stopped worker deterministically).
      fuse_sessions: coalesce compatible sessions' queued moves into
        ONE padded device launch (round 12, service/fusion.py) —
        sessions grouped by fusion key (same mesh + facade kind +
        static walk/scoring configuration) pack one slab, run one
        walk, and scatter per-session results back bitwise-equal to
        solo runs. Default on; False reproduces the one-op-at-a-time
        round-11 path bit for bit (and a 1-session service never
        fuses either way — a group of one runs the unfused path).
      max_fuse: the fusion window — at most this many compatible
        session heads share one launch (bounds slab size and trace
        keys).
      admission_budget: global cap on transport (source/move) cost
        units queued or in flight across ALL sessions (round 20).
        None (default) = unbounded, the pre-round-20 behaviour. With a
        budget, a submit that would exceed it — or an ``open_session``
        arriving while the budget is already full — refuses with a
        structured ``ServiceOverloadedError`` BEFORE any state
        changes, so a thousand eager clients backlog at the protocol
        layer instead of OOMing the staging heap. Reads and the close
        sentinel never count against (or get refused by) the budget:
        telemetry and teardown must stay live under overload.
    """

    def __init__(self, *, handle_signals: bool = False,
                 quantum: Optional[int] = None, autostart: bool = True,
                 fuse_sessions: bool = True, max_fuse: int = 8,
                 admission_budget: Optional[int] = None):
        if int(max_fuse) < 1:
            raise ValueError(f"max_fuse must be >= 1, got {max_fuse!r}")
        if admission_budget is not None and int(admission_budget) < 1:
            raise ValueError(
                f"admission_budget must be >= 1, got {admission_budget!r}"
            )
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._sessions: Dict[str, TallySession] = {}
        self._sched = DeficitRoundRobinScheduler(quantum=quantum)
        self._seq = itertools.count(1)
        self._drain = False  # the resilience dispatcher's duck-typed flag
        self._stop = False
        self._inflight = 0
        self._autostart = bool(autostart)
        self._handle_signals = bool(handle_signals)
        self._fuse = bool(fuse_sessions)
        self._max_fuse = int(max_fuse)
        self._admission_budget = (
            None if admission_budget is None else int(admission_budget)
        )
        # Transport cost units admitted and not yet completed
        # (queued + in flight) — the admission ledger. Credited in
        # _submit under the lock, debited when the worker resolves the
        # op, so the budget bounds live staging-heap footprint.
        self._admitted_cost = 0
        self.admission_stats: Dict[str, int] = {
            "refused_ops": 0, "refused_sessions": 0,
        }
        # Serving telemetry (read by the fusion A/B): how many device
        # dispatch opportunities coalesced. "fused_groups" counts
        # shared launches, "fused_moves" the moves they carried,
        # "solo_moves"/"solo_other" the ops that ran one at a time.
        self.fusion_stats: Dict[str, int] = {
            "fused_groups": 0, "fused_moves": 0,
            "solo_moves": 0, "solo_other": 0,
        }
        self._worker: Optional[threading.Thread] = None
        if self._handle_signals:
            from pumiumtally_tpu.resilience import install_drain_owner

            install_drain_owner(self)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._worker is not None or self._stop:
                return
            self._worker = threading.Thread(
                target=self._worker_loop, name="pumiumtally-service",
                daemon=True,
            )
            self._worker.start()

    def __enter__(self) -> "TallyService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(drain=exc_type is None)

    @property
    def drain_requested(self) -> bool:
        return self._drain

    def request_drain(self) -> None:
        """What the SIGTERM handler effects: stop intake everywhere;
        queued and in-flight work still completes. The controlling
        loop (CLI serve / a driver) observes ``drain_requested`` and
        calls ``shutdown(drain=True)``."""
        with self._cv:
            self._drain = True
            for sess in self._sessions.values():
                sess.begin_drain()
            self._cv.notify_all()

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None
                 ) -> Dict[str, Any]:
        """Stop intake, finish every queued op, optionally write one
        drain checkpoint per autosave-armed open session, stop the
        worker. Returns ``{session_id: (generation, path) | None}``
        for the sessions drained (empty when ``drain=False``)."""
        self.request_drain()
        with self._lock:
            has_pending = bool(self._inflight) or any(
                s.pending() for s in self._sessions.values()
            )
        if has_pending:
            # Queued ops always complete before the service stops —
            # even when the worker was never started (autostart=False
            # and a shutdown before start()).
            self.start()
        saved: Dict[str, Any] = {}
        with self._cv:
            quiesced = self._cv.wait_for(
                lambda: self._inflight == 0 and not any(
                    s.pending() for s in self._sessions.values()
                ),
                timeout=timeout,
            )
            sessions = list(self._sessions.values())
        if not quiesced:
            # Never checkpoint while the worker may still be mutating
            # facade state — a mid-move snapshot would break the
            # bitwise-resume guarantee. The service stays draining;
            # the caller can retry shutdown.
            raise TimeoutError(
                f"service did not quiesce within {timeout}s; no drain "
                "checkpoints written — retry shutdown()"
            )
        # Checkpoints OUTSIDE the lock: saves fetch device arrays and
        # fsync — nothing a submit (they all refuse now) can race.
        # Per-session containment: one session's failing store (ENOSPC,
        # EACCES) must not cost the OTHER sessions their generations,
        # nor skip the worker-stop/handler-release below — the drained
        # process still exits 0 for the sessions whose storage is
        # healthy.
        for sess in sessions:
            if drain and sess.state is not SessionState.CLOSED:
                try:
                    saved[sess.id] = sess.drain_checkpoint()
                except Exception as e:  # noqa: BLE001 — see above
                    warnings.warn(
                        f"session {sess.id!r}: drain checkpoint "
                        f"failed ({e!r}); its state is lost but the "
                        "drain continues"
                    )
                    saved[sess.id] = None
            sess.mark_closed()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            worker = self._worker
        if worker is not None:
            worker.join(timeout=timeout)
        if self._handle_signals:
            from pumiumtally_tpu.resilience import release_drain_owner

            release_drain_owner(self)
        return saved

    # -- sessions --------------------------------------------------------
    def open_session(self, tally, *, session_id: Optional[str] = None,
                     max_queue: Optional[int] = None,
                     priority: Priority = Priority.NORMAL
                     ) -> "SessionHandle":
        """Admit one client: wrap its facade (any of the five kinds,
        built by the caller so the client picks engine/config) in a
        session and register it with the scheduler, in the lane named
        by ``priority`` (fixed for the session's lifetime). With an
        admission budget armed, an open arriving while the budget is
        already full refuses with ``ServiceOverloadedError`` — a new
        client's first submit could never be admitted anyway, and
        refusing at open lets a router place it elsewhere."""
        with self._lock:
            if self._drain or self._stop:
                raise ServiceDrainingError(
                    "service is draining: no new sessions"
                )
            if (self._admission_budget is not None
                    and self._admitted_cost >= self._admission_budget):
                self.admission_stats["refused_sessions"] += 1
                raise ServiceOverloadedError(
                    f"admission budget full ({self._admitted_cost}/"
                    f"{self._admission_budget} cost units queued or in "
                    "flight): no new sessions — retry after outstanding "
                    "work resolves, or route elsewhere",
                    budget=self._admission_budget,
                    admitted=self._admitted_cost,
                )
            sid = session_id
            if sid is None:
                # The generator must skip ids a caller claimed
                # explicitly — open_session(session_id="s1") then
                # open_session() would otherwise refuse the caller
                # who passed nothing.
                sid = f"s{next(self._seq)}"
                while sid in self._sessions:
                    sid = f"s{next(self._seq)}"
            if sid in self._sessions:
                raise ValueError(f"session id {sid!r} already open")
            kw = {} if max_queue is None else {"max_queue": max_queue}
            sess = TallySession(sid, tally, priority=Priority(priority),
                                **kw)
            self._sessions[sid] = sess
            self._sched.register(sid, priority=sess.priority)
        if self._handle_signals and (
            threading.current_thread() is threading.main_thread()
        ):
            # Newest owner wins in the dispatcher; re-assert ownership
            # in case a session's facade installed its own runner.
            # Main thread only: Python cannot (re)bind handlers
            # elsewhere, and a socket-thread open would otherwise
            # trigger the dispatcher's misleading not-main-thread
            # warning (the handler installed at construction stays in
            # force regardless).
            from pumiumtally_tpu.resilience import install_drain_owner

            install_drain_owner(self)
        return SessionHandle(self, sess)

    def session_ids(self) -> tuple:
        with self._lock:
            return tuple(self._sessions)

    # -- telemetry --------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """One structured, JSON-serializable snapshot of serving
        telemetry (round 20) — what the load generator and the router
        read instead of scraping logs. Schema (pinned by
        tests/test_traffic.py):

        - ``"sessions"``: ``{sid: {state, priority, pending,
          queued_cost, ops_completed, moves_completed, latency_p50_ms,
          latency_p99_ms}}`` — the latency quantiles are
          submit→resolve wall time over the session's last
          ``session.LATENCY_WINDOW`` completions (None before the
          first);
        - ``"fusion"``: a copy of ``fusion_stats``;
        - ``"admission"``: ``{budget, admitted_cost, queued_cost,
          inflight_cost, refused_ops, refused_sessions}`` — admitted =
          queued + inflight; budget None when unbounded.
        """
        with self._lock:
            sessions: Dict[str, Any] = {}
            queued = 0
            for sid, sess in self._sessions.items():
                q = sess.latency_quantiles()
                qc = sess.queued_cost()
                queued += qc
                sessions[sid] = {
                    "state": sess.state.value,
                    "priority": sess.priority.name.lower(),
                    "pending": sess.pending(),
                    "queued_cost": qc,
                    "ops_completed": sess.ops_completed,
                    "moves_completed": sess.moves_completed,
                    "latency_p50_ms": None if q is None else q[0] * 1e3,
                    "latency_p99_ms": None if q is None else q[1] * 1e3,
                }
            return {
                "sessions": sessions,
                "fusion": dict(self.fusion_stats),
                "admission": {
                    "budget": self._admission_budget,
                    "admitted_cost": self._admitted_cost,
                    "queued_cost": queued,
                    "inflight_cost": self._admitted_cost - queued,
                    "refused_ops": self.admission_stats["refused_ops"],
                    "refused_sessions":
                        self.admission_stats["refused_sessions"],
                },
            }

    # -- submission (called by SessionHandle) -----------------------------
    def _submit(self, sess: TallySession, op: staging.StagedOp) -> Future:
        with self._cv:
            if self._drain or self._stop:
                raise ServiceDrainingError(
                    "service is draining: no new work accepted"
                )
            transport = op.kind != "call"
            if (transport and self._admission_budget is not None
                    and self._admitted_cost + op.cost
                    > self._admission_budget):
                # Refused BEFORE sess.submit: nothing queued, no
                # accounting moved, caller buffers untouched
                # (accept-then-zero — SessionHandle.move only zeroes
                # flying after this returns).
                self.admission_stats["refused_ops"] += 1
                raise ServiceOverloadedError(
                    f"admission budget exhausted: {self._admitted_cost}"
                    f"/{self._admission_budget} cost units queued or in "
                    f"flight, op costs {op.cost} — retry after "
                    "outstanding futures resolve",
                    budget=self._admission_budget,
                    admitted=self._admitted_cost,
                    cost=op.cost,
                )
            sess.submit(op)  # may still refuse busy/closed: not admitted
            op.t_submit = time.perf_counter()
            if transport:
                self._admitted_cost += op.cost
            self._cv.notify_all()
        if self._autostart:
            self.start()
        return op.future

    def _close_session(self, sess: TallySession) -> Future:
        """Queue the session-close sentinel: runs after every already
        queued op, writes the drain checkpoint (if armed), closes the
        session, releases its scheduler slot. Idempotent while the
        sentinel is in flight: a repeated close returns the SAME
        future (a second sentinel could never run once the first one
        unregisters the session)."""
        def _finalize(tally):
            # finally: a failing session_close checkpoint still
            # CLOSES the session (the exception reaches the client
            # through the close future) — otherwise the facade would
            # leak in the scheduler ring forever behind a cached
            # failed future.
            try:
                return sess.drain_checkpoint(reason="session_close")
            finally:
                with self._cv:
                    sess.mark_closed()
                    self._sched.unregister(sess.id)
                    self._sessions.pop(sess.id, None)
                    self._cv.notify_all()

        op = staging.stage_call("close", _finalize)
        with self._cv:
            if sess.close_future is not None:
                return sess.close_future  # idempotent repeat close
            if sess.state is SessionState.CLOSED:
                raise SessionClosedError(
                    f"session {sess.id!r} is already closed"
                )
            if self._drain or self._stop:
                raise ServiceDrainingError(
                    "service is draining: it closes every session "
                    "itself at shutdown"
                )
            sess.begin_drain()
            sess.submit_final(op)
            op.t_submit = time.perf_counter()
            sess.close_future = op.future
            self._cv.notify_all()
        if self._autostart:
            self.start()
        return op.future

    # -- worker ----------------------------------------------------------
    def _head_cost(self, sid: str) -> Optional[int]:
        sess = self._sessions.get(sid)
        return None if sess is None else sess.head_cost()

    def _group_key(self, sid: str):
        """The fusion key of a session's queued head, or None when
        that head must run alone: only MOVE ops of facades that
        declare a fusion key (PumiTally._fusion_key) ever co-fuse —
        sources, reads, batch closes and the close sentinel keep the
        one-at-a-time path."""
        sess = self._sessions.get(sid)
        if sess is None:
            return None
        op = sess.head()
        if op is None or op.kind != "move":
            return None
        fkey = getattr(sess.tally, "_fusion_key", None)
        return None if fkey is None else fkey()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                # ONE scheduler-lock round trip per dispatched GROUP
                # (round-12 micro-fix): the lead pick and every
                # co-fused head pop under a single acquisition, so a
                # K-way fused dispatch costs one lock round trip, not
                # K.
                if self._fuse and self._max_fuse > 1:
                    sids = self._sched.pick_group(
                        self._head_cost, self._group_key, self._max_fuse
                    )
                else:
                    one = self._sched.pick(self._head_cost)
                    sids = None if one is None else [one]
                if sids is None:
                    if self._stop:
                        return
                    # Every producer notifies this condition (_submit,
                    # _close_session, request_drain, shutdown), so the
                    # timeout is only a liveness safety net, not the
                    # wake mechanism — long enough that an idle server
                    # barely wakes, short enough that a missed notify
                    # could never hang a drain.
                    self._cv.wait(1.0)
                    continue
                items = []
                for sid in sids:
                    sess = self._sessions[sid]
                    items.append((sess, sess.pop()))
                self._inflight += len(items)
            # Execute OUTSIDE the lock: device work must never block
            # staging/submission on the client threads. A facade-level
            # drain exit (SystemExit, absorbed by run_op_contained /
            # run_group) folds into a service-wide drain instead of
            # killing the worker.
            coalesced = solo_ran = 0
            if len(items) == 1:
                sess, op = items[0]
                drain = staging.run_op_contained(sess.tally, op)
                solo_ran = 1
            else:
                # Deferred import: the fuse-off (and never-fusing)
                # service keeps the round-11 import graph.
                from pumiumtally_tpu.service import fusion

                drain, coalesced, solo_ran = fusion.run_group(items)
            if drain:
                self.request_drain()
            with self._cv:
                # Telemetry counts what actually DISPATCHED: a group
                # whose launch fell back to solo execution reports its
                # moves as solo (the A/B's dispatches-per-move is
                # computed from exactly these counters), and a staged
                # op that refused before any launch counts nowhere.
                if coalesced:
                    self.fusion_stats["fused_groups"] += 1
                    self.fusion_stats["fused_moves"] += coalesced
                if solo_ran:
                    key = (
                        "solo_moves"
                        if items[0][1].kind == "move" else "solo_other"
                    )
                    self.fusion_stats[key] += solo_ran
                for sess, op in items:
                    self._inflight -= 1
                    if op.kind != "call":
                        self._admitted_cost -= op.cost
                    sess.note_completed(op)
                self._cv.notify_all()


class SessionHandle:
    """A client's view of its session: the three-call protocol plus
    reads, each returning a ``concurrent.futures.Future`` that resolves
    when the op executes (in submission order). Prepack + validation
    run synchronously on the caller's thread — errors raise HERE, and
    the caller's buffers are free for reuse the moment a method
    returns."""

    def __init__(self, service: TallyService, session: TallySession):
        self._service = service
        self._session = session

    @property
    def id(self) -> str:
        return self._session.id

    @property
    def state(self) -> SessionState:
        return self._session.state

    @property
    def pending(self) -> int:
        """Ops currently queued (staged but not yet executed)."""
        return self._session.pending()

    @property
    def tally(self):
        """The wrapped facade. Read-only inspection between resolved
        futures only — mutating protocol calls MUST go through the
        handle (the worker owns execution order)."""
        return self._session.tally

    # -- protocol --------------------------------------------------------
    def copy_initial_position(self, positions, size: Optional[int] = None
                              ) -> Future:
        op = staging.stage_source(self._session.tally, positions, size)
        return self._service._submit(self._session, op)

    def move(self, particle_origin, particle_destinations, flying=None,
             weights=None, size: Optional[int] = None, energy=None,
             time=None) -> Future:
        """Stage one ``MoveToNextLocation``. Flying-buffer semantics
        mirror the direct protocol as far as an async API can: a
        refusal HERE (validation error, ``ServiceBusyError``) leaves
        the caller's flying buffer untouched, so the retry stages the
        same bytes. But acceptance zeroes it immediately — submit is
        the last moment the buffer is still the caller's to write —
        so an op that later fails at EXECUTION (e.g. move before
        source, poisoned facade; surfaced on the future) differs from
        a direct call, which raises before zeroing: after an errored
        future, re-stage ``flying`` explicitly rather than re-sending
        the (now zeroed) buffer."""
        op = staging.stage_move(
            self._session.tally, particle_origin, particle_destinations,
            flying, weights, size, energy, time,
        )
        fut = self._service._submit(self._session, op)
        # The protocol's host side effect, applied only once the op is
        # ACCEPTED: a ServiceBusyError above leaves the caller's
        # buffers untouched, so the retry stages identical bytes (the
        # staged int8 copy inside the op is what transports).
        staging.zero_flying_side_effect(flying,
                                        self._session.tally.num_particles)
        return fut

    def close_batch(self, trigger=None) -> Future:
        return self._call("close_batch",
                          lambda t: t.close_batch(trigger=trigger))

    def finalize(self) -> Future:
        return self._call("finalize", lambda t: t.finalize())

    def write(self, filename: Optional[str] = None) -> Future:
        return self._call("write", lambda t: t.WriteTallyResults(filename))

    def checkpoint(self, **meta) -> Future:
        return self._call("checkpoint", lambda t: t.checkpoint_now(**meta))

    # -- reads (FIFO-consistent: they observe every prior submitted op) --
    def flux(self) -> Future:
        return self._call("flux", lambda t: np.asarray(t.flux))

    def normalized_flux(self) -> Future:
        return self._call("normalized_flux",
                          lambda t: np.asarray(t.normalized_flux()))

    def score_bank(self) -> Future:
        return self._call("score_bank", lambda t: np.asarray(t.score_bank))

    def health_report(self) -> Future:
        return self._call("health", lambda t: t.health_report())

    def batch_statistics(self) -> Future:
        return self._call("batch_statistics",
                          lambda t: t.batch_statistics())

    def lost_particles(self) -> Future:
        return self._call("lost_particles", lambda t: t.lost_particles)

    def _call(self, label: str, fn) -> Future:
        return self._service._submit(
            self._session, staging.stage_call(label, fn)
        )

    # -- lifecycle -------------------------------------------------------
    def close(self) -> Future:
        """Drain this session: queued ops finish, one checkpoint
        generation is written (when autosave is armed), the session
        leaves the scheduler ring. The future resolves to the
        ``(generation, path)`` saved, or None."""
        return self._service._close_session(self._session)


# ---------------------------------------------------------------------------
# NDJSON socket front end
# ---------------------------------------------------------------------------

_WIRE_F64 = np.dtype("<f8")
_WIRE_I8 = np.dtype("<i1")


def _decode_array(payload: str, dtype) -> np.ndarray:
    return np.frombuffer(base64.b64decode(payload), dtype=dtype).copy()


def _encode_array(a: np.ndarray) -> str:
    # One conversion: ascontiguousarray handles dtype AND byte order
    # (the explicit .astype('<f8') it replaces copied a second time
    # even on little-endian hosts, where '<f8' IS float64).
    return base64.b64encode(
        np.ascontiguousarray(a, dtype=_WIRE_F64).tobytes()
    ).decode("ascii")


class SocketFrontend:
    """Newline-delimited-JSON TCP front end over a ``TallyService``.

    One request object per line, one response object per line. Ops:

    - ``{"op": "open", "facade": "mono"|"stream"|"part",
         "num_particles": n, "mesh": {"box": [lx,ly,lz,nx,ny,nz]}?,
         "chunk_size": c?, "batch_stats": bool?, "sentinel": bool?,
         "checkpoint_dir": path?, "priority": "high"|"normal"|"low"?}``
      → ``{"ok": true, "session": id}``.
      Omitted mesh = the server's default; ``{"path": ...}`` meshes
      need ``allow_mesh_paths=True`` (the CLI's --allow-mesh-paths).
      ``checkpoint_dir`` must be unique per open session (one
      generation store per session); an in-use dir refuses.
    - ``{"op": "source"|"move", "session": id, ...arrays...,
         "wait": bool?}`` — arrays base64 little-endian (f64
      positions/origins/dests/weights/energy/time, int8 flying).
      ``wait`` false acks after staging (pipelining); surface errors
      later via "sync". The direct protocol's host side effect —
      ``MoveToNextLocation`` zeroes the caller's flying buffer in
      place — cannot reach across the wire: the server zeroes only
      its decoded copy, so a remote client porting from the in-process
      API must zero its OWN flying buffer after any accepted move
      (``"ok": true`` without ``"busy"``; a busy refusal means the
      buffer is untouched and the retry resends the same bytes).
    - ``{"op": "sync", "session": id}`` — wait for every pending op of
      this connection's session, report the first failure.
    - ``{"op": "flux"|"normalized_flux"|"health"|"lost", "session": id}``
    - ``{"op": "close_batch"|"finalize"|"write"|"close", "session": id}``
      ("write" takes "filename"; refused unless ``allow_write``).
    - ``{"op": "ping"}`` → ``{"ok": true, "draining": bool,
         "load": {sessions, queued_cost, inflight_cost, admitted_cost,
         budget}, "fusion": {...fusion_stats}}`` — the aggregate the
      router's placement and the load generator poll.
    - ``{"op": "stats"}`` → ``{"ok": true, "stats":
         TallyService.stats()}`` (per-session p50/p99 latency).

    Failures answer ``{"ok": false, "error": <class>, "message": ...}``
    with ``"busy": true`` for per-session backpressure refusals (retry
    after a future resolves) and ``"overloaded": true`` for
    service-wide admission-budget refusals (back off or route to
    another worker) — in both cases the refused op was never admitted
    and the client's buffers are untouched.
    """

    def __init__(self, service: TallyService, host: str = "127.0.0.1",
                 port: int = 0, *, default_mesh=None,
                 default_particles: int = 100_000,
                 allow_mesh_paths: bool = False, allow_write: bool = False):
        self.service = service
        self.default_mesh = default_mesh
        self.default_particles = int(default_particles)
        self.allow_mesh_paths = bool(allow_mesh_paths)
        self.allow_write = bool(allow_write)
        self._srv = socket.create_server((host, int(port)))
        # Timeout-based accept: closing a listening socket does not
        # reliably wake a blocked accept() on all platforms, so stop()
        # would otherwise hang until its join timeout. The loop wakes
        # every 250 ms to observe _closing.
        self._srv.settimeout(0.25)
        self.host, self.port = self._srv.getsockname()[:2]
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._closing = False
        # checkpoint_dir reservations, across ALL connections: two
        # sessions sharing a directory would share one GenerationStore
        # — keep-pruning then deletes the OTHER session's generations
        # and "one drain generation per session" silently collapses.
        # An open naming an in-use dir refuses with a structured error.
        self._ckpt_lock = threading.Lock()
        self._ckpt_reserved: set = set()  # realpaths in use
        self._ckpt_by_sid: Dict[str, str] = {}
        self._box_meshes: Dict[tuple, Any] = {}  # see _resolve_mesh

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self.service.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pumiumtally-serve-accept",
            daemon=True,
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._srv.accept()
            except TimeoutError:
                continue  # periodic _closing check (see settimeout)
            except OSError:
                return  # socket closed
            conn.settimeout(None)  # connections block; only accept polls
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
            )
            t.start()
            # Prune finished connection threads so a long-lived server
            # handling many short connections stays bounded.
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # -- checkpoint-dir reservations --------------------------------------
    def _reserve_ckpt_dir(self, ck) -> Optional[str]:
        """Reserve an open request's checkpoint_dir (realpath, so two
        spellings of one directory collide); None when the request has
        no checkpointing. Raises on a dir another open session holds."""
        if not ck:
            return None
        ckreal = os.path.realpath(str(ck))
        with self._ckpt_lock:
            if ckreal in self._ckpt_reserved:
                raise ValueError(
                    f"checkpoint_dir {str(ck)!r} is already in use by "
                    "an open session — give each session its own "
                    "directory (a shared dir shares one generation "
                    "store, whose pruning would delete the other "
                    "session's checkpoints)"
                )
            self._ckpt_reserved.add(ckreal)
        return ckreal

    def _release_ckpt_dir(self, sid: str) -> None:
        with self._ckpt_lock:
            d = self._ckpt_by_sid.pop(sid, None)
            if d is not None:
                self._ckpt_reserved.discard(d)

    # -- per-connection protocol -----------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        handles: Dict[str, SessionHandle] = {}
        pending: Dict[str, List[Future]] = {}
        dropped: Dict[str, int] = {}  # failures pruned past the cap
        try:
            with conn, conn.makefile("rwb") as f:
                for raw in f:
                    line = raw.strip()
                    if not line:
                        continue
                    try:
                        reply = self._dispatch(
                            json.loads(line.decode("utf-8")), handles,
                            pending, dropped,
                        )
                    except Exception as e:  # noqa: BLE001 — protocol
                        # boundary: EVERY malformed request (bad
                        # base64, wrong types, unknown sessions, busy
                        # queues) answers a structured error; only a
                        # dead peer drops the connection.
                        reply = {
                            "ok": False,
                            "error": type(e).__name__,
                            "message": str(e),
                            "busy": isinstance(e, ServiceBusyError),
                            "overloaded": isinstance(
                                e, ServiceOverloadedError
                            ),
                        }
                    f.write(json.dumps(reply, default=float)
                            .encode("utf-8") + b"\n")
                    f.flush()
        except (OSError, json.JSONDecodeError):
            pass  # peer went away / sent garbage: drop the connection
        finally:
            # Connection-scoped sessions: a client that vanishes
            # without close must not leak its facades (device arrays)
            # into the scheduler ring forever. Best-effort drain-close
            # each one (writes the usual session_close checkpoint when
            # autosave is armed).
            for h in list(handles.values()):
                try:
                    fut = h.close()
                except (ServiceDrainingError, SessionClosedError):
                    # shutdown owns them now / already closed — the
                    # drain (or the earlier close) writes the
                    # checkpoint, so the reservation can go now.
                    self._release_ckpt_dir(h.id)
                else:
                    # close() only QUEUES the sentinel that writes the
                    # drain checkpoint — releasing the dir here would
                    # let a new open reuse it while that write is
                    # still in flight (two GenerationStores sharing a
                    # dir = mutual keep-prune data loss). Release when
                    # the close op actually resolves, either way.
                    fut.add_done_callback(
                        lambda _f, sid=h.id: self._release_ckpt_dir(sid)
                    )

    def _dispatch(self, req: dict, handles: Dict[str, SessionHandle],
                  pending: Dict[str, List[Future]],
                  dropped: Dict[str, int]) -> dict:
        op = req.get("op")
        if op == "ping":
            # Schema-pinned (tests/test_traffic.py): "load" is what the
            # router's least-loaded placement and the load generator
            # read — live queue depth + in-flight particle cost, not
            # open-session count.
            st = self.service.stats()
            adm = st["admission"]
            return {
                "ok": True,
                "draining": self.service.drain_requested,
                "load": {
                    "sessions": len(st["sessions"]),
                    "queued_cost": adm["queued_cost"],
                    "inflight_cost": adm["inflight_cost"],
                    "admitted_cost": adm["admitted_cost"],
                    "budget": adm["budget"],
                },
                "fusion": st["fusion"],
            }
        if op == "stats":
            # The full per-session snapshot (p50/p99 latency included);
            # ping stays the cheap aggregate.
            return {"ok": True, "stats": self.service.stats()}
        if op == "open":
            pr = req.get("priority")
            try:
                priority = (Priority.NORMAL if pr is None
                            else Priority[str(pr).upper()])
            except KeyError:
                raise ValueError(
                    f"unknown priority {pr!r}: expected one of "
                    f"{[p.name.lower() for p in Priority]}"
                ) from None
            ckreal = self._reserve_ckpt_dir(req.get("checkpoint_dir"))
            try:
                h = self.service.open_session(
                    self._build_tally(req),
                    max_queue=req.get("max_queue"),
                    priority=priority,
                )
            except BaseException:
                if ckreal is not None:
                    with self._ckpt_lock:
                        self._ckpt_reserved.discard(ckreal)
                raise
            if ckreal is not None:
                with self._ckpt_lock:
                    self._ckpt_by_sid[h.id] = ckreal
            handles[h.id] = h
            pending[h.id] = []
            return {"ok": True, "session": h.id}
        if op not in ("source", "move", "sync", "flux",
                      "normalized_flux", "health", "lost", "close_batch",
                      "finalize", "write", "close"):
            raise ValueError(f"unknown op {op!r}")
        h = handles[req["session"]]  # KeyError → error reply
        waitlist = pending[h.id]
        if op == "source":
            fut = h.copy_initial_position(
                _decode_array(req["positions"], _WIRE_F64)
            )
            return self._ack(fut, waitlist, dropped, h.id, req)
        if op == "move":
            def arr(key, dtype=_WIRE_F64):
                return (
                    None if key not in req
                    else _decode_array(req[key], dtype)
                )
            fut = h.move(
                arr("origins"), _decode_array(req["dests"], _WIRE_F64),
                flying=arr("flying", _WIRE_I8), weights=arr("weights"),
                energy=arr("energy"), time=arr("time"),
            )
            return self._ack(fut, waitlist, dropped, h.id, req)
        if op == "sync":
            return self._sync(waitlist, dropped, h.id)
        if op == "flux":
            return {"ok": True, "dtype": "float64",
                    "flux": _encode_array(h.flux().result())}
        if op == "normalized_flux":
            return {"ok": True, "dtype": "float64",
                    "flux": _encode_array(h.normalized_flux().result())}
        if op == "health":
            return {"ok": True, "health": h.health_report().result()
                    .as_dict()}
        if op == "lost":
            return {"ok": True,
                    "lost_particles": h.lost_particles().result()}
        if op == "close_batch":
            r = h.close_batch().result()
            out = {"ok": True}
            if r is not None:
                out["trigger"] = {
                    "converged": bool(r.converged),
                    "value": float(r.value),
                    "batches_remaining": r.batches_remaining,
                }
            return out
        if op == "finalize":
            h.finalize().result()
            return {"ok": True}
        if op == "write":
            if not self.allow_write:
                raise RuntimeError(
                    "write is disabled on this server (start with "
                    "allow_write / --allow-write to enable VTK output)"
                )
            h.write(req.get("filename")).result()
            return {"ok": True}
        # op == "close" (the allowlist above is exhaustive)
        fut = h.close()
        try:
            saved = fut.result()
        finally:
            # The session is closed/unregistered even when its drain
            # checkpoint failed (_finalize's finally) — drop the wire
            # bookkeeping and the dir reservation either way, so a
            # retry gets an honest "unknown session" instead of the
            # cached failure forever, and the dir is reusable.
            handles.pop(h.id, None)
            pending.pop(h.id, None)
            dropped.pop(h.id, None)
            self._release_ckpt_dir(h.id)
        return {"ok": True, "checkpoint": saved}

    # Resolved failures retained for the next "sync", per session. The
    # bound matters: without it a pipeline-forever driver whose session
    # persistently fails (e.g. a poisoned facade failing every move)
    # would grow the waitlist O(ops). Beyond the cap the OLDEST
    # resolved failures are dropped and counted; sync reports the
    # count. Unresolved futures are never dropped (their verdict isn't
    # known yet) and are bounded by the session queue depth anyway.
    _MAX_RETAINED_FAILURES = 32

    def _ack(self, fut: Future, waitlist: List[Future],
             dropped: Dict[str, int], sid: str, req: dict) -> dict:
        if req.get("wait", True):
            fut.result()  # raises → error reply path
            return {"ok": True}
        # Prune resolved SUCCESSFUL futures so a driver that pipelines
        # forever without ever sending "sync" stays bounded; failures
        # are retained (up to the cap above) for the next sync.
        waitlist[:] = [
            x for x in waitlist
            if not (x.done() and x.exception() is None)
        ]
        resolved = [x for x in waitlist if x.done()]
        overflow = len(resolved) - self._MAX_RETAINED_FAILURES + 1
        if overflow > 0:
            drop = set(id(x) for x in resolved[:overflow])
            waitlist[:] = [x for x in waitlist if id(x) not in drop]
            dropped[sid] = dropped.get(sid, 0) + len(drop)
        waitlist.append(fut)
        return {"ok": True, "queued": True}

    def _sync(self, waitlist: List[Future], dropped: Dict[str, int],
              sid: str) -> dict:
        # Await EVERY future before clearing: raising out of the loop
        # at the first failure would clear (and so silently discard)
        # any later failures still on the list — the one thing _ack's
        # retention promise forbids. One reply surfaces them all,
        # including the count of failures dropped past the cap.
        failures: List[BaseException] = []
        for fut in waitlist:
            e = fut.exception()
            if e is not None:
                failures.append(e)
        waitlist.clear()
        ndropped = dropped.pop(sid, 0)
        if failures or ndropped:
            if len(failures) == 1 and not ndropped:
                raise failures[0]
            parts = [f"{type(e).__name__}: {e}" for e in failures]
            if ndropped:
                parts.append(
                    f"(+{ndropped} earlier failures dropped past the "
                    f"{self._MAX_RETAINED_FAILURES}-entry retention cap)"
                )
            raise RuntimeError(
                f"{len(failures) + ndropped} pipelined ops failed: "
                + "; ".join(parts)
            )
        return {"ok": True}

    # -- session construction --------------------------------------------
    def _build_tally(self, req: dict):
        from pumiumtally_tpu import (
            CheckpointPolicy,
            PartitionedPumiTally,
            PumiTally,
            SentinelPolicy,
            StreamingTally,
            TallyConfig,
        )

        mesh = self._resolve_mesh(req.get("mesh"))
        n = int(req.get("num_particles", self.default_particles))
        kw: Dict[str, Any] = {
            # Serving default: no per-move convergence D2H sync (the
            # health op reports through the sentinel instead).
            "check_found_all": bool(req.get("check_found_all", False)),
        }
        if req.get("batch_stats"):
            kw["batch_stats"] = True
        if req.get("sentinel"):
            kw["sentinel"] = SentinelPolicy()
        if req.get("checkpoint_dir"):
            kw["checkpoint"] = CheckpointPolicy(
                dir=str(req["checkpoint_dir"]),
                every_n_batches=int(req.get("every_n_batches", 1)),
                keep=int(req.get("keep", 3)),
                handle_signals=False,  # the service owns the handler
            )
        facade = req.get("facade", "mono")
        if facade == "mono":
            return PumiTally(mesh, n, TallyConfig(**kw))
        if facade == "stream":
            return StreamingTally(
                mesh, n, chunk_size=int(req.get("chunk_size", 1 << 20)),
                config=TallyConfig(**kw),
            )
        if facade == "part":
            return PartitionedPumiTally(
                mesh, n,
                TallyConfig(capacity_factor=float(
                    req.get("capacity_factor", 4.0)
                ), **kw),
            )
        raise ValueError(
            f"unknown facade {facade!r} (mono/stream/part)"
        )

    def _resolve_mesh(self, spec):
        if spec is None:
            if self.default_mesh is None:
                raise ValueError(
                    "no mesh in the open request and the server has no "
                    "default mesh"
                )
            return self.default_mesh
        if "box" in spec:
            from pumiumtally_tpu import build_box

            lx, ly, lz, nx, ny, nz = spec["box"]
            key = (float(lx), float(ly), float(lz),
                   int(nx), int(ny), int(nz))
            # One mesh OBJECT per box spec, not per open: fusion keys
            # include the mesh's identity, so sessions opened with the
            # same box must share one mesh to ever co-fuse (they also
            # then share the walk table's device buffers). Meshes are
            # immutable; the cache only ever grows by distinct specs.
            with self._ckpt_lock:
                mesh = self._box_meshes.get(key)
            if mesh is None:
                built = build_box(*key)
                with self._ckpt_lock:
                    mesh = self._box_meshes.setdefault(key, built)
            return mesh
        if "path" in spec:
            if not self.allow_mesh_paths:
                raise ValueError(
                    "mesh-path loading is disabled on this server "
                    "(start with allow_mesh_paths / --allow-mesh-paths)"
                )
            return str(spec["path"])  # facades load .msh/.osh paths
        raise ValueError(f"unknown mesh spec {spec!r} (box/path)")


# ---------------------------------------------------------------------------
# Per-host service workers: the session router (round 13)
# ---------------------------------------------------------------------------

class SessionRouter:
    """Thin NDJSON routing front end over several per-host service
    workers — the horizontal form of the PR 10 service: each host (or
    process) runs its own ``TallyService`` + ``SocketFrontend`` against
    its local devices, and clients talk to ONE router address.

    Session-homing rule: a session's facade arrays live on the chips of
    exactly one worker, so every op for a session must land on the
    worker that opened it. The router pins each session to a home
    worker at ``open`` — the least-LOADED worker by live queue depth
    plus in-flight particle cost read over the ping channel (round 20;
    open-session count and worker index break ties, and a worker whose
    ping fails or predates the load schema falls back to the router's
    own session count) — or the request's ``"home": <index>`` hint —
    and forwards every subsequent op for that id there verbatim.
    Router session ids are ``"<home>:<worker-sid>"`` (rewritten in
    both directions), so a client can read its session's home from the
    id and the reply's ``"home"`` field.

    The protocol is byte-identical to ``SocketFrontend``'s per line —
    the router adds no ops and removes none; ``ping`` is answered with
    the aggregate (``draining`` true when ANY worker drains, the
    worker count, and the summed worker loads plus per-backend
    breakdown). One worker connection per client connection, opened
    lazily: the workers' per-connection session cleanup then makes a
    vanished client drop its sessions on every worker it touched, with
    no router-side bookkeeping.

    Trust model: same as ``SocketFrontend`` — no authentication, deploy
    inside the perimeter. Workers are typically ``pumiumtally serve``
    processes launched one per host by the job scheduler; the router is
    ``pumiumtally route --backend host:port ...``.
    """

    def __init__(self, backends, host: str = "127.0.0.1", port: int = 0,
                 *, connect_timeout: float = 10.0):
        if not backends:
            raise ValueError("SessionRouter needs at least one backend")
        self.backends = [(str(h), int(p)) for h, p in backends]
        self.connect_timeout = float(connect_timeout)
        self._srv = socket.create_server((host, int(port)))
        self._srv.settimeout(0.25)  # periodic _closing check (see
        # SocketFrontend.__init__ — same accept-loop liveness reasoning)
        self.host, self.port = self._srv.getsockname()[:2]
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._closing = False
        self._count_lock = threading.Lock()
        self._open_sessions = [0] * len(self.backends)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pumiumtally-route-accept",
            daemon=True,
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._srv.accept()
            except TimeoutError:
                continue
            except OSError:
                return
            conn.settimeout(None)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
            )
            t.start()
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # -- per-connection forwarding ---------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        files: Dict[int, Any] = {}  # backend idx -> rwb file
        socks: Dict[int, socket.socket] = {}
        owned: Dict[str, int] = {}  # router sid -> home backend idx
        try:
            with conn, conn.makefile("rwb") as f:
                for raw in f:
                    line = raw.strip()
                    if not line:
                        continue
                    try:
                        reply = self._route(
                            json.loads(line.decode("utf-8")), files,
                            socks, owned,
                        )
                    except Exception as e:  # noqa: BLE001 — protocol
                        # boundary, like SocketFrontend._serve_conn:
                        # every failure (bad session ids, dead workers,
                        # forwarded errors re-raised) answers
                        # structured; only a dead CLIENT drops the
                        # connection.
                        reply = {
                            "ok": False,
                            "error": type(e).__name__,
                            "message": str(e),
                            "busy": isinstance(e, ServiceBusyError),
                            "overloaded": isinstance(
                                e, ServiceOverloadedError
                            ),
                        }
                    f.write(json.dumps(reply, default=float)
                            .encode("utf-8") + b"\n")
                    f.flush()
        except (OSError, json.JSONDecodeError):
            pass  # peer went away / sent garbage
        finally:
            # Closing the worker connections is the whole cleanup: each
            # worker's own per-connection finally drain-closes the
            # sessions this client opened through it.
            with self._count_lock:
                for sid, b in owned.items():
                    self._open_sessions[b] -= 1
            for s in socks.values():
                try:
                    s.close()
                except OSError:
                    pass

    def _backend_file(self, idx: int, files: Dict[int, Any],
                      socks: Dict[int, socket.socket]):
        if idx not in files:
            s = socket.create_connection(
                self.backends[idx], timeout=self.connect_timeout
            )
            s.settimeout(None)  # ops block until the worker replies
            socks[idx] = s
            files[idx] = s.makefile("rwb")
        return files[idx]

    def _forward(self, idx: int, req: dict, files, socks) -> dict:
        f = self._backend_file(idx, files, socks)
        f.write(json.dumps(req, default=float).encode("utf-8") + b"\n")
        f.flush()
        line = f.readline()
        if not line:
            raise RuntimeError(
                f"worker {idx} ({self.backends[idx][0]}:"
                f"{self.backends[idx][1]}) closed the connection"
            )
        return json.loads(line.decode("utf-8"))

    def _least_loaded(self, files, socks) -> int:
        """Open-time placement by LIVE load (round 20): score each
        worker ``(queued + in-flight transport cost, open sessions,
        index)`` read over the ping channel, pick the minimum — a
        backlogged worker stops winning opens even when its session
        COUNT is lowest (sessions are cheap; queued particles are
        not). A worker whose ping fails, or an older worker whose ping
        reply has no ``"load"`` yet, falls back to the router's own
        open-session count at zero cost, so a mixed or half-down fleet
        still places (the open itself will surface a dead worker)."""
        best = None
        for i in range(len(self.backends)):
            try:
                ld = self._forward(
                    i, {"op": "ping"}, files, socks
                ).get("load") or {}
            except (OSError, RuntimeError, ValueError):
                ld = {}
            with self._count_lock:
                fallback_sessions = self._open_sessions[i]
            score = (
                int(ld.get("queued_cost", 0))
                + int(ld.get("inflight_cost", 0)),
                int(ld.get("sessions", fallback_sessions)),
                i,
            )
            if best is None or score < best:
                best = score
        return best[2]

    def _home_of(self, sid: str) -> tuple:
        b, sep, rest = str(sid).partition(":")
        if not sep or not b.isdigit() or int(b) >= len(self.backends):
            raise ValueError(
                f"unknown session {sid!r} (router ids look like "
                f"'<home>:<worker-sid>' with home < "
                f"{len(self.backends)})"
            )
        return int(b), rest

    def _route(self, req: dict, files, socks, owned: Dict[str, int]
               ) -> dict:
        op = req.get("op")
        if op == "ping":
            # Aggregate health: draining when ANY worker drains (a
            # drain anywhere means new opens may land on a draining
            # host — clients should stop submitting). Worker loads are
            # summed and returned per backend too, so a load generator
            # pointed at the router reads fleet-wide telemetry from
            # one socket.
            draining = False
            per_backend = []
            load = {"sessions": 0, "queued_cost": 0, "inflight_cost": 0}
            for i in range(len(self.backends)):
                r = self._forward(i, {"op": "ping"}, files, socks)
                draining = draining or bool(r.get("draining"))
                ld = r.get("load") or {}
                per_backend.append(ld)
                for k in load:
                    load[k] += int(ld.get(k, 0))
            return {"ok": True, "draining": draining,
                    "backends": len(self.backends),
                    "load": load, "per_backend": per_backend}
        if op == "open":
            home = req.pop("home", None)
            if home is None:
                home = self._least_loaded(files, socks)
            home = int(home)
            if not 0 <= home < len(self.backends):
                raise ValueError(
                    f"home {home} out of range (have "
                    f"{len(self.backends)} workers)"
                )
            reply = self._forward(home, req, files, socks)
            if reply.get("ok") and "session" in reply:
                sid = f"{home}:{reply['session']}"
                owned[sid] = home
                with self._count_lock:
                    self._open_sessions[home] += 1
                reply = dict(reply, session=sid, home=home)
            return reply
        # Every other op carries a session id: forward to its home.
        home, worker_sid = self._home_of(req.get("session"))
        reply = self._forward(
            home, dict(req, session=worker_sid), files, socks,
        )
        if op == "close" and reply.get("ok"):
            sid = f"{home}:{worker_sid}"
            if owned.pop(sid, None) is not None:
                with self._count_lock:
                    self._open_sessions[home] -= 1
        return reply
