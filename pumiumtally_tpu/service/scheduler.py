"""Deficit-round-robin scheduling over ready sessions, in priority lanes.

The service owns ONE device and many sessions; something must decide
whose staged op runs next. Plain round-robin is fair in op COUNT but
not in work: a session moving 500k particles per op would get 500k
particle-moves for every 4k a small session gets per turn. Deficit
round robin (Shreedhar & Varghese) fixes that with one counter per
session:

- sessions sit on a ring, visited in registration order;
- each visit credits the session's deficit counter with a QUANTUM of
  cost units; its head op runs iff its cost fits the accumulated
  deficit (cost = particles touched for transport ops, 1 for reads —
  staging.StagedOp.cost);
- a served op's cost is debited; the visit continues on the same
  session while further heads fit, then moves on;
- a session whose queue empties forfeits its banked CREDIT (the
  classic DRR reset — idle time banks no credit, so a bursty client
  cannot starve the ring with saved-up quantum). Owed DEBT — a
  negative deficit from ``pick_group``'s co-fused pre-payment — is
  kept: a session that rides fused launches and then empties still
  pays before its next lead service.

Priority lanes (round 20): every session registers into one of the
``Priority`` lanes (HIGH/NORMAL/LOW — paying traffic over batch
campaigns, per ROADMAP item 1). The pick is STRICT priority between
lanes — the highest lane with queued work serves, lower lanes wait —
and deficit round robin within a lane, each lane keeping its own ring
cursor and visit state over the one shared deficit ledger. A skipped
idle lane forfeits banked credit exactly like an empty ring visit
(debt stays). Single-lane services (everything registered NORMAL, the
default) behave bit-identically to the flat scheduler.

Starvation is bounded by construction, not by lane weights: a LOW
session whose head is fusion-compatible with a HIGH lead still rides
the shared launch through ``pick_group`` (co-fusion scans lanes in
priority order but never excludes one), pre-paying its own cost — so
under a saturated high lane, compatible low-lane work advances at the
fused cadence while incompatible low-lane work waits for the high
lane to drain (tests/test_traffic.py pins both halves).

Fairness contract (docs/DESIGN.md "Multi-session service"): over any
window in which a set of SAME-LANE sessions stays backlogged and
their lane serves, the cost served to any two of them differs by at
most one quantum plus one maximal op cost — O(1) unfairness,
independent of queue depths, so one hot client cannot starve its
lane. With the default AUTO quantum (the largest head cost currently
queued in the serving lane) every visited backlogged session serves
at least one op per ring pass, which also makes ``pick``
work-conserving in a single pass.

The scheduler is a plain synchronous data structure — the service
calls it under its own lock; nothing here blocks, allocates device
memory, or touches jax.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, List, Optional


class Priority(enum.IntEnum):
    """Strict-priority service lanes (lower value = more urgent). The
    lane is fixed at ``open_session``; DRR fairness applies within a
    lane, lanes preempt at op granularity (an in-flight op always
    finishes — preemption-safe by construction)."""

    HIGH = 0
    NORMAL = 1
    LOW = 2


class _Lane:
    """One priority lane's ring state (deficits live in the scheduler's
    shared ledger — a session's debt follows it across fused rides
    regardless of which lane led the launch)."""

    __slots__ = ("keys", "cursor", "visiting")

    def __init__(self) -> None:
        self.keys: List[str] = []
        self.cursor = 0
        self.visiting: Optional[str] = None


class DeficitRoundRobinScheduler:
    """Strict-priority + DRR picker over registered session keys.

    Args:
      quantum: cost units credited per visit. None (default) = auto:
        the largest head cost among the serving lane's currently
        backlogged sessions, re-derived each pick — guarantees
        one-pass work conservation while keeping service
        work-proportional when op costs differ.
    """

    def __init__(self, quantum: Optional[int] = None):
        if quantum is not None and int(quantum) < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum!r}")
        self._quantum = None if quantum is None else int(quantum)
        self._lanes = {p: _Lane() for p in Priority}
        self._lane_of: dict = {}
        self._order: List[str] = []  # overall registration order
        self._deficit: dict = {}

    # -- membership ------------------------------------------------------
    def register(self, key: str,
                 priority: Priority = Priority.NORMAL) -> None:
        if key in self._deficit:
            raise ValueError(f"session {key!r} already registered")
        pr = Priority(priority)
        self._lanes[pr].keys.append(key)
        self._lane_of[key] = pr
        self._order.append(key)
        self._deficit[key] = 0

    def unregister(self, key: str) -> None:
        pr = self._lane_of.get(key)
        if pr is None:
            raise ValueError(f"session {key!r} is not registered")
        lane = self._lanes[pr]
        idx = lane.keys.index(key)
        lane.keys.pop(idx)
        del self._lane_of[key]
        self._order.remove(key)
        del self._deficit[key]
        if lane.visiting == key:
            lane.visiting = None
        if idx < lane.cursor:
            lane.cursor -= 1
        if lane.keys:
            lane.cursor %= len(lane.keys)
        else:
            lane.cursor = 0

    @property
    def keys(self) -> tuple:
        return tuple(self._order)

    def deficit(self, key: str) -> int:
        return self._deficit[key]

    def priority(self, key: str) -> Priority:
        return self._lane_of[key]

    # -- picking ---------------------------------------------------------
    def pick(
        self, head_cost: Callable[[str], Optional[int]]
    ) -> Optional[str]:
        """The key whose head op should run next, charging its cost.

        ``head_cost(key)`` returns the session's head-op cost, or None
        when it has nothing queued. Returns None iff no session has
        work. The caller must then actually pop and run that head op —
        pick() has already debited it.
        """
        if not self._order:
            return None
        costs = {k: head_cost(k) for k in self._order}
        serving = None
        for pr in Priority:
            lane = self._lanes[pr]
            if any(costs[k] is not None for k in lane.keys):
                serving = lane
                break
        if serving is None:
            for lane in self._lanes.values():
                lane.visiting = None
            return None
        # Lanes ABOVE the serving one are idle by construction of the
        # scan: forfeit their banked credit (idle banks no credit —
        # the empty-ring-visit rule), keep co-fusion debt.
        for pr in Priority:
            lane = self._lanes[pr]
            if lane is serving:
                break
            for k in lane.keys:
                self._deficit[k] = min(0, self._deficit[k])
            lane.visiting = None
        return self._pick_in_lane(serving, costs)

    def _pick_in_lane(self, lane: _Lane, costs: dict) -> Optional[str]:
        """Classic DRR over one lane's ring (the flat round-11
        algorithm verbatim, scoped to the lane's keys/cursor/visit)."""
        n = len(lane.keys)
        backlogged = [
            costs[k] for k in lane.keys if costs[k] is not None
        ]
        quantum = self._quantum
        if quantum is None:
            quantum = max(1, max(backlogged))
        # Continue the in-progress visit first: classic DRR serves one
        # queue until its deficit is spent, THEN moves the ring.
        if lane.visiting is not None:
            k = lane.visiting
            c = costs.get(k)
            if c is not None and c <= self._deficit[k]:
                self._deficit[k] -= c
                return k
            if c is None and k in self._deficit:
                # Emptied: forfeit banked CREDIT only. A negative
                # deficit is debt from pick_group's co-fused
                # pre-payment — zeroing it would let a session that
                # empties between submissions ride fused launches
                # without ever being charged.
                self._deficit[k] = min(0, self._deficit[k])
            lane.visiting = None
        # Ring scan. With auto quantum the first backlogged session
        # serves immediately; with a small manual quantum the deficit
        # accumulates across passes until a head fits. An unserved
        # full pass jumps the deficit clock ARITHMETICALLY (every
        # backlogged session is about to receive the same m quanta
        # anyway, in ring order — crediting m-1 of them in bulk
        # changes nothing but skips O(cost/quantum) spin passes under
        # the service lock).
        while True:
            served_none = True
            for _ in range(n):
                k = lane.keys[lane.cursor]
                lane.cursor = (lane.cursor + 1) % n
                c = costs[k]
                if c is None:
                    # Credit forfeits on empty; co-fusion debt stays
                    # (see the visit-continuation branch above).
                    self._deficit[k] = min(0, self._deficit[k])
                    continue
                self._deficit[k] += quantum
                if c <= self._deficit[k]:
                    self._deficit[k] -= c
                    lane.visiting = k
                    return k
                served_none = False  # backlogged, not yet affordable
            if served_none:
                # Only emptied queues were seen this pass (cannot
                # happen: backlogged was non-empty and costs are
                # fixed for this pick) — guard against livelock.
                return None
            passes_needed = min(
                -(-(costs[k] - self._deficit[k]) // quantum)
                for k in lane.keys if costs[k] is not None
            )
            if passes_needed > 1:
                for k in lane.keys:
                    if costs[k] is not None:
                        self._deficit[k] += (passes_needed - 1) * quantum

    def pick_group(
        self,
        head_cost: Callable[[str], Optional[int]],
        group_key: Callable[[str], Optional[Any]],
        max_group: int,
    ) -> Optional[List[str]]:
        """The fusion window (round 12): one DRR pick, then up to
        ``max_group - 1`` more sessions whose queued heads are
        COMPATIBLE with it — ``group_key(sid)`` returns the head's
        fusion key, or None for a head that must run alone (non-move
        ops, non-fusable facades, empty queues).

        Fairness accounting is unchanged in its bounds: the lead pick
        goes through ``pick`` (quantum credits, deficit debit, visit
        continuation), and every co-fused session is charged ITS OWN
        head cost against its deficit — early service is pre-paid
        service, so over any backlogged window the cost served per
        session still tracks the deficit clock within one quantum plus
        one maximal op cost. Co-fused members are scanned lane-major
        (priority order, ring/registration order within a lane), so
        group composition is deterministic given the queue states —
        and a LOWER-lane session with a compatible head deliberately
        rides a higher lead's launch (pre-paying): that ride-along is
        the low lane's starvation bound under a saturated high lane.
        Returns None iff no session has work; the caller must pop and
        run every returned head (their costs are already debited)."""
        lead = self.pick(head_cost)
        if lead is None:
            return None
        group = [lead]
        if int(max_group) <= 1:
            return group
        key = group_key(lead)
        if key is None:
            return group
        for pr in Priority:
            for k in self._lanes[pr].keys:
                if len(group) >= int(max_group):
                    return group
                if k == lead:
                    continue
                c = head_cost(k)
                if c is None or group_key(k) != key:
                    continue
                self._deficit[k] -= int(c)
                group.append(k)
        return group
