"""Deficit-round-robin scheduling over ready sessions.

The service owns ONE device and many sessions; something must decide
whose staged op runs next. Plain round-robin is fair in op COUNT but
not in work: a session moving 500k particles per op would get 500k
particle-moves for every 4k a small session gets per turn. Deficit
round robin (Shreedhar & Varghese) fixes that with one counter per
session:

- sessions sit on a ring, visited in registration order;
- each visit credits the session's deficit counter with a QUANTUM of
  cost units; its head op runs iff its cost fits the accumulated
  deficit (cost = particles touched for transport ops, 1 for reads —
  staging.StagedOp.cost);
- a served op's cost is debited; the visit continues on the same
  session while further heads fit, then moves on;
- a session whose queue empties forfeits its banked CREDIT (the
  classic DRR reset — idle time banks no credit, so a bursty client
  cannot starve the ring with saved-up quantum). Owed DEBT — a
  negative deficit from ``pick_group``'s co-fused pre-payment — is
  kept: a session that rides fused launches and then empties still
  pays before its next lead service.

Fairness contract (docs/DESIGN.md "Multi-session service"): over any
window in which a set of sessions stays backlogged, the cost served to
any two of them differs by at most one quantum plus one maximal op
cost — O(1) unfairness, independent of queue depths, so one hot client
cannot starve the rest. With the default AUTO quantum (the largest
head cost currently queued) every visited backlogged session serves at
least one op per ring pass, which also makes ``pick`` work-conserving
in a single pass.

The scheduler is a plain synchronous data structure — the service
calls it under its own lock; nothing here blocks, allocates device
memory, or touches jax.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class DeficitRoundRobinScheduler:
    """DRR picker over registered session keys.

    Args:
      quantum: cost units credited per visit. None (default) = auto:
        the largest head cost among currently backlogged sessions,
        re-derived each pick — guarantees one-pass work conservation
        while keeping service work-proportional when op costs differ.
    """

    def __init__(self, quantum: Optional[int] = None):
        if quantum is not None and int(quantum) < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum!r}")
        self._quantum = None if quantum is None else int(quantum)
        self._keys: List[str] = []
        self._deficit: dict = {}
        self._cursor = 0
        self._visiting: Optional[str] = None

    # -- membership ------------------------------------------------------
    def register(self, key: str) -> None:
        if key in self._deficit:
            raise ValueError(f"session {key!r} already registered")
        self._keys.append(key)
        self._deficit[key] = 0

    def unregister(self, key: str) -> None:
        idx = self._keys.index(key)
        self._keys.pop(idx)
        del self._deficit[key]
        if self._visiting == key:
            self._visiting = None
        if idx < self._cursor:
            self._cursor -= 1
        if self._keys:
            self._cursor %= len(self._keys)
        else:
            self._cursor = 0

    @property
    def keys(self) -> tuple:
        return tuple(self._keys)

    def deficit(self, key: str) -> int:
        return self._deficit[key]

    # -- picking ---------------------------------------------------------
    def pick(
        self, head_cost: Callable[[str], Optional[int]]
    ) -> Optional[str]:
        """The key whose head op should run next, charging its cost.

        ``head_cost(key)`` returns the session's head-op cost, or None
        when it has nothing queued. Returns None iff no session has
        work. The caller must then actually pop and run that head op —
        pick() has already debited it.
        """
        n = len(self._keys)
        if n == 0:
            return None
        costs = {k: head_cost(k) for k in self._keys}
        backlogged = [c for c in costs.values() if c is not None]
        if not backlogged:
            self._visiting = None
            return None
        quantum = self._quantum
        if quantum is None:
            quantum = max(1, max(backlogged))
        # Continue the in-progress visit first: classic DRR serves one
        # queue until its deficit is spent, THEN moves the ring.
        if self._visiting is not None:
            k = self._visiting
            c = costs.get(k)
            if c is not None and c <= self._deficit[k]:
                self._deficit[k] -= c
                return k
            if c is None and k in self._deficit:
                # Emptied: forfeit banked CREDIT only. A negative
                # deficit is debt from pick_group's co-fused
                # pre-payment — zeroing it would let a session that
                # empties between submissions ride fused launches
                # without ever being charged.
                self._deficit[k] = min(0, self._deficit[k])
            self._visiting = None
        # Ring scan. With auto quantum the first backlogged session
        # serves immediately; with a small manual quantum the deficit
        # accumulates across passes until a head fits. An unserved
        # full pass jumps the deficit clock ARITHMETICALLY (every
        # backlogged session is about to receive the same m quanta
        # anyway, in ring order — crediting m-1 of them in bulk
        # changes nothing but skips O(cost/quantum) spin passes under
        # the service lock).
        while True:
            served_none = True
            for _ in range(n):
                k = self._keys[self._cursor]
                self._cursor = (self._cursor + 1) % n
                c = costs[k]
                if c is None:
                    # Credit forfeits on empty; co-fusion debt stays
                    # (see the visit-continuation branch above).
                    self._deficit[k] = min(0, self._deficit[k])
                    continue
                self._deficit[k] += quantum
                if c <= self._deficit[k]:
                    self._deficit[k] -= c
                    self._visiting = k
                    return k
                served_none = False  # backlogged but not yet affordable
            if served_none:
                # Only emptied queues were seen this pass (cannot
                # happen: backlogged was non-empty and costs are
                # fixed for this pick) — guard against livelock.
                return None
            passes_needed = min(
                -(-(costs[k] - self._deficit[k]) // quantum)
                for k in self._keys if costs[k] is not None
            )
            if passes_needed > 1:
                for k in self._keys:
                    if costs[k] is not None:
                        self._deficit[k] += (passes_needed - 1) * quantum

    def pick_group(
        self,
        head_cost: Callable[[str], Optional[int]],
        group_key: Callable[[str], Optional[Any]],
        max_group: int,
    ) -> Optional[List[str]]:
        """The fusion window (round 12): one DRR pick, then up to
        ``max_group - 1`` more sessions whose queued heads are
        COMPATIBLE with it — ``group_key(sid)`` returns the head's
        fusion key, or None for a head that must run alone (non-move
        ops, non-fusable facades, empty queues).

        Fairness accounting is unchanged in its bounds: the lead pick
        goes through ``pick`` (quantum credits, deficit debit, visit
        continuation), and every co-fused session is charged ITS OWN
        head cost against its deficit — early service is pre-paid
        service, so over any backlogged window the cost served per
        session still tracks the deficit clock within one quantum plus
        one maximal op cost. Co-fused members are scanned in
        registration (ring) order, so group composition is
        deterministic given the queue states. Returns None iff no
        session has work; the caller must pop and run every returned
        head (their costs are already debited)."""
        lead = self.pick(head_cost)
        if lead is None:
            return None
        group = [lead]
        if int(max_group) <= 1:
            return group
        key = group_key(lead)
        if key is None:
            return group
        for k in self._keys:
            if len(group) >= int(max_group):
                break
            if k == lead:
                continue
            c = head_cost(k)
            if c is None or group_key(k) != key:
                continue
            self._deficit[k] -= int(c)
            group.append(k)
        return group
