"""Deficit-round-robin scheduling over ready sessions.

The service owns ONE device and many sessions; something must decide
whose staged op runs next. Plain round-robin is fair in op COUNT but
not in work: a session moving 500k particles per op would get 500k
particle-moves for every 4k a small session gets per turn. Deficit
round robin (Shreedhar & Varghese) fixes that with one counter per
session:

- sessions sit on a ring, visited in registration order;
- each visit credits the session's deficit counter with a QUANTUM of
  cost units; its head op runs iff its cost fits the accumulated
  deficit (cost = particles touched for transport ops, 1 for reads —
  staging.StagedOp.cost);
- a served op's cost is debited; the visit continues on the same
  session while further heads fit, then moves on;
- a session whose queue empties forfeits its deficit (the classic DRR
  reset — idle time banks no credit, so a bursty client cannot starve
  the ring with saved-up quantum).

Fairness contract (docs/DESIGN.md "Multi-session service"): over any
window in which a set of sessions stays backlogged, the cost served to
any two of them differs by at most one quantum plus one maximal op
cost — O(1) unfairness, independent of queue depths, so one hot client
cannot starve the rest. With the default AUTO quantum (the largest
head cost currently queued) every visited backlogged session serves at
least one op per ring pass, which also makes ``pick`` work-conserving
in a single pass.

The scheduler is a plain synchronous data structure — the service
calls it under its own lock; nothing here blocks, allocates device
memory, or touches jax.
"""

from __future__ import annotations

from typing import Callable, List, Optional


class DeficitRoundRobinScheduler:
    """DRR picker over registered session keys.

    Args:
      quantum: cost units credited per visit. None (default) = auto:
        the largest head cost among currently backlogged sessions,
        re-derived each pick — guarantees one-pass work conservation
        while keeping service work-proportional when op costs differ.
    """

    def __init__(self, quantum: Optional[int] = None):
        if quantum is not None and int(quantum) < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum!r}")
        self._quantum = None if quantum is None else int(quantum)
        self._keys: List[str] = []
        self._deficit: dict = {}
        self._cursor = 0
        self._visiting: Optional[str] = None

    # -- membership ------------------------------------------------------
    def register(self, key: str) -> None:
        if key in self._deficit:
            raise ValueError(f"session {key!r} already registered")
        self._keys.append(key)
        self._deficit[key] = 0

    def unregister(self, key: str) -> None:
        idx = self._keys.index(key)
        self._keys.pop(idx)
        del self._deficit[key]
        if self._visiting == key:
            self._visiting = None
        if idx < self._cursor:
            self._cursor -= 1
        if self._keys:
            self._cursor %= len(self._keys)
        else:
            self._cursor = 0

    @property
    def keys(self) -> tuple:
        return tuple(self._keys)

    def deficit(self, key: str) -> int:
        return self._deficit[key]

    # -- picking ---------------------------------------------------------
    def pick(
        self, head_cost: Callable[[str], Optional[int]]
    ) -> Optional[str]:
        """The key whose head op should run next, charging its cost.

        ``head_cost(key)`` returns the session's head-op cost, or None
        when it has nothing queued. Returns None iff no session has
        work. The caller must then actually pop and run that head op —
        pick() has already debited it.
        """
        n = len(self._keys)
        if n == 0:
            return None
        costs = {k: head_cost(k) for k in self._keys}
        backlogged = [c for c in costs.values() if c is not None]
        if not backlogged:
            self._visiting = None
            return None
        quantum = self._quantum
        if quantum is None:
            quantum = max(1, max(backlogged))
        # Continue the in-progress visit first: classic DRR serves one
        # queue until its deficit is spent, THEN moves the ring.
        if self._visiting is not None:
            k = self._visiting
            c = costs.get(k)
            if c is not None and c <= self._deficit[k]:
                self._deficit[k] -= c
                return k
            if c is None and k in self._deficit:
                self._deficit[k] = 0  # emptied: forfeit banked credit
            self._visiting = None
        # Ring scan. With auto quantum the first backlogged session
        # serves immediately; with a small manual quantum the deficit
        # accumulates across passes until a head fits. An unserved
        # full pass jumps the deficit clock ARITHMETICALLY (every
        # backlogged session is about to receive the same m quanta
        # anyway, in ring order — crediting m-1 of them in bulk
        # changes nothing but skips O(cost/quantum) spin passes under
        # the service lock).
        while True:
            served_none = True
            for _ in range(n):
                k = self._keys[self._cursor]
                self._cursor = (self._cursor + 1) % n
                c = costs[k]
                if c is None:
                    self._deficit[k] = 0
                    continue
                self._deficit[k] += quantum
                if c <= self._deficit[k]:
                    self._deficit[k] -= c
                    self._visiting = k
                    return k
                served_none = False  # backlogged but not yet affordable
            if served_none:
                # Only emptied queues were seen this pass (cannot
                # happen: backlogged was non-empty and costs are
                # fixed for this pick) — guard against livelock.
                return None
            passes_needed = min(
                -(-(costs[k] - self._deficit[k]) // quantum)
                for k in self._keys if costs[k] is not None
            )
            if passes_needed > 1:
                for k in self._keys:
                    if costs[k] is not None:
                        self._deficit[k] += (passes_needed - 1) * quantum
