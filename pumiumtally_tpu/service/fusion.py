"""Cross-session batch fusion: coalesced device launches (round 12).

The paper's premise is amortization — ship particle batches to the
device and walk them in one bulk pass instead of paying per-particle
host overhead. The service layer (round 11) reintroduced that overhead
one level up: ONE facade call per session per dispatch, each paying
its own staging pass and its own device launch, with a CPU-measured
~30% serving tax. Under heavy traffic most sessions run the SAME
jitted programs — so their queued moves should share one launch.

This module is that dispatch-amortization layer:

- the worker hands ``run_group`` the head MOVES of several sessions
  the scheduler grouped by **fusion key** (``PumiTally._fusion_key``:
  mesh identity + facade kind + the static walk/scoring configuration
  — i.e. sessions whose moves already lower to byte-identical HLO);
- each facade stages its move WITHOUT mutating state
  (``_fused_move_stage``), the host buffers pack into one padded
  particle slab (total rows rounded up to a power of two; dead
  padding rows carry ``in_flight=0`` / ``dest=x`` and retire on the
  walk's first iteration with zero contribution — the walk's existing
  done-mask semantics), and ONE jitted program (entry point
  ``"walk_fused"``) concatenates the sessions' committed state, runs
  ONE ``move_step`` over the slab, and scatters every session's
  flux / scoring-bank contribution back to its own banks through the
  walk's segmented-commit hook (``walk(tally_seg=)`` — the scoring
  bank's fused deterministic scatter contract from round 10 is the
  template: per-session index offsets ride the walk as never-permuted
  walk-constant rows);
- each facade then commits its slice (``_fused_move_commit``) — the
  solo move's post-walk sequence (sentinel audit, counters, fence,
  timing, resilience hook) runs per-session, after the shared launch.

Round 20 extends the window past the monolithic facade: compatible
``StreamingTally`` sessions (same chunk grid, pinned by their
``"stream"``-kinded fusion key) fuse CHUNK-WISE — one shared launch
per chunk index through the SAME ``walk_fused`` program
(``_pack_and_launch_stream``), preserving the solo pipeline's
staging/walk overlap K-sessions wide. Monolithic and streaming heads
never mix: their keys differ in kind.

Determinism (the service's core contract, extended): a session's
fused campaign output is BITWISE the solo run. Per-particle outputs
are independent arithmetic; for the accumulated banks, a session's
particles keep their relative row order through every stable stage
partition of the cascade, other sessions' updates land in other bank
segments, padding rows drop at the scatter, and a done particle's
extra-iteration updates add exact (sign-safe) zeros — so each bank
segment sees the bit-identical addition sequence a solo walk commits
(docs/DESIGN.md "Cross-session fusion"; pinned by
tests/test_fusion.py).

Failure containment: a session whose stage step refuses (poisoned
engine, move before source) gets the error on ITS future and leaves
the group; a failing shared launch falls back to solo execution per
session (warned — the futures then resolve exactly as unfused ops
would); a failing per-session commit lands on that session's future
while the other sessions' results commit.
"""

from __future__ import annotations

import time
import warnings
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu.api.streaming import FusedStreamStage
from pumiumtally_tpu.api.tally import move_step, move_step_continue
from pumiumtally_tpu.service import staging
from pumiumtally_tpu.utils.profiling import register_entry_point


def padded_total(n: int) -> int:
    """Slab row count for ``n`` staged particles: the next power of
    two (equal-sized pow2 sessions pack with ZERO dead rows — the
    serving sweet spot the A/B measures). Dead rows cost one walk
    iteration each and vanish at the first compaction boundary."""
    return 1 << max(0, int(n) - 1).bit_length()


def _fused_move_impl(mesh, xs, elems, fluxes, banks, sbins, sfacs,
                     dests, fly, w, origins, *, spans, pad,
                     use_committed, tol, max_iters, walk_kw,
                     score_kinds, stride):
    """ONE launch for K sessions' head moves.

    Per-session arrays arrive as tuples (``xs``/``elems``/``fluxes``
    and, with scoring armed, ``banks``/``sbins``/``sfacs``); the
    staged inputs arrive as already-packed ``[P]``-row slabs. All
    structure (``spans``, ``pad``, ``use_committed``, the walk
    statics) is static, so one group composition is one cache key.

    The committed state concatenates INSIDE the program, the walk runs
    with the segmented flux commit (segment k at ``k·E + elem``;
    padding at ``K·E`` → dropped) and, when scoring is armed, with
    per-session bin offsets pre-shifted by ``k·E·stride`` (the
    session-local DROP sentinel ``>= stride`` remaps to the fused
    bank's end so it still drops instead of landing in a neighbour's
    segment). Returns one ``(x, elem, flux, done, s, bank)`` slice per
    session.
    """
    E = fluxes[0].shape[0]
    K = len(spans)
    fdtype = xs[0].dtype

    def cat(parts, pad_part):
        return jnp.concatenate(
            list(parts) + ([pad_part] if pad else []), axis=0
        )

    x = cat(xs, jnp.zeros((pad, 3), fdtype))
    elem = cat(elems, jnp.zeros((pad,), jnp.int32))
    flux = jnp.concatenate(list(fluxes))
    seg = cat(
        [jnp.full((spans[k],), k * E, jnp.int32) for k in range(K)],
        jnp.full((pad,), K * E, jnp.int32),
    )
    score_kw = {}
    if score_kinds:
        bank = jnp.concatenate(list(banks))
        drop = jnp.asarray(K * E * stride, jnp.int32)
        sbin = cat(
            [
                jnp.where(
                    sbins[k] >= stride, drop,
                    sbins[k] + jnp.asarray(k * E * stride, jnp.int32),
                )
                for k in range(K)
            ],
            jnp.full((pad,), drop),
        )
        sfac = cat(sfacs, jnp.zeros((pad, len(score_kinds)), fdtype))
        score_kw = {"score_kinds": score_kinds,
                    "score_ops": (bank, sbin, sfac)}
    kw = dict(tol=tol, max_iters=max_iters, walk_kw=walk_kw,
              tally_seg=seg, **score_kw)
    if all(use_committed):
        # Every session continues from its committed state: the fused
        # program is phase B only, exactly like each solo move.
        res = move_step_continue(mesh, x, elem, dests, fly, w, flux,
                                 **kw)
    else:
        # Sessions without staged origins synthesize origins == their
        # committed positions: phase A walks zero distance for those
        # rows and leaves their (x, elem) bitwise unchanged, so the
        # follow-up phase B matches their solo continue-mode move.
        parts = []
        a = 0
        for k in range(K):
            parts.append(
                xs[k] if use_committed[k] else origins[a:a + spans[k]]
            )
            a += spans[k]
        org = cat(parts, jnp.zeros((pad, 3), fdtype))
        res = move_step(mesh, x, elem, org, dests, fly, w, flux, **kw)
    if score_kinds:
        x2, elem2, flux2, done, s_b, bank2 = res
    else:
        x2, elem2, flux2, done, s_b = res
        bank2 = None
    out = []
    a = 0
    for k in range(K):
        n_k = spans[k]
        out.append((
            x2[a:a + n_k], elem2[a:a + n_k],
            flux2[k * E:(k + 1) * E], done[a:a + n_k], s_b[a:a + n_k],
            None if bank2 is None
            else bank2[k * E * stride:(k + 1) * E * stride],
        ))
        a += n_k
    return tuple(out)


_fused_move = register_entry_point(
    "walk_fused",
    partial(
        jax.jit,
        static_argnames=("spans", "pad", "use_committed", "tol",
                         "max_iters", "walk_kw", "score_kinds",
                         "stride"),
    )(_fused_move_impl),
)


def _run_solo(live) -> bool:
    """Execute staged-but-not-launched moves one at a time through the
    normal facade path (the stage step mutated nothing, so the full
    ``MoveToNextLocation`` replays cleanly — with the worker's own
    containment, shared via ``staging.run_op_contained``). The
    fallback for a failed pack/launch and for groups that shrank to
    one live session — errors then land on exactly the failing
    session's future, as unfused ops' do."""
    drain = False
    for sess, op, _st in live:
        drain = staging.run_op_contained(sess.tally, op) or drain
    return drain


def run_group(items: List[Tuple]) -> Tuple[bool, int, int]:
    """Execute one fused group: ``items`` is a list of
    ``(session, StagedOp)`` move heads sharing one fusion key (the
    worker popped them under the lock in one round trip). Resolves
    every op's future (result None, like a solo move, or its own
    exception). Returns ``(drain, coalesced, solo_ran)``:

    - ``drain``: a facade's resilience hook raised SystemExit — the
      worker folds it into a service-wide drain, exactly as for solo
      ops;
    - ``coalesced``: moves that actually went through the ONE shared
      launch; ``solo_ran``: moves executed one launch at a time (the
      fallback paths). The worker's ``fusion_stats`` — what the A/B's
      dispatches-per-move is computed from — count these honestly: a
      fallback is K dispatches, not one, and a staged op that refused
      before any launch dispatched nothing."""
    t0 = time.perf_counter()
    live = []
    for sess, op in items:
        try:
            st = sess.tally._fused_move_stage(op)
        except BaseException as e:  # noqa: BLE001 — a stage refusal is
            # that session's own error (poisoned engine, move before
            # source); it leaves the group, the rest still fuse.
            op.future.set_exception(e)
        else:
            live.append((sess, op, st))
    if not live:
        return False, 0, 0
    if len(live) == 1:
        return _run_solo(live), 0, 1
    chunked = isinstance(live[0][2], FusedStreamStage)
    try:
        if chunked:
            outs, devs = _pack_and_launch_stream(live)
        else:
            outs, devs = _pack_and_launch(live)
    except BaseException as e:  # noqa: BLE001 — availability first: a
        # failing shared launch must not take K sessions down when
        # each op can still run solo (and a per-session cause then
        # surfaces on its own future). Warn so a fusion-layer bug is
        # not silently absorbed as a perf loss.
        warnings.warn(
            f"fused launch failed ({type(e).__name__}: {e}); "
            "re-executing the group unfused"
        )
        return _run_solo(live), 0, len(live)
    drain = False
    a = 0
    for k, (sess, op, st) in enumerate(live):
        try:
            s_ops = None
            if chunked:
                # One sentinel-operand slice tuple per chunk: session
                # k's rows sit at the same offset in every chunk slab.
                if sess.tally._sentinel is not None:
                    C = sess.tally.chunk_size
                    lo = k * C
                    s_ops = [
                        (
                            None if st.origins is None
                            else org[lo:lo + C],
                            d[lo:lo + C], f[lo:lo + C], wv[lo:lo + C],
                        )
                        for (d, f, wv, org) in devs
                    ]
            elif sess.tally._sentinel is not None:
                n_k = sess.tally.num_particles
                dests_dev, fly_dev, w_dev, org_dev = devs
                x_start = (
                    st.x_prev if st.origins is None
                    else org_dev[a:a + n_k]
                )
                s_ops = (x_start, dests_dev[a:a + n_k],
                         fly_dev[a:a + n_k], w_dev[a:a + n_k])
            sess.tally._fused_move_commit(outs[k], st, t0, s_ops)
        except SystemExit as e:
            op.future.set_exception(e)
            drain = True
        except BaseException as e:  # noqa: BLE001 — one session's
            # failing commit (quarantine IO, ladder refusal) must not
            # cost the other sessions their already-launched results.
            op.future.set_exception(e)
        else:
            op.future.set_result(None)
        if not chunked:
            a += sess.tally.num_particles
    return drain, len(live), 0


def _pack_and_launch(live):
    """Pack the staged host buffers into padded slabs (ONE host
    concatenation + ONE upload per operand, however many sessions),
    then run the fused program. Returns the per-session output slices
    and the uploaded slab device arrays (the sentinel commits slice
    them for their audit operands)."""
    rep = live[0][0].tally  # representative: the key pinned the statics
    wd = np.dtype(rep.dtype)
    spans = tuple(sess.tally.num_particles for sess, _op, _st in live)
    P0 = sum(spans)
    pad = padded_total(P0) - P0
    zeros3 = np.zeros((pad, 3), wd)
    stages = [st for _sess, _op, st in live]
    dests = np.concatenate([st.dests for st in stages] + [zeros3])
    fly = np.concatenate(
        [
            st.fly if st.fly is not None else np.ones(n, np.int8)
            for st, n in zip(stages, spans)
        ]
        + [np.zeros(pad, np.int8)]
    )
    w = np.concatenate(
        [
            st.w if st.w is not None else np.ones(n, wd)
            for st, n in zip(stages, spans)
        ]
        + [np.zeros(pad, wd)]
    )
    use_committed = tuple(st.origins is None for st in stages)
    org_dev = None
    if not all(use_committed):
        org_dev = jnp.asarray(np.concatenate(
            [
                st.origins if st.origins is not None
                else np.zeros((n, 3), wd)
                for st, n in zip(stages, spans)
            ]
            + [zeros3]
        ))
    scoring = rep._scoring is not None
    tallies = [sess.tally for sess, _op, _st in live]
    dests_dev = jnp.asarray(dests)
    fly_dev = jnp.asarray(fly)
    w_dev = jnp.asarray(w)
    outs = _fused_move(
        rep.mesh,
        tuple(t.x for t in tallies),
        tuple(t.elem for t in tallies),
        tuple(t.flux for t in tallies),
        tuple(t._score_bank for t in tallies) if scoring else None,
        tuple(st.sbin for st in stages) if scoring else None,
        tuple(st.sfac for st in stages) if scoring else None,
        dests_dev, fly_dev, w_dev, org_dev,
        spans=spans, pad=pad, use_committed=use_committed,
        tol=rep._tol, max_iters=rep._max_iters, walk_kw=rep._walk_kw,
        score_kinds=rep._scoring.spec.kinds if scoring else (),
        stride=rep._scoring.stride if scoring else 0,
    )
    return outs, (dests_dev, fly_dev, w_dev, org_dev)


def _pack_and_launch_stream(live):
    """The streaming (chunk-wise) pack: one fused launch PER CHUNK
    INDEX, through the SAME ``walk_fused`` program as the monolithic
    path — the fusion key pinned every session to one chunk grid, so
    chunk j of each session contributes exactly ``chunk_size`` rows
    and all launches share one static ``(spans, pad, use_committed)``
    composition (one trace key per group size, however many chunks).
    Each chunk's launch dispatches before the next chunk's host pack,
    so the solo streaming pipeline's staging/walk overlap is kept —
    just K-sessions wide. Returns per-SESSION output lists
    (``outs[k][j]`` = session k's chunk-j slices) and the per-chunk
    uploaded slab tuples (the sentinel commits slice them)."""
    rep = live[0][0].tally  # representative: the key pinned the statics
    wd = np.dtype(rep.dtype)
    K = len(live)
    C = rep.chunk_size
    spans = (C,) * K
    pad = padded_total(K * C) - K * C
    zeros3 = np.zeros((pad, 3), wd)
    stages = [st for _sess, _op, st in live]
    tallies = [sess.tally for sess, _op, _st in live]
    use_committed = tuple(st.origins is None for st in stages)
    scoring = rep._scoring is not None
    outs = [[] for _ in range(K)]
    devs = []
    for j in range(rep.nchunks):
        dests_dev = jnp.asarray(np.concatenate(
            [st.dests[j] for st in stages] + [zeros3]
        ))
        fly_dev = jnp.asarray(np.concatenate(
            [st.fly[j] for st in stages] + [np.zeros(pad, np.int8)]
        ))
        w_dev = jnp.asarray(np.concatenate(
            [st.w[j] for st in stages] + [np.zeros(pad, wd)]
        ))
        org_dev = None
        if not all(use_committed):
            org_dev = jnp.asarray(np.concatenate(
                [
                    st.origins[j] if st.origins is not None
                    else np.zeros((C, 3), wd)
                    for st in stages
                ]
                + [zeros3]
            ))
        chunk_outs = _fused_move(
            rep.mesh,
            tuple(t._x[j] for t in tallies),
            tuple(t._elem[j] for t in tallies),
            tuple(t._flux[j] for t in tallies),
            tuple(t._score[j] for t in tallies) if scoring else None,
            tuple(st.sbin[j] for st in stages) if scoring else None,
            tuple(st.sfac[j] for st in stages) if scoring else None,
            dests_dev, fly_dev, w_dev, org_dev,
            spans=spans, pad=pad, use_committed=use_committed,
            tol=rep._tol, max_iters=rep._max_iters,
            walk_kw=rep._walk_kw,
            score_kinds=rep._scoring.spec.kinds if scoring else (),
            stride=rep._scoring.stride if scoring else 0,
        )
        for k in range(K):
            outs[k].append(chunk_outs[k])
        devs.append((dests_dev, fly_dev, w_dev, org_dev))
    return outs, devs
