"""Double-buffered host staging for the multi-session service.

The three-call protocol is synchronous by construction: the caller's
buffers are validated, cast, and uploaded inside the protocol call, so
a host app serializes its own staging against the device walk. The
service breaks that coupling with a PREPACK step that runs on the
CLIENT's thread at submit time:

- every caller buffer is validated and copied into an OWNED flat f64
  host array (``StagedOp``) — the caller may recycle its buffers the
  moment ``submit`` returns, long before the device has even seen the
  move;
- validation happens HERE, before the op enters any queue: a
  malformed move (wrong shape, NaN destination, f32-overflow energy)
  raises at submit with the same argument-naming errors the facades
  produce, and never occupies a queue slot — backpressure and refusal
  both leave the session's committed state untouched;
- the narrow (working-dtype) arms reuse the staging facade's own
  machinery: streaming facades expose ``_prevalidate_narrow``
  (api/streaming.py — chunk-at-a-time casts, discarded after the
  check) and the other facades get the equivalent whole-batch cast
  check, so an f64 value that overflows f32 to inf refuses at submit
  too.

The "double buffer" is the bounded per-session queue this feeds
(session.DEFAULT_QUEUE_DEPTH = 2): one move's owned arrays sit staged
while the previous move walks, and the worker consumes the facade
call — whose own host→device staging then runs against pre-validated,
already-cast-free f64 bytes — as soon as the device frees up. With an
unfenced facade (``fenced_timing=False``) the facade call returns at
dispatch, so move k+1's prepack and protocol staging genuinely overlap
move k's device compute.

Bitwise contract: the facade receives byte-identical f64 inputs to
what a direct caller would pass (prepack only flattens, validates, and
copies — it never converts to the working dtype, so the facade's own
cast runs exactly once, exactly as in a direct call). A campaign
driven through ``StagedOp``s is therefore bitwise-identical to the
same campaign driven directly, pinned by tests/test_service.py.
"""

from __future__ import annotations

import dataclasses
from concurrent.futures import Future
from typing import Any, Callable, Optional

import numpy as np

# Through the api package surface (which re-exports them for exactly
# this consumer), not api.tally directly — a helper rename then breaks
# HERE, not just in external users.
from pumiumtally_tpu.api import (
    check_finite,
    host_positions,
    host_scalar_field,
    zero_flying_side_effect,
)


class OpFuture(Future):
    """A staged op's result future: cancellation is refused (returns
    False, as the ``Future`` contract allows). A queued op always
    RUNS — the protocol has no un-submit, so a session's campaign is
    exactly its submission sequence regardless of client impatience —
    and a cancel that could land (futures start PENDING in the queue)
    would make the worker's ``set_result`` raise ``InvalidStateError``
    outside its op guard, killing the one thread that drains every
    session. Clients that stop caring simply drop the reference."""

    def cancel(self) -> bool:  # noqa: D102 — contract in class doc
        return False


@dataclasses.dataclass
class StagedOp:
    """One queued unit of session work: a prepacked protocol call plus
    the future its submitter holds. ``cost`` is the deficit-round-robin
    charge (particles touched for transport ops, 1 for reads — see
    scheduler.DeficitRoundRobinScheduler)."""

    kind: str  # "source" | "move" | "call"
    label: str
    future: Future
    cost: int = 1
    positions: Optional[np.ndarray] = None  # source payload, flat [3n] f64
    origins: Optional[np.ndarray] = None  # move payload, all owned
    dests: Optional[np.ndarray] = None
    flying: Optional[np.ndarray] = None
    weights: Optional[np.ndarray] = None
    energy: Optional[np.ndarray] = None
    time: Optional[np.ndarray] = None
    fn: Optional[Callable[[Any], Any]] = None  # "call" payload
    # Stamped by TallyService._submit at admission; session
    # note_completed turns it into a p50/p99 latency sample.
    t_submit: Optional[float] = None


def _owned_f64(a: np.ndarray) -> np.ndarray:
    """An owned f64 copy — host_positions/host_scalar_field may return
    a VIEW of the caller's buffer, and a staged op outlives the call
    that submitted it (the whole point), so it must not alias memory
    the caller is about to recycle."""
    return np.array(a, dtype=np.float64, copy=True)


def _prevalidate_narrow_generic(tally, dests_h, origins_h, w_h, e_h,
                                t_h) -> None:
    """Working-dtype finite check for facades without a chunked
    prevalidator: cast-and-check (cast discarded), so the
    f32-overflow corner refuses at submit exactly like the streaming
    facades' ``_prevalidate_narrow`` arm."""
    dt = np.dtype(tally.dtype)
    if dt == np.float64:
        return  # cast is identity; the raw f64 check already ran
    check_finite(np.asarray(dests_h, dtype=dt), "destinations")
    if origins_h is not None:
        check_finite(np.asarray(origins_h, dtype=dt), "origins")
    if w_h is not None:
        check_finite(np.asarray(w_h, dtype=dt), "weights")
    if e_h is not None:
        check_finite(np.asarray(e_h, dtype=dt), "energy")
    if t_h is not None:
        check_finite(np.asarray(t_h, dtype=dt), "time")


def stage_source(tally, positions, size: Optional[int] = None) -> StagedOp:
    """Prepack one ``CopyInitialPosition``: flat owned f64 [3n], raw
    finite check (the localization walk has no narrow corner a clamped
    walk would miss — the facade re-checks after its own cast)."""
    n = tally.num_particles
    pos = _owned_f64(host_positions(positions, size, n))
    if tally.config.validate_inputs:
        check_finite(pos, "positions")
    return StagedOp(kind="source", label="source", future=OpFuture(),
                    cost=max(1, n), positions=pos)


def stage_move(tally, particle_origin, particle_destinations, flying=None,
               weights=None, size: Optional[int] = None, energy=None,
               time=None) -> StagedOp:
    """Prepack one ``MoveToNextLocation``.

    Validation order mirrors the facades: scoring-attribute
    combination errors first (naming the argument), then raw f64
    finite checks, then the working-dtype arms. The protocol's
    flying-zeroing side effect deliberately does NOT happen here —
    prepack may yet be refused at the queue, and a refusal must leave
    the caller's buffers untouched so the retry stages the same
    bytes; the submit path zeroes only after the op is ACCEPTED
    (server.SessionHandle.move — the load-bearing, test-pinned
    ordering).
    """
    n = tally.num_particles
    tally._score_args_check(energy, time)
    dests_h = _owned_f64(host_positions(particle_destinations, size, n))
    origins_h = (
        None if particle_origin is None
        else _owned_f64(host_positions(particle_origin, size, n))
    )
    w_h = (
        None if weights is None
        else _owned_f64(host_scalar_field(weights, n, "weights"))
    )
    e_h = (
        None if energy is None
        else _owned_f64(host_scalar_field(energy, n, "energy"))
    )
    t_h = (
        None if time is None
        else _owned_f64(host_scalar_field(time, n, "time"))
    )
    fly_h = None
    if flying is not None:
        fly_np = np.asarray(flying)
        if fly_np.size < n:
            raise ValueError(
                f"flying buffer has {fly_np.size} values, need {n}"
            )
        fly_h = fly_np.reshape(-1)[:n].astype(np.int8)  # astype copies
    if tally.config.validate_inputs:
        check_finite(dests_h, "destinations")
        if origins_h is not None:
            check_finite(origins_h, "origins")
        if w_h is not None:
            check_finite(w_h, "weights")
        if e_h is not None:
            check_finite(e_h, "energy")
        if t_h is not None:
            check_finite(t_h, "time")
        narrow = getattr(tally, "_prevalidate_narrow", None)
        if narrow is not None:
            # The streaming facades' chunk-at-a-time working-dtype
            # arms (no full-batch cast copies).
            narrow(dests_h, origins_h, w_h, e_h, t_h)
        else:
            _prevalidate_narrow_generic(tally, dests_h, origins_h, w_h,
                                        e_h, t_h)
    # The protocol's flying-zeroing side effect does NOT happen here:
    # prepack may yet be REFUSED at the queue (ServiceBusyError), and a
    # refusal must leave the caller's buffers untouched so the retry
    # stages the same bytes — the submit path applies it only after
    # the op is accepted (server.SessionHandle.move).
    return StagedOp(kind="move", label="move", future=OpFuture(),
                    cost=max(1, n), origins=origins_h, dests=dests_h,
                    flying=fly_h, weights=w_h, energy=e_h, time=t_h)


def stage_call(label: str, fn: Callable[[Any], Any],
               cost: int = 1) -> StagedOp:
    """Prepack an arbitrary facade call (flux/health reads, batch
    close, VTK write, checkpoint). Riding the SAME per-session FIFO as
    the moves is what makes reads consistent: a flux read submitted
    after move k observes exactly moves 1..k, regardless of how the
    scheduler interleaves other sessions."""
    return StagedOp(kind="call", label=label, future=OpFuture(), cost=cost,
                    fn=fn)


def execute_op(tally, op: StagedOp):
    """Run one staged op against the session's facade (worker thread).
    Returns the facade call's result (futures carry it to the
    client)."""
    if op.kind == "source":
        return tally.CopyInitialPosition(op.positions)
    if op.kind == "move":
        kw = {}
        if op.energy is not None:
            kw["energy"] = op.energy
        if op.time is not None:
            kw["time"] = op.time
        return tally.MoveToNextLocation(
            op.origins, op.dests, op.flying, op.weights, **kw
        )
    return op.fn(tally)


def run_op_contained(tally, op: StagedOp) -> bool:
    """``execute_op`` with the server's containment contract, in ONE
    place (the worker's solo path and the fusion fallback both route
    here — a policy change cannot silently diverge them): the result
    or exception lands on exactly this op's future, and the return
    says whether a facade-level drain exit (SystemExit — e.g.
    checkpoint_now with a pending runner drain) was absorbed, so the
    caller folds it into a service-wide drain instead of letting it
    kill the one worker thread that serves every session."""
    try:
        result = execute_op(tally, op)
    except SystemExit as e:
        op.future.set_exception(e)
        return True
    except BaseException as e:  # noqa: BLE001 — server boundary: one
        # client's failing op must not take the worker (and every
        # other session) down.
        op.future.set_exception(e)
        return False
    op.future.set_result(result)
    return False
