"""Checkpoint/resume of tally state.

The reference has none — its flux lives only in device memory until the
final VTK write (SURVEY.md §5 "Checkpoint/resume: none"), so a crashed
run loses the whole tally. Here the complete engine state (flux,
committed positions, element ids, move counter) round-trips through one
``.npz`` file; long campaigns checkpoint between MoveToNextLocation
calls and resume exactly.
"""

from __future__ import annotations

import numpy as np

_FORMAT_VERSION = 1


def save_tally_state(tally, path: str) -> None:
    """Write the full engine state of a ``PumiTally`` to ``path``."""
    np.savez_compressed(
        path,
        format_version=np.int64(_FORMAT_VERSION),
        flux=np.asarray(tally.flux),
        x=np.asarray(tally.x),
        elem=np.asarray(tally.elem),
        iter_count=np.int64(tally.iter_count),
        num_particles=np.int64(tally.num_particles),
        capacity=np.int64(tally.x.shape[0]),
        nelems=np.int64(tally.mesh.nelems),
        is_initialized=np.bool_(tally.is_initialized),
    )


def load_tally_state(tally, path: str) -> None:
    """Restore state saved by ``save_tally_state`` into ``tally``.

    The target must be built over the same mesh and particle capacity;
    mismatches raise rather than silently corrupt the tally.
    """
    import jax.numpy as jnp

    with np.load(path) as z:
        if int(z["format_version"]) != _FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {int(z['format_version'])} != "
                f"{_FORMAT_VERSION}"
            )
        if int(z["nelems"]) != tally.mesh.nelems:
            raise ValueError(
                f"checkpoint mesh has {int(z['nelems'])} elements, "
                f"target has {tally.mesh.nelems}"
            )
        if int(z["num_particles"]) != tally.num_particles:
            raise ValueError(
                f"checkpoint has {int(z['num_particles'])} particles, "
                f"target has {tally.num_particles}"
            )
        # The internal capacity differs across device-mesh configs
        # (padding to a multiple of the mesh size); restoring across
        # them would corrupt array shapes.
        if int(z["capacity"]) != tally._cap:
            raise ValueError(
                f"checkpoint particle capacity {int(z['capacity'])} != "
                f"target capacity {tally._cap} (was it saved under a "
                "different device_mesh configuration?)"
            )
        tally.flux = jnp.asarray(z["flux"], dtype=tally.dtype)
        tally.x = jnp.asarray(z["x"], dtype=tally.dtype)
        tally.elem = jnp.asarray(z["elem"], dtype=jnp.int32)
        tally.iter_count = int(z["iter_count"])
        tally.is_initialized = bool(z["is_initialized"])
