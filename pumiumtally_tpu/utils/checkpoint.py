"""Checkpoint/resume of tally state.

The reference has none — its flux lives only in device memory until the
final VTK write (SURVEY.md §5 "Checkpoint/resume: none"), so a crashed
run loses the whole tally. Here the complete engine state (flux,
committed positions, element ids, move counter) round-trips through one
``.npz`` file; long campaigns checkpoint between MoveToNextLocation
calls and resume exactly.

Failure-mode contract (round 8, docs/DESIGN.md "Fault tolerance"):

- ``save_tally_state`` is ATOMIC: the payload is written to a temp file
  in the target directory, flushed, fsync'd, and ``os.replace``d over
  the destination — a crash mid-save leaves either the old checkpoint
  or the new one on disk, never a truncated hybrid.
- ``load_tally_state`` raises ``CorruptCheckpointError`` (a ValueError)
  on a truncated/bit-flipped/garbage file instead of leaking raw
  ``zipfile``/``numpy`` internals; header MISMATCHES (wrong mesh,
  wrong particle count, too-new format) stay plain ValueError — they
  mean a mis-configured target, not a damaged file.
- Besides the canonical cross-engine payload, a checkpoint carries the
  saving engine's exact slot LAYOUT (partitioned state rows, per-chunk
  flux): restored into an identically configured engine, transport
  continues bit-for-bit — the resilience layer's kill-and-resume
  guarantee. A differently configured target silently falls back to
  the canonical restore (still exact state, scatter-order flux class).
"""

from __future__ import annotations

import io
import os
import warnings
import zipfile
import zlib
from typing import Union

import numpy as np

# v3 (round 7) added the batch-statistics lanes (TallyConfig.
# batch_stats): flux_sum / flux_sq_sum / batch counter / open-batch
# snapshot. A checkpoint WITHOUT statistics still writes v2, so plain
# tallies stay readable by older code; a stats-carrying checkpoint
# writes v3 and an older reader refuses it up front with the
# "format ... newer than" header error — never a shape error from
# half-understood arrays. The round-8 layout extras (``eng*_*`` /
# ``chunk_flux`` / ``lost_total``) do NOT bump the version: old readers
# ignore unknown keys and restore canonically, which is still a valid
# (just not bitwise-layout-exact) state.
_FORMAT_VERSION = 3

# Slot-state rows are saved verbatim for layout-exact partitioned
# restore by iterating the ENGINE's own state dict (round 10 — the
# optional scoring rows sbin/sfac ride exactly when present); a key
# the checkpoint lacks makes the loader fall back to the canonical
# restore, so drift degrades gracefully.


class CorruptCheckpointError(ValueError):
    """The checkpoint file itself is damaged (truncated, bit-flipped,
    or not a checkpoint at all) — as opposed to a well-formed
    checkpoint that does not fit the target engine (plain ValueError).
    The generational store (pumiumtally_tpu.resilience) catches this to
    fall back to an earlier generation."""


def _engine_kind(tally) -> str:
    # Local imports: utils must not import the api package at module
    # load (api imports utils).
    from pumiumtally_tpu.api.partitioned import PartitionedPumiTally
    from pumiumtally_tpu.api.streaming import (
        StreamingPartitionedTally,
        StreamingTally,
    )

    if isinstance(tally, PartitionedPumiTally):
        return "partitioned"
    if isinstance(tally, StreamingPartitionedTally):
        return "streaming_partitioned"
    if isinstance(tally, StreamingTally):
        return "streaming"
    return "monolithic"


def _engine_layout_arrays(eng, prefix: str) -> dict:
    """One PartitionedEngine's exact slot state, key-prefixed for the
    checkpoint payload (layout-exact restore; module docstring).
    Iterates the engine's OWN state keys so optional rows (the
    scoring ``sbin``/``sfac``, round 10) ride along exactly when the
    engine carries them — a scoring-less engine's payload stays
    byte-identical to pre-scoring builds; old readers ignore the extra
    keys (no format bump)."""
    out = {prefix + k: np.asarray(v) for k, v in eng.state.items()}
    out[prefix + "flux_padded"] = np.asarray(eng.flux_padded)
    if eng.score_padded is not None:
        out[prefix + "score_padded"] = np.asarray(eng.score_padded)
    out[prefix + "cap"] = np.int64(eng.cap)
    out[prefix + "nparts"] = np.int64(eng.nparts)
    out[prefix + "L"] = np.int64(eng.part.L)
    out[prefix + "n"] = np.int64(eng.n)
    return out


def collect_tally_state(tally) -> dict:
    """The full checkpoint payload of any facade as a name→array dict
    (the serialization half of ``save_tally_state``; the resilience
    generation store serializes the same dict through its digest
    wrapper)."""
    kind = _engine_kind(tally)
    if kind == "monolithic":
        x = np.asarray(tally.x)
        elem = np.asarray(tally.elem)
    else:
        # Canonical caller order; engines re-derive their layout.
        x = np.asarray(tally.positions)
        elem = np.asarray(tally.elem_ids)
    extra = {}
    stats = getattr(tally, "_stats", None)
    if stats is not None:
        # Batch-statistics lanes (canonical [E] original order — the
        # layout they already live in) + counters + the open-batch
        # flux snapshot, so a restarted run resumes its statistics
        # EXACTLY: the next close_batch measures the same delta it
        # would have un-restarted.
        extra = {
            "stats_flux_sum": np.asarray(stats.flux_sum),
            "stats_flux_sq_sum": np.asarray(stats.flux_sq_sum),
            "stats_num_batches": np.int64(stats.num_batches),
            "stats_moves_in_batch": np.int64(stats.moves_in_batch),
            "stats_batch_open": np.bool_(stats.open_flux is not None),
            "stats_open_flux": (
                np.zeros((stats.nelems,), np.float64)
                if stats.open_flux is None
                else np.asarray(stats.open_flux)
            ),
        }
    scoring = getattr(tally, "_scoring", None)
    if scoring is not None:
        # Scoring lanes (round 10): the CANONICAL flattened bank; the
        # per-chunk / per-engine layout extras ride below. Extra keys
        # only — scoring-less saves stay byte-identical and old
        # readers ignore them (no format bump, like the round-8
        # layout extras).
        extra["score_bank"] = np.asarray(tally.score_bank)
        # The saving spec's static identity (scores/overflow/bin
        # counts): the restore refuses a bank whose lane layout does
        # not match the target spec (lane values under a different
        # (bin, score) interpretation would be silently wrong data).
        extra["score_spec"] = np.str_(repr(scoring.spec.static_key()))
        sstats = getattr(tally, "_score_stats", None)
        if sstats is not None:
            extra.update({
                "sstats_flux_sum": np.asarray(sstats.flux_sum),
                "sstats_flux_sq_sum": np.asarray(sstats.flux_sq_sum),
                "sstats_num_batches": np.int64(sstats.num_batches),
                "sstats_moves_in_batch": np.int64(sstats.moves_in_batch),
                "sstats_batch_open": np.bool_(
                    sstats.open_flux is not None
                ),
                "sstats_open_flux": (
                    np.zeros((sstats.nelems,), np.float64)
                    if sstats.open_flux is None
                    else np.asarray(sstats.open_flux)
                ),
            })
    # Layout-exact extras (round 8): the saving engine's own slot/chunk
    # arrangement, so a same-configured target resumes bit-for-bit.
    # The monolithic/sharded facade's canonical arrays ARE its layout.
    if kind == "streaming":
        extra["chunk_flux"] = np.stack(
            [np.asarray(f) for f in tally._flux]
        )
        extra["chunk_size"] = np.int64(tally.chunk_size)
        if scoring is not None:
            extra["chunk_score"] = np.stack(
                [np.asarray(b) for b in tally._score]
            )
    elif kind == "partitioned":
        extra["eng_count"] = np.int64(1)
        extra.update(_engine_layout_arrays(tally.engine, "eng0_"))
    elif kind == "streaming_partitioned":
        extra["eng_count"] = np.int64(len(tally.engines))
        extra["chunk_size"] = np.int64(tally.chunk_size)
        for k, eng in enumerate(tally.engines):
            extra.update(_engine_layout_arrays(eng, f"eng{k}_"))
    return {
        # Minimum version that can read the payload: plain tallies
        # stay v2-compatible; only stats-carrying checkpoints demand
        # the v3 reader (see _FORMAT_VERSION note).
        "format_version": np.int64(
            _FORMAT_VERSION if stats is not None else 2
        ),
        "kind": np.str_(kind),
        "flux": np.asarray(tally.flux),
        "x": x,
        "elem": elem,
        "iter_count": np.int64(tally.iter_count),
        "num_particles": np.int64(tally.num_particles),
        "capacity": np.int64(x.shape[0]),
        "nelems": np.int64(tally.mesh.nelems),
        "is_initialized": np.bool_(tally.is_initialized),
        # Cumulative leakage counter (facade ``lost_particles``, the
        # rolled part only — the open batch's lost particles ride in
        # the state itself and re-derive on restore).
        "lost_total": np.int64(getattr(tally, "_lost_total", 0)),
        **extra,
    }


def save_tally_state(tally, path: str) -> None:
    """Write the full engine state of any tally facade to ``path``,
    ATOMICALLY (temp file + fsync + ``os.replace`` — a crash mid-save
    never corrupts an existing checkpoint at ``path``).

    Monolithic, streaming, and partitioned engines are all supported;
    the caller-visible canonical form (positions/element ids in particle
    order, flux in original element order) is what is stored, so a
    checkpoint can be restored into a DIFFERENT engine configuration
    over the same mesh (e.g. saved partitioned, resumed monolithic) —
    the reference has no checkpointing at all (SURVEY.md §5). The
    saving engine's exact layout rides along so a SAME-configured
    engine resumes bit-for-bit (module docstring).
    """
    if not path.endswith(".npz"):
        path += ".npz"  # np.savez's own path convention, kept
    arrays = collect_tally_state(tally)
    atomic_write(path, lambda f: np.savez_compressed(f, **arrays))


def atomic_write(path: str, write_payload, tmp_path: str = None,
                 pre_replace=None) -> None:
    """THE atomic-durability sequence, shared by every checkpoint
    writer (this module and the resilience generation store): payload →
    temp file (same directory, so the rename cannot cross filesystems)
    → flush → fsync → ``os.replace`` → directory fsync. A crash at any
    instant leaves either the old file or the new one, never a
    truncated hybrid. ``pre_replace`` runs between the fsync and the
    rename — the fault harness's kill-mid-save injection point."""
    tmp = tmp_path or f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_payload(f)
            f.flush()
            os.fsync(f.fileno())
        if pre_replace is not None:
            pre_replace()
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(path) or ".")


def atomic_append(path: str, payload: bytes) -> None:
    """Append-safe variant of ``atomic_write`` for line-oriented logs
    (the sentinel quarantine JSONL): the existing file content plus the
    new payload is written to a temp file and renamed over the
    original through the SAME temp+fsync+replace+dir-fsync sequence —
    a crash mid-append leaves either the old log or the extended one,
    never a torn record. O(file) per append by design: quarantine
    events are rare (an append per *unrecoverable* particle batch),
    and torn tail records are exactly what a plain ``open(path, "a")``
    cannot rule out. Readers should still skip a torn final line
    (``sentinel.quarantine.read_quarantine`` does) for logs written by
    older code or foreign tools."""
    try:
        with open(path, "rb") as f:
            existing = f.read()
    except FileNotFoundError:
        existing = b""
    atomic_write(path, lambda f: (f.write(existing), f.write(payload)))


def _fsync_dir(d: str) -> None:
    """Best-effort directory fsync so the rename itself is durable
    (not just the file bytes) — preemption-safe autosave must survive
    power loss at any instant."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def read_checkpoint_arrays(path: Union[str, io.IOBase]) -> dict:
    """Load a checkpoint ``.npz`` (path or file-like object) eagerly
    into a plain name→array dict.

    Every decompression happens HERE, so damage anywhere in the file
    surfaces as one ``CorruptCheckpointError`` up front — the caller
    never has a half-restored tally on its hands. A missing file stays
    ``FileNotFoundError`` (absence is not corruption)."""
    label = path if isinstance(path, str) else "<buffer>"
    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, zlib.error, EOFError, OSError,
            KeyError, ValueError) as e:
        raise CorruptCheckpointError(
            f"corrupt checkpoint {label!r}: not a readable checkpoint "
            f"archive ({type(e).__name__}: {e}). The file is truncated, "
            "bit-flipped, or not a checkpoint; restore from an earlier "
            "generation (pumiumtally_tpu.resilience keeps several)"
        ) from e


def _check_header(z, tally) -> None:
    for key in ("format_version", "nelems", "num_particles", "flux",
                "x", "elem", "iter_count", "is_initialized"):
        if key not in z:
            raise CorruptCheckpointError(
                f"corrupt checkpoint: required array {key!r} missing"
            )
    if int(z["format_version"]) > _FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {int(z['format_version'])} newer than "
            f"{_FORMAT_VERSION}"
        )
    if int(z["nelems"]) != tally.mesh.nelems:
        raise ValueError(
            f"checkpoint mesh has {int(z['nelems'])} elements, "
            f"target has {tally.mesh.nelems}"
        )
    if int(z["num_particles"]) != tally.num_particles:
        raise ValueError(
            f"checkpoint has {int(z['num_particles'])} particles, "
            f"target has {tally.num_particles}"
        )


def load_tally_state(tally, path: Union[str, io.IOBase]) -> None:
    """Restore state saved by ``save_tally_state`` into ``tally``.

    The target must be built over the same mesh and particle count;
    mismatches raise rather than silently corrupt the tally. The saved
    state is canonical (caller particle order, original element order),
    so the target's engine kind need not match the saver's; when it
    DOES match — same kind, same layout geometry — the saved layout
    extras restore the engine bit-for-bit instead. A damaged file
    raises ``CorruptCheckpointError`` before the tally is touched.
    ``path`` may be a file-like object (the resilience generation
    store's verified payloads load through a BytesIO)."""
    z = read_checkpoint_arrays(path)
    apply_tally_state(tally, z)


def apply_tally_state(tally, z: dict) -> None:
    """Restore an already-loaded checkpoint dict (see
    ``read_checkpoint_arrays``) into ``tally``."""
    _apply_tally_state_inner(tally, z)
    # A restore rewrites flux outside any move: re-baseline the
    # sentinel's conservation delta or the first post-resume move
    # would audit against the pre-restore sum (false anomaly).
    sentinel = getattr(tally, "_sentinel", None)
    if sentinel is not None:
        sentinel.resync(tally.flux)


def _apply_tally_state_inner(tally, z: dict) -> None:
    import jax.numpy as jnp

    _check_header(z, tally)

    # Restoring rewrites committed positions out from under the
    # auto-continue echo check — invalidate its bookkeeping.
    if hasattr(tally, "_last_dests_host"):
        tally._last_dests_host = None
        tally._last_dests_dev = None
        tally._echo_misses = 0
    if hasattr(tally, "_lost_total"):
        tally._lost_total = int(z.get("lost_total", 0))

    kind = _engine_kind(tally)
    n = tally.num_particles
    flux = np.asarray(z["flux"], dtype=np.float64)
    x = np.asarray(z["x"], dtype=np.float64)[:n]
    elem = np.asarray(z["elem"], dtype=np.int32)[:n]
    saved_kind = str(z["kind"]) if "kind" in z else "monolithic"
    if saved_kind == "monolithic" and kind == "monolithic":
        # v1-compatible direct restore (capacity layout preserved
        # only when both sides are monolithic with equal capacity).
        if int(z["capacity"]) == tally._cap:
            tally.flux = jnp.asarray(z["flux"], dtype=tally.dtype)
            tally.x = jnp.asarray(z["x"], dtype=tally.dtype)
            tally.elem = jnp.asarray(z["elem"], dtype=jnp.int32)
            tally.iter_count = int(z["iter_count"])
            tally.is_initialized = bool(z["is_initialized"])
            _restore_stats(tally, z)
            _restore_scoring(tally, kind, z, layout_done=False)
            return
    if saved_kind == kind and _restore_layout_exact(tally, kind, z):
        tally.iter_count = int(z["iter_count"])
        tally.is_initialized = bool(z["is_initialized"])
        _restore_stats(tally, z)
        # Layout-exact restore already placed the per-engine / per-
        # chunk banks verbatim; only the scoring statistics (and the
        # no-bank / dropped-bank corners) remain.
        _restore_scoring(tally, kind, z, layout_done=True)
        return
    _restore_canonical(tally, kind, x, elem, flux, z)
    _restore_stats(tally, z)
    _restore_scoring(tally, kind, z, layout_done=False)


def _restore_stats(tally, z) -> None:
    """Batch-statistics restore, covering the version skew both ways:

    - stats-enabled target + stats-carrying (v3) checkpoint: exact
      lane/counter/open-snapshot restore — a resumed run's statistics
      continue bit-for-bit;
    - stats-enabled target + pre-stats (v2) checkpoint: lanes
      zero-initialized, batch counter 0, and a fresh batch opened at
      the restored flux (forward compatibility — old campaigns gain
      statistics from the restore point on);
    - stats-disabled target + stats-carrying checkpoint: the lanes are
      dropped with a warning (the flux itself restores unchanged).
    A stats checkpoint read by a pre-v3 reader never reaches here: its
    header check refuses "format 3 newer than 2" up front."""
    stats = getattr(tally, "_stats", None)
    has = "stats_flux_sum" in z
    if stats is None:
        if has:
            warnings.warn(
                "checkpoint carries batch statistics but the target "
                "engine has batch_stats disabled; statistics lanes "
                "dropped (flux restored unchanged)"
            )
        return
    if not has:
        import jax.numpy as jnp

        stats.reset(open_flux=jnp.asarray(z["flux"], dtype=tally.dtype))
        return
    stats.restore(
        z["stats_flux_sum"],
        z["stats_flux_sq_sum"],
        int(z["stats_num_batches"]),
        int(z["stats_moves_in_batch"]),
        z["stats_open_flux"] if bool(z["stats_batch_open"]) else None,
    )


def _restore_scoring(tally, kind, z, layout_done: bool) -> None:
    """Scoring-lane restore (round 10), mirroring the statistics
    version-skew contract:

    - scoring-armed target + scoring-carrying checkpoint: exact bank
      restore (the layout-exact path already placed per-engine/chunk
      banks; the canonical path scatters the flattened bank here) and
      exact scoring-statistics restore;
    - scoring-armed target + pre-scoring checkpoint: zero banks (a
      restored campaign gains scoring lanes from the restore point);
    - scoring-armed target + a bank saved under a DIFFERENT spec
      (other scores/bins/overflow, or a different lane count): zero
      banks with a warning — installing lane values under the wrong
      (bin, score) interpretation would be silently wrong data;
    - scoring-less target + scoring-carrying checkpoint: lanes dropped
      with a warning (flux restores unchanged)."""
    import jax.numpy as jnp

    scoring = getattr(tally, "_scoring", None)
    has = "score_bank" in z
    if scoring is None:
        if has:
            warnings.warn(
                "checkpoint carries scoring lanes but the target "
                "engine has no TallyConfig.scoring; scoring lanes "
                "dropped (flux restored unchanged)"
            )
        return
    want_spec = repr(scoring.spec.static_key())
    want_size = tally.mesh.nelems * scoring.stride
    if has and (
        str(z.get("score_spec", want_spec)) != want_spec
        or np.asarray(z["score_bank"]).size != want_size
    ):
        warnings.warn(
            "checkpoint scoring lanes were saved under a different "
            f"ScoringSpec ({z.get('score_spec')!s} vs {want_spec}); "
            "banks zeroed — scoring restarts at the restore point "
            "(flux restored unchanged)"
        )
        has = False  # treat as a pre-scoring checkpoint below
    if not has:
        _zero_scoring_banks(tally, kind)
        # Statistics over the old spec's lanes are as stale as the
        # lanes themselves: reset at the (zeroed) bank.
        sstats = getattr(tally, "_score_stats", None)
        if sstats is not None:
            sstats.reset(
                open_flux=jnp.asarray(tally.score_bank, dtype=tally.dtype)
            )
        return
    if not layout_done:
        _restore_scoring_canonical(
            tally, kind, np.asarray(z["score_bank"], np.float64)
        )
    sstats = getattr(tally, "_score_stats", None)
    if sstats is None:
        return
    if "sstats_flux_sum" in z:
        sstats.restore(
            z["sstats_flux_sum"],
            z["sstats_flux_sq_sum"],
            int(z["sstats_num_batches"]),
            int(z["sstats_moves_in_batch"]),
            z["sstats_open_flux"] if bool(z["sstats_batch_open"]) else None,
        )
    else:
        sstats.reset(
            open_flux=jnp.asarray(tally.score_bank, dtype=tally.dtype)
        )


def _zero_scoring_banks(tally, kind) -> None:
    import jax.numpy as jnp

    if kind == "streaming":
        tally._score = [jnp.zeros_like(b) for b in tally._score]
    elif kind == "partitioned":
        tally.engine.score_padded = jnp.zeros_like(
            tally.engine.score_padded
        )
    elif kind == "streaming_partitioned":
        for eng in tally.engines:
            eng.score_padded = jnp.zeros_like(eng.score_padded)
    else:
        tally._score_bank = tally._scoring.zero_bank()


def _restore_partitioned_score(eng, bank: np.ndarray) -> None:
    """Canonical [E·B·S] bank → the engine's padded-glid lane layout
    (the inverse of ``score_original``)."""
    import jax.numpy as jnp

    stride = eng.score_stride
    rows = np.zeros((eng.nparts * eng.part.L, stride), np.float64)
    rows[np.asarray(eng.part.glid_of_orig)] = bank.reshape(-1, stride)
    eng.score_padded = jnp.asarray(
        rows.reshape(-1), dtype=eng.flux_padded.dtype
    )


def _restore_scoring_canonical(tally, kind, bank: np.ndarray) -> None:
    import jax.numpy as jnp

    if kind == "streaming":
        # Whole bank into chunk 0 (the flux convention: the sum over
        # chunks reproduces the canonical total).
        tally._score = [jnp.asarray(bank, dtype=tally.dtype)] + [
            jnp.zeros_like(tally._score[0])
            for _ in range(tally.nchunks - 1)
        ]
    elif kind == "partitioned":
        _restore_partitioned_score(tally.engine, bank)
    elif kind == "streaming_partitioned":
        for k, eng in enumerate(tally.engines):
            if k == 0:
                _restore_partitioned_score(eng, bank)
            else:
                eng.score_padded = jnp.zeros_like(eng.score_padded)
    else:
        tally._score_bank = jnp.asarray(bank, dtype=tally.dtype)


def _engine_layout_matches(eng, z, prefix: str) -> bool:
    """The saved layout fits this engine verbatim: same slot geometry
    and every state row THIS engine carries present (a scoring-armed
    target needs the saved sbin/sfac + bank; a pre-scoring checkpoint
    then falls back to the canonical restore)."""
    for key, want in (
        ("cap", eng.cap), ("nparts", eng.nparts),
        ("L", eng.part.L), ("n", eng.n),
    ):
        if prefix + key not in z or int(z[prefix + key]) != int(want):
            return False
    if eng.score_padded is not None and (
        prefix + "score_padded" not in z
        or z[prefix + "score_padded"].size != eng.score_padded.size
    ):
        return False
    # Shape equality per row (not just presence): a scoring spec with
    # a different score count changes the sfac row width even at equal
    # slot geometry — installing it verbatim would poison the engine.
    return all(
        prefix + k in z
        and tuple(z[prefix + k].shape) == tuple(eng.state[k].shape)
        for k in eng.state
    ) and prefix + "flux_padded" in z


def _restore_engine_layout(eng, z, prefix: str) -> None:
    import jax.numpy as jnp

    eng.state = {
        k: jnp.asarray(z[prefix + k], dtype=eng.state[k].dtype)
        for k in eng.state
    }
    eng.flux_padded = jnp.asarray(
        z[prefix + "flux_padded"], dtype=eng.flux_padded.dtype
    )
    if eng.score_padded is not None:
        eng.score_padded = jnp.asarray(
            z[prefix + "score_padded"], dtype=eng.score_padded.dtype
        )
    eng._n_lost_dev = jnp.sum(eng.state["lost"])
    eng._n_lost_cache = None


def _restore_layout_exact(tally, kind, z) -> bool:
    """Try the layout-exact restore path (module docstring). Returns
    False — leaving the tally untouched — whenever the saved layout
    does not fit this target exactly; the caller then falls back to
    the canonical restore."""
    import jax.numpy as jnp

    if kind == "streaming":
        cf = z.get("chunk_flux")
        cs = z.get("chunk_score")
        scoring_armed = getattr(tally, "_scoring", None) is not None
        if (
            cf is None
            or "chunk_size" not in z
            or int(z["chunk_size"]) != tally.chunk_size
            or cf.shape[0] != tally.nchunks
            or (scoring_armed and (
                cs is None or cs.shape[0] != tally.nchunks
                or cs.shape[1] != tally._scoring.bank_size
            ))
        ):
            return False
        # Positions/elements restore through the canonical staging
        # (exact: the canonical arrays are bit-copies of the chunk
        # state), then the per-chunk flux split replaces the
        # all-in-chunk-0 canonical layout so the flux SUM reproduces
        # the saving engine's addition order bit-for-bit.
        n = tally.num_particles
        _restore_canonical(
            tally, kind,
            np.asarray(z["x"], dtype=np.float64)[:n],
            np.asarray(z["elem"], dtype=np.int32)[:n],
            np.asarray(z["flux"], dtype=np.float64), z,
        )
        tally._flux = [
            jnp.asarray(cf[k], dtype=tally.dtype)
            for k in range(tally.nchunks)
        ]
        if scoring_armed:
            tally._score = [
                jnp.asarray(cs[k], dtype=tally.dtype)
                for k in range(tally.nchunks)
            ]
        return True
    if kind == "partitioned":
        eng = tally.engine
        if int(z.get("eng_count", 0)) != 1 or not _engine_layout_matches(
            eng, z, "eng0_"
        ):
            return False
        _restore_engine_layout(eng, z, "eng0_")
        return True
    if kind == "streaming_partitioned":
        engines = tally.engines
        if (
            int(z.get("eng_count", 0)) != len(engines)
            or "chunk_size" not in z
            or int(z["chunk_size"]) != tally.chunk_size
            or not all(
                _engine_layout_matches(eng, z, f"eng{k}_")
                for k, eng in enumerate(engines)
            )
        ):
            return False
        for k, eng in enumerate(engines):
            _restore_engine_layout(eng, z, f"eng{k}_")
        return True
    return False


def _restore_canonical(tally, kind, x, elem, flux, z) -> None:
    import jax.numpy as jnp

    n = tally.num_particles
    if kind in ("monolithic", "streaming") and np.any(elem[:n] < 0):
        # elem == -1 marks LOST particles (source in no element, a
        # partitioned-engine state); non-partitioned engines have no
        # way to keep them excluded from transport — aliasing them
        # onto a real element would silently corrupt the tally.
        raise ValueError(
            "checkpoint contains lost particles (element id -1); "
            "restore it into a partitioned engine"
        )
    if kind == "monolithic":
        cap = tally._cap
        xf = np.zeros((cap, 3), np.float64)
        ef = np.zeros((cap,), np.int32)
        xf[:n] = x[:n]
        ef[:n] = elem[:n]
        if cap > n:  # padded slots: park at slot n-1's state (inactive)
            xf[n:] = x[n - 1]
            ef[n:] = elem[n - 1]
        tally.x = jnp.asarray(xf, dtype=tally.dtype)
        tally.elem = jnp.asarray(ef)
        tally.flux = jnp.asarray(flux, dtype=tally.dtype)
    elif kind == "streaming":
        # Reuse the engine's own staging helpers so the chunk layout
        # and padding convention (repeat the last row) cannot diverge
        # from what the walk path expects; only the final chunk pads,
        # so elem's scalar fill matches x's last-row pad.
        xflat = np.ascontiguousarray(x.reshape(-1))
        for k in range(tally.nchunks):
            # retain=True: these chunks become persistent engine state,
            # so they must own their memory (the no-copy fast path is
            # only safe for chunks consumed within one fenced call).
            tally._x[k] = tally._stage_chunk_positions(xflat, k, retain=True)
            tally._elem[k] = tally._stage_chunk_vec(
                elem, k, np.int32, int(elem[n - 1])
            )
        tally._flux = [jnp.asarray(flux, dtype=tally.dtype)] + [
            jnp.zeros_like(tally._flux[0]) for _ in range(tally.nchunks - 1)
        ]
    elif kind == "partitioned":
        _restore_partitioned_engine(tally.engine, x, elem, flux, tally.dtype)
    elif kind == "streaming_partitioned":
        # Per-chunk engines; the accumulated flux lives wholly in
        # engine 0 (the flux property sums engines).
        for k, eng in enumerate(tally.engines):
            lo, hi = tally._chunk_bounds(k)
            _restore_partitioned_engine(
                eng, x[lo:hi], elem[lo:hi],
                flux if k == 0 else None, tally.dtype,
            )
    tally.iter_count = int(z["iter_count"])
    tally.is_initialized = bool(z["is_initialized"])


def _restore_partitioned_engine(eng, x, elem, flux, dtype) -> None:
    """Rebuild one PartitionedEngine's slot layout from canonical
    (caller-order) state: particle pid in slot pid, then one migration
    distributes to owners. ``elem == -1`` marks lost particles (no
    containing element) — they stay unlocated and excluded from
    transport, never aliased onto a real element. ``flux`` (original
    element order) may be None to leave this engine's owned flux zero."""
    import jax.numpy as jnp

    n = eng.n
    glid_all = np.asarray(eng.part.glid_of_orig)
    lost = elem < 0
    glid = np.where(lost, -1, glid_all[np.clip(elem, 0, None)])
    st = dict(eng.state)
    pid = np.full(eng.cap, -1, np.int32)
    pid[:n] = np.arange(n, dtype=np.int32)
    alive = pid >= 0
    xf = np.zeros((eng.cap, 3), np.float64)
    xf[:n] = x
    pend = np.full(eng.cap, -1, np.int32)
    pend[:n] = glid
    lostf = np.zeros(eng.cap, bool)
    lostf[:n] = lost
    st["x"] = jnp.asarray(xf, dtype=dtype)
    st["pid"] = jnp.asarray(pid)
    st["alive"] = jnp.asarray(alive)
    st["pending"] = jnp.asarray(pend)
    st["lelem"] = jnp.zeros((eng.cap,), jnp.int32)
    st["done"] = jnp.asarray(~alive)
    st["exited"] = jnp.zeros((eng.cap,), bool)
    st["lost"] = jnp.asarray(lostf)
    from pumiumtally_tpu.parallel.partition import migrate

    # Slot routing is at BLOCK granularity (nparts groups of
    # cap_per_block) — sub-split engines (blocks_per_chip > 1) have
    # more slot groups than chips.
    eng.state, overflow = migrate(
        part_L=eng.part.L, ndev=eng.nparts,
        cap_per_chip=eng.cap_per_block, state=st,
        partition_method=eng.partition_method,
    )
    if bool(overflow):
        # The checkpointed particle distribution does not fit this
        # engine's provisioning — e.g. the SAVING engine recovered an
        # overflow by escalating capacity (round 9), and the restore
        # target was built with the original factor. Recover the same
        # way: one demand-sized escalation over the intact pre-migrate
        # snapshot (the overflow-safe migrate kept it), then retry; a
        # second failure is a real configuration error and raises.
        eng._escalate_capacity(eng._needed_capacity_growth())
        eng.state, overflow = migrate(
            part_L=eng.part.L, ndev=eng.nparts,
            cap_per_chip=eng.cap_per_block, state=eng.state,
            partition_method=eng.partition_method,
        )
        eng._check_overflow(overflow)
    eng.state["done"] = jnp.ones((eng.cap,), bool)
    eng.state["pending"] = jnp.full((eng.cap,), -1, jnp.int32)
    eng._n_lost_dev = None
    eng._n_lost_cache = int(lost.sum())
    if flux is not None:
        # Owned flux layout: original order -> padded glid slots.
        fpad = np.zeros((eng.nparts * eng.part.L,), np.float64)
        fpad[glid_all] = flux
        eng.flux_padded = jnp.asarray(fpad, dtype=dtype)
    else:
        eng.flux_padded = jnp.zeros_like(eng.flux_padded)
