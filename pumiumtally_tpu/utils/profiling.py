"""Profiling: fenced phase timers, XLA trace capture, retrace tripwire.

The reference's instrumentation is wall-clock only, and its intended
``Kokkos::fence()`` before timestamps never fires due to a macro-name
mismatch (SURVEY.md §5) — so its device timing is unfenced as shipped.
Here ``phase_timer`` always fences with ``block_until_ready``, and
``trace`` wraps ``jax.profiler`` for real XLA timeline capture
(view with TensorBoard / xprof).

``retrace_guard`` is the runtime counterpart of the jaxlint static
analyzer (pumiumtally_tpu/analysis, rule JL004): static analysis can
flag retrace BAIT (unhashable static defaults), but cache-key
instability is only observable at run time — an entry point that
recompiles on every call with identical shapes is indistinguishable
from a healthy one without counting cache misses. The guard counts two
things over a ``with`` block:

- per-entry-point compiles, via the counting wrappers
  ``register_entry_point`` returns: each call reads the wrapped
  ``PjitFunction._cache_size()`` before/after and credits the growth
  (one cache entry == one compile == one distinct (shape, static-args)
  key — so "more than B new entries" is exactly "retraced beyond
  budget B"). Counting at CALL time, not guard exit, so per-engine
  entry points garbage-collected mid-block still count in full;
- total backend compiles, via jax's monitoring event
  ``/jax/core/compile/backend_compile_duration`` (catches compiles in
  UNregistered functions too).

tests/conftest.py wraps every tier-1 test in a guard with the budgets
declared in ``config.RETRACE_BUDGETS``; ``bench.py`` records the
compile counts of each measured workload alongside its throughput.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
import weakref
from typing import Any, Callable, Dict, Iterator, Optional

import jax


@contextlib.contextmanager
def phase_timer(sink, field: str, fence=None) -> Iterator[None]:
    """Accumulate fenced wall seconds into ``sink.<field>``.

    ``fence`` is an optional array/pytree to ``block_until_ready``
    before taking the closing timestamp.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if fence is not None:
            jax.block_until_ready(fence)
        setattr(sink, field, getattr(sink, field) + time.perf_counter() - t0)


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[None]:
    """Capture an XLA profiler trace around the block.

    No-op when log_dir is None so call sites can be left in place.
    """
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# Retrace tripwire
# ---------------------------------------------------------------------------

class RetraceBudgetExceeded(RuntimeError):
    """An entry point compiled more than its declared budget allows."""


_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
# With a persistent compilation cache armed (jax_compilation_cache_dir
# — the test harness arms one so subprocess-driver tests reuse the
# parent's compiles), the first in-process materialization of a
# program can arrive as a disk retrieval instead of a backend compile,
# and jax then emits this duration event INSTEAD of the one above. For
# retrace accounting both mean the same thing — one distinct
# (shape, static-args) program key materialized — so both count.
_CACHE_RETRIEVAL_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"

# Name -> cumulative compiles observed through that name's counting
# wrappers (monotonic; guards diff snapshots of this). Call-time
# accounting rather than cache-size sampling because per-engine entry
# points (the partitioned phase/locate closures) are garbage-collected
# with their engine — usually BEFORE a surrounding guard exits (test
# locals die at function return, fixture teardown runs after), so any
# exit-time cache-size read would miss their compiles entirely.
_COMPILE_COUNTS: Dict[str, int] = {}
# Name -> list of weakrefs to every registrant (introspection only; the
# counts above are authoritative). Weak so the registry never keeps a
# dead engine's compiled programs alive.
_ENTRY_POINTS: Dict[str, list] = {}

_global_compiles = 0
_listener_installed = False


def _on_compile_duration(event: str, duration: float, **kwargs: Any) -> None:
    global _global_compiles
    if event in (_COMPILE_DURATION_EVENT, _CACHE_RETRIEVAL_EVENT):
        _global_compiles += 1


def _ensure_compile_listener() -> None:
    """Install the (process-global, never removed) compile counter."""
    global _listener_installed
    if _listener_installed:
        return
    from jax._src import monitoring

    monitoring.register_event_duration_secs_listener(_on_compile_duration)
    _listener_installed = True


def compile_count() -> int:
    """Total backend compiles observed since the listener went in.

    Only deltas are meaningful (compiles before the first
    ``retrace_guard``/``compile_count`` call are not seen).
    """
    _ensure_compile_listener()
    return _global_compiles


class _CountingEntryPoint:
    """Transparent call-counting proxy around one jitted callable.

    Each ``__call__`` reads the wrapped jit cache size before and after
    and credits the growth (== compiles this call caused: one cache
    entry per distinct (shape, static-args) key) to the entry point's
    global counter — two C-level getter calls per dispatch, noise next
    to staging a buffer. Everything else (``.lower``, ``._cache_size``,
    …) delegates to the wrapped function.
    """

    __slots__ = ("_fn", "_name")

    def __init__(self, name: str, fn: Callable) -> None:
        self._fn = fn
        self._name = name

    def __call__(self, *args: Any, **kwargs: Any):
        before = self._fn._cache_size()
        try:
            return self._fn(*args, **kwargs)
        finally:
            grew = self._fn._cache_size() - before
            if grew > 0:
                _COMPILE_COUNTS[self._name] = (
                    _COMPILE_COUNTS.get(self._name, 0) + grew
                )

    def __getattr__(self, attr: str):
        return getattr(self._fn, attr)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"<entry point {self._name!r}: {self._fn!r}>"


def register_entry_point(name: str, fn: Callable) -> Callable:
    """Wrap ``fn`` for per-call retrace accounting under ``name``.

    ``fn`` must expose ``_cache_size()`` (any ``jax.jit`` product
    does). Returns the counting wrapper — call sites MUST adopt the
    return value (``step = register_entry_point("walk", step)``), or
    their compiles go uncounted. Several live registrants may share one
    name (the partitioned engines build a fresh jitted phase per
    (engine, config-key)); the name's counter sums them.
    """
    if isinstance(fn, _CountingEntryPoint):
        return fn  # idempotent
    if not hasattr(fn, "_cache_size"):
        raise TypeError(
            f"entry point {name!r}: {fn!r} has no _cache_size(); "
            "register the jax.jit-wrapped callable, not the python fn"
        )
    _COMPILE_COUNTS.setdefault(name, 0)
    refs = _ENTRY_POINTS.setdefault(name, [])
    refs[:] = [r for r in refs if r() is not None]
    refs.append(weakref.ref(fn))
    return _CountingEntryPoint(name, fn)


def entry_point_names() -> list:
    return sorted(_ENTRY_POINTS)


@dataclasses.dataclass
class RetraceReport:
    """What compiled during one ``retrace_guard`` block.

    ``compiles``: per-entry-point compiles observed by the counting
    wrappers (one per NEW jit cache entry == one per distinct (shape,
    static-args) key), counted at call time so entry points whose
    engine dies inside the block still count in full.
    ``total_compiles``: backend compiles from any function, registered
    or not. ``exceeded``: name -> (compiles, budget) for every budget
    overrun.
    """

    compiles: Dict[str, int] = dataclasses.field(default_factory=dict)
    total_compiles: int = 0
    exceeded: Dict[str, tuple] = dataclasses.field(default_factory=dict)

    def render(self) -> str:
        per = ", ".join(
            f"{k}={v}" for k, v in sorted(self.compiles.items())
        ) or "none"
        return (
            f"compiles: total={self.total_compiles}, per entry point: {per}"
        )


@contextlib.contextmanager
def retrace_guard(
    budgets: Optional[Dict[str, int]] = None,
    raise_on_exceed: bool = True,
) -> Iterator[RetraceReport]:
    """Count jit compiles over the block; enforce per-entry budgets.

    ``budgets`` maps entry-point names (``register_entry_point``) to
    the maximum NEW compiles allowed; names without a budget are
    counted but never fail. The special key ``"total"`` bounds
    ``total_compiles``. With ``raise_on_exceed`` (default) a breach
    raises ``RetraceBudgetExceeded`` — but never while another
    exception is already unwinding. Pass ``raise_on_exceed=False`` to
    only record breaches in ``report.exceeded`` (the conftest fixture
    does, to turn them into test failures with context).
    """
    _ensure_compile_listener()
    before = dict(_COMPILE_COUNTS)
    total_before = _global_compiles
    report = RetraceReport()
    ok = False
    try:
        yield report
        ok = True
    finally:
        report.total_compiles = _global_compiles - total_before
        for name, count in _COMPILE_COUNTS.items():
            delta = count - before.get(name, 0)
            if delta > 0:
                report.compiles[name] = delta
        for name, budget in (budgets or {}).items():
            got = (
                report.total_compiles
                if name == "total"
                else report.compiles.get(name, 0)
            )
            if got > budget:
                report.exceeded[name] = (got, budget)
        if ok and report.exceeded and raise_on_exceed:
            detail = ", ".join(
                f"{n}: {g} compiles > budget {b}"
                for n, (g, b) in sorted(report.exceeded.items())
            )
            raise RetraceBudgetExceeded(
                f"retrace budget exceeded ({detail}). A healthy entry "
                "point compiles once per distinct (shape, static-args) "
                "key; growth beyond the declared budget means the jit "
                "cache key is unstable (see jaxlint rule JL004 and "
                "docs/STATIC_ANALYSIS.md)."
            )
