"""Profiling: fenced phase timers + XLA trace capture.

The reference's instrumentation is wall-clock only, and its intended
``Kokkos::fence()`` before timestamps never fires due to a macro-name
mismatch (SURVEY.md §5) — so its device timing is unfenced as shipped.
Here ``phase_timer`` always fences with ``block_until_ready``, and
``trace`` wraps ``jax.profiler`` for real XLA timeline capture
(view with TensorBoard / xprof).
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def phase_timer(sink, field: str, fence=None) -> Iterator[None]:
    """Accumulate fenced wall seconds into ``sink.<field>``.

    ``fence`` is an optional array/pytree to ``block_until_ready``
    before taking the closing timestamp.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if fence is not None:
            jax.block_until_ready(fence)
        setattr(sink, field, getattr(sink, field) + time.perf_counter() - t0)


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[None]:
    """Capture an XLA profiler trace around the block.

    No-op when log_dir is None so call sites can be left in place.
    """
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
