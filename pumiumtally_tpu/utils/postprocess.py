"""Label-wise tally reductions (per-cell / per-material summaries).

The reference emits only the per-element flux field (VTK cell data,
reference PumiTallyImpl.cpp:411-416); physics users then want it
reduced over labels — per-pincell powers across an assembly, fuel vs
moderator averages. These helpers do that reduction against any
integer element labeling (the ``region`` / ``cell_id`` arrays the mesh
generators return, or a ``class_id`` tag read from an ``.osh`` file),
as deterministic ``segment_sum``-style bincounts.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _check(labels: np.ndarray, n: int, name: str) -> np.ndarray:
    lab = np.asarray(labels).reshape(-1)
    if lab.shape[0] != n:
        raise ValueError(f"{name} has {lab.shape[0]} entries for {n} elements")
    if not np.issubdtype(lab.dtype, np.integer):
        # A float tag (e.g. read back from VTK cell data) must be
        # exactly integral — truncation would silently re-bin elements.
        as_int = lab.astype(np.int64)
        if not np.array_equal(as_int, lab):
            raise ValueError(f"{name} must hold integral values")
        lab = as_int
    if lab.size and lab.min() < 0:
        raise ValueError(f"{name} must be non-negative integers")
    return lab.astype(np.int64)


def label_totals(
    flux: np.ndarray,
    volumes: np.ndarray,
    labels: np.ndarray,
    num_labels: int = 0,
) -> np.ndarray:
    """Integrated tally per label: ``sum(flux_e · volume_e)`` over the
    elements carrying each label — with ``flux`` the volume-normalized
    field the engine reports (``normalized_flux``), this is the total
    track length (∝ reaction-rate integral) per pincell / material.
    Returns [max(max(label)+1, num_labels)] float64, zeros for unused
    labels — pass ``num_labels`` (e.g. nx·ny) so trailing empty labels
    keep their slots when reducing a slice."""
    flux = np.asarray(flux, np.float64).reshape(-1)
    vol = np.asarray(volumes, np.float64).reshape(-1)
    lab = _check(labels, flux.shape[0], "labels")
    if vol.shape[0] != flux.shape[0]:
        raise ValueError(
            f"volumes has {vol.shape[0]} entries for {flux.shape[0]} elements"
        )
    return np.bincount(lab, weights=flux * vol, minlength=num_labels)


def label_averages(
    flux: np.ndarray,
    volumes: np.ndarray,
    labels: np.ndarray,
    num_labels: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """(volume-weighted mean flux per label, total volume per label).
    Labels with zero volume report a zero mean (not NaN)."""
    flux = np.asarray(flux, np.float64).reshape(-1)
    vol = np.asarray(volumes, np.float64).reshape(-1)
    lab = _check(labels, flux.shape[0], "labels")
    if vol.shape[0] != flux.shape[0]:
        raise ValueError(
            f"volumes has {vol.shape[0]} entries for {flux.shape[0]} elements"
        )
    totals = np.bincount(lab, weights=flux * vol, minlength=num_labels)
    vols = np.bincount(lab, weights=vol, minlength=num_labels)
    mean = np.divide(
        totals, vols, out=np.zeros_like(totals), where=vols > 0
    )
    return mean, vols
