"""Single-client interlock for the accelerator tunnel.

The round-4 capture was contaminated: a verify-drive opened a second
TPU client during bench.py's measurement window and the contended
two_phase row read 0.963M moves/s vs 1.102M clean (docs/PERF_NOTES.md).
Worse, a second client has historically wedged the tunnel outright.

This is a cooperative flock(2) interlock every chip-touching tool takes
around its device window:

- ``bench.py`` holds it exclusively for the whole measurement;
- drive/probe scripts take it (or skip, for probes) before dialing;
- shell tools use ``flock <LOCK_PATH> cmd`` — same file, same
  semantics.

Reentrancy: in-process nesting is tracked by a module-level flag
(``_held_in_process``) — flock(2) is per-open-file, so a second
acquire in the same process would self-deadlock without it. A holder
ALSO exports ``PUMIUMTALLY_CHIP_LOCK_HELD=1``, which exists purely for
CHILD-PROCESS inheritance (bench's vmem child, ``flock`` shell tools):
children see the env var and skip re-acquiring the parent's window.
The env var is not consulted as this process's own state beyond that —
a stale value inherited from a crashed parent shell is honored as "a
parent holds the window", which is exactly its meaning. The lock
protects a *window*, not correctness — a non-cooperating process can
still dial the tunnel; the interlock makes the in-repo tools honest
with each other.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager

LOCK_PATH = os.environ.get(
    "PUMIUMTALLY_CHIP_LOCK", "/tmp/pumiumtally_chip.lock"
)
_HELD_ENV = "PUMIUMTALLY_CHIP_LOCK_HELD"
# THIS process already holds the lock (nested chip_lock contexts).
# Module state, not the env var: os.environ is process-global mutable
# state that anything (a test harness, a driver) may scrub mid-window,
# and the env var's documented meaning is child-inheritance only.
_held_in_process = False


@contextmanager
def chip_lock(timeout_s: float | None = None, *, blocking: bool = True):
    """Acquire the accelerator window lock.

    Yields True when the lock is held (or inherited from a parent
    holder/outer context), False when ``blocking=False``/timeout
    expired and the lock is busy — the caller decides whether to skip
    or proceed unlocked.
    """
    global _held_in_process
    if _held_in_process:
        yield True  # an outer context in this process owns the window
        return
    if os.environ.get(_HELD_ENV) == "1":
        yield True  # a parent process owns the window (inherited env)
        return
    try:
        import fcntl
    except ImportError:  # non-POSIX: interlock degrades to a no-op
        yield True
        return
    fd = os.open(LOCK_PATH, os.O_CREAT | os.O_RDWR, 0o666)
    acquired = False
    try:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                acquired = True
                break
            except OSError:
                if not blocking or (
                    deadline is not None and time.monotonic() >= deadline
                ):
                    break
                time.sleep(1.0)
        if acquired:
            _held_in_process = True
            os.environ[_HELD_ENV] = "1"  # for child processes only
        try:
            yield acquired
        finally:
            if acquired:
                _held_in_process = False
                os.environ.pop(_HELD_ENV, None)
                fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)
