"""Leveled logging with the reference's message style.

The reference logs with raw printf and ``[INFO]``/``[ERROR]``/``[TIME]``
prefixes and no verbosity control (reference PumiTallyImpl.cpp:23-28,
292-294, 445, 536). We keep the exact prefix style — host-app log
scrapers keyed on it keep working — but route through ``logging`` with
a settable level (env ``PUMIUMTALLY_LOG`` or ``set_verbosity``).
"""

from __future__ import annotations

import logging
import os
import sys

_LOGGER_NAME = "pumiumtally_tpu"
_PREFIXES = {
    logging.DEBUG: "[DEBUG]",
    logging.INFO: "[INFO]",
    logging.WARNING: "[WARNING]",
    logging.ERROR: "[ERROR]",
    logging.CRITICAL: "[CRITICAL]",
}


class _PrefixFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        prefix = _PREFIXES.get(record.levelno, f"[{record.levelname}]")
        return f"{prefix} {record.getMessage()}"


class _CurrentStderrHandler(logging.StreamHandler):
    """StreamHandler resolving ``sys.stderr`` at EMIT time.

    The module logger installs its handler once per process; a handler
    holding the stream OBJECT captured at that moment writes past any
    later ``sys.stderr`` replacement — pytest's capsys among them, which
    made test outcomes depend on whether an earlier test had already
    touched the logger (e.g. the bf16 block-kernel reroute logs during
    engine construction)."""

    def __init__(self) -> None:
        super().__init__(sys.stderr)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value) -> None:
        # The base __init__ (and setStream) assign the captured object;
        # discard it — the property above always answers with the
        # CURRENT sys.stderr.
        pass


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = _CurrentStderrHandler()
        handler.setFormatter(_PrefixFormatter())
        logger.addHandler(handler)
        logger.propagate = False
        level = os.environ.get("PUMIUMTALLY_LOG", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
    return logger


def set_verbosity(level: str) -> None:
    """'DEBUG' | 'INFO' | 'WARNING' | 'ERROR' | 'CRITICAL'."""
    get_logger().setLevel(getattr(logging, level.upper()))
