"""Leveled logging with the reference's message style.

The reference logs with raw printf and ``[INFO]``/``[ERROR]``/``[TIME]``
prefixes and no verbosity control (reference PumiTallyImpl.cpp:23-28,
292-294, 445, 536). We keep the exact prefix style — host-app log
scrapers keyed on it keep working — but route through ``logging`` with
a settable level (env ``PUMIUMTALLY_LOG`` or ``set_verbosity``).
"""

from __future__ import annotations

import logging
import os
import sys

_LOGGER_NAME = "pumiumtally_tpu"
_PREFIXES = {
    logging.DEBUG: "[DEBUG]",
    logging.INFO: "[INFO]",
    logging.WARNING: "[WARNING]",
    logging.ERROR: "[ERROR]",
    logging.CRITICAL: "[CRITICAL]",
}


class _PrefixFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        prefix = _PREFIXES.get(record.levelno, f"[{record.levelname}]")
        return f"{prefix} {record.getMessage()}"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_PrefixFormatter())
        logger.addHandler(handler)
        logger.propagate = False
        level = os.environ.get("PUMIUMTALLY_LOG", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
    return logger


def set_verbosity(level: str) -> None:
    """'DEBUG' | 'INFO' | 'WARNING' | 'ERROR' | 'CRITICAL'."""
    get_logger().setLevel(getattr(logging, level.upper()))
