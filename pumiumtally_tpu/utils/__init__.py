"""Aux subsystems: logging, profiling, checkpoint/resume, autotuning
(SURVEY.md §5)."""

from pumiumtally_tpu.utils.autotune import autotune_walk
from pumiumtally_tpu.utils.logging import get_logger, set_verbosity
from pumiumtally_tpu.utils.profiling import phase_timer, trace
from pumiumtally_tpu.utils.checkpoint import (
    CorruptCheckpointError,
    load_tally_state,
    save_tally_state,
)

__all__ = [
    "autotune_walk",
    "get_logger",
    "set_verbosity",
    "phase_timer",
    "trace",
    "save_tally_state",
    "load_tally_state",
    "CorruptCheckpointError",
]
