"""Walk-kernel autotuner: measure, pick, return a tuned ``TallyConfig``.

The walk kernel's throughput knobs (``TallyConfig.walk_*`` —
``cond_every`` unroll depth, cascade permutation strategy, window
shrink ratio, smallest window) have no universally best setting: the
optimum depends on the backend (TPU generation vs CPU), the mesh size
(gather-table locality), and the step-length distribution (how fast
the active set decays). The reference hard-codes its equivalents
(Kokkos launch parameters); here the deployment can measure instead of
guess — the same philosophy as XLA's own gemm autotuning.

``autotune_walk`` times a short, synthetic-but-representative workload
(same shape as bench.py's: uniform interior sources, clipped gaussian
steps) for each candidate configuration ON THE CURRENT BACKEND and
returns the fastest as a ready-to-use ``TallyConfig``. Results are
correctness-invariant by construction: every candidate runs the same
bitwise-specified walk (permutation modes are bitwise-identical;
cond_every/window changes only reorder the flux scatter within FP
tolerance), so tuning can never change physics.

Typical use (once per deployment/mesh class, ~a minute on a TPU):

    from pumiumtally_tpu.utils.autotune import autotune_walk
    cfg, report = autotune_walk(mesh, n_particles=200_000)
    tally = PumiTally(mesh, n, cfg)

Pass ``candidates=`` to sweep a custom grid, and ``base=`` to tune on
top of an existing config (device mesh, tolerances etc. are preserved).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from pumiumtally_tpu.config import TallyConfig

# Default grid: the configurations that showed up as winners or
# near-winners in the round-2/3 measurements (docs/PERF_NOTES.md).
# Small on purpose — autotuning pays one jit compile per entry.
DEFAULT_CANDIDATES: Tuple[dict, ...] = (
    {"walk_perm_mode": "packed", "walk_cond_every": 4},
    {"walk_perm_mode": "packed", "walk_cond_every": 8},
    {"walk_perm_mode": "indirect", "walk_cond_every": 4},
    {"walk_perm_mode": "packed", "walk_cond_every": 4,
     "walk_window_factor": 4},
    {"walk_perm_mode": "indirect", "walk_cond_every": 4,
     "walk_window_factor": 4},
    {"walk_perm_mode": "arrays", "walk_cond_every": 4},
    # Corners the CPU-tuned set above does not reach, in case the
    # on-chip optimum sits outside it: a coarse cascade with a large
    # unroll (fewest while-loop conds AND fewest stage boundaries),
    # and no cascade at all (pure lock-step — wins if compaction's
    # permutes cost more than the lock-step waste on this backend).
    {"walk_perm_mode": "packed", "walk_cond_every": 8,
     "walk_window_factor": 8},
    {"walk_cond_every": 4, "walk_min_window": 1 << 30},
    # Round-4 first on-chip capture: indirect won bench's runtime sweep
    # (1.09M moves/s) while packed's best static corner was cond_every
    # 8 — probe their combination too (tools/r4_onchip/digest.md).
    {"walk_perm_mode": "indirect", "walk_cond_every": 8},
    # Redistribution axis (this PR): the default stage boundary is now
    # the sort-free counting-rank done-partition. "sorted" restores the
    # element-locality argsort (r2 measured the locality worth ~1.03x —
    # worth re-probing against the saved argsort cost per chip), and
    # the argsort partition_method keeps the binary partition but
    # computes it with the old sort (isolates rank-vs-sort compute from
    # the locality effect).
    {"walk_perm_mode": "sorted", "walk_cond_every": 4},
    {"walk_perm_mode": "packed", "walk_cond_every": 4,
     "walk_partition_method": "argsort"},
    # Table-precision axis (two-tier bf16 select + f32 refine,
    # docs/PERF_NOTES.md "Table precision tiers"): measured in every
    # sweep so the chip window records the byte-halving's real rate,
    # but adopted as the winner only under allow_approximate=True —
    # the tier is NOT bitwise vs f32 (benign tie-class divergence),
    # and autotune's default contract is that tuning never changes
    # physics.
    {"walk_table_dtype": "bfloat16", "walk_cond_every": 4},
)

# Knobs that change results beyond bitwise/scatter-order equivalence;
# adopting one as the tuned winner needs the caller's explicit opt-in.
_APPROXIMATE_KNOBS = ("walk_table_dtype",)


def _is_approximate(knobs: dict) -> bool:
    return any(
        knobs.get(k) not in (None, "float32") for k in _APPROXIMATE_KNOBS
    )


def _workload(mesh, n: int, moves: int, mean_step: float, seed: int):
    """bench.py-shaped trajectory strictly inside the mesh's bbox."""
    import jax.numpy as jnp

    coords = np.asarray(mesh.coords, np.float64)
    lo, hi = coords.min(axis=0), coords.max(axis=0)
    span = hi - lo
    rng = np.random.default_rng(seed)
    pts = [lo + rng.uniform(0.05, 0.95, (n, 3)) * span]
    for _ in range(moves + 1):
        step = rng.normal(scale=mean_step / np.sqrt(3.0), size=(n, 3)) * span
        pts.append(np.clip(pts[-1] + step, lo + 0.02 * span, hi - 0.02 * span))
    dt = mesh.coords.dtype
    return [jnp.asarray(p, dt) for p in pts]


def autotune_walk(
    mesh,
    n_particles: int = 200_000,
    moves: int = 3,
    mean_step: float = 0.25,
    candidates: Optional[Sequence[dict]] = None,
    base: Optional[TallyConfig] = None,
    seed: int = 0,
    verbose: bool = False,
    allow_approximate: bool = False,
) -> Tuple[TallyConfig, List[dict]]:
    """Measure each candidate's continue-mode walk rate on the current
    backend; return (best TallyConfig, full report).

    ``mesh`` is a ``TetMesh`` (or anything ``build_box`` etc. return).
    The report is a list of ``{"knobs", "moves_per_sec"}`` dicts sorted
    fastest-first; the fastest ADOPTABLE entry produced the returned
    config: approximate-tier candidates (walk_table_dtype="bfloat16")
    are always measured and reported, but only adopted when
    ``allow_approximate=True`` — otherwise the returned config keeps
    the never-changes-physics contract. The sweep uses the raw kernel
    (``ops.walk.walk``) — no facade/staging noise — with one warmup
    (compile) move per candidate and ``moves`` timed moves.
    """
    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.api.tally import _localize_step
    from pumiumtally_tpu.ops.walk import walk

    cands = list(candidates if candidates is not None else DEFAULT_CANDIDATES)
    base = base if base is not None else TallyConfig()
    pts = _workload(mesh, n_particles, moves, mean_step, seed)

    # One shared localization (identical start state for every candidate).
    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    tol = base.resolved_tolerance(mesh.coords.dtype)
    max_iters = base.resolved_max_iters(mesh.nelems)
    x0, e0, done, _ = _localize_step(
        mesh,
        jnp.broadcast_to(c0, (n_particles, 3)),
        jnp.zeros((n_particles,), jnp.int32),
        pts[0], tol=tol, max_iters=max_iters,
    )
    if not bool(jnp.all(done)):
        raise RuntimeError("autotune workload failed to localize")
    fly = jnp.ones((n_particles,), jnp.int8)
    w = jnp.ones((n_particles,), mesh.coords.dtype)

    report = []
    mesh_lo = None  # built once, only if a bf16-tier candidate runs
    for knobs in cands:
        cfg = dataclasses.replace(base, **knobs)
        kw = dict(cfg.walk_kwargs())
        if kw.get("table_dtype") == "bfloat16":
            if mesh_lo is None:
                mesh_lo = mesh.with_lowp_tables()
            m_c = mesh_lo
        else:
            m_c = mesh
        g = jax.jit(partial(
            walk, tally=True, tol=tol, max_iters=max_iters, **kw
        ))
        flux0 = jnp.zeros((mesh.nelems,), mesh.coords.dtype)
        r = g(m_c, x0, e0, pts[1], fly, w, flux0)  # warmup/compile
        float(jnp.sum(r.flux))  # sync (block_until_ready is lazy on
        x, e, flux = r.x, r.elem, r.flux  # some remote backends)
        t0 = time.perf_counter()
        for m in range(2, moves + 2):
            r = g(m_c, x, e, pts[m], fly, w, flux)
            x, e, flux = r.x, r.elem, r.flux
        float(jnp.sum(flux))
        rate = n_particles * moves / (time.perf_counter() - t0)
        report.append({"knobs": dict(knobs), "moves_per_sec": rate})
        if verbose:
            print(f"autotune: {knobs} -> {rate / 1e6:.3f}M moves/s")

    report.sort(key=lambda r: -r["moves_per_sec"])
    adoptable = [
        r for r in report
        if allow_approximate or not _is_approximate(r["knobs"])
    ]
    if not adoptable:
        # Every candidate was approximate and adoption is disallowed:
        # the sweep's rates are still in the report, but the returned
        # config stays the (physics-identical) base.
        return dataclasses.replace(base), report
    # Mark which report entry produced the returned config — with
    # approximate candidates in the sweep, report[0] may NOT be the
    # adopted winner, and provenance printers must pair the adopted
    # settings with the adopted entry's rate, not the sweep-fastest's.
    adoptable[0]["adopted"] = True
    best = dataclasses.replace(base, **_drop_defaults(adoptable[0]["knobs"]))
    return best, report


def _drop_defaults(knobs: dict) -> dict:
    """Strip knobs whose value equals the kernel default: the returned
    config must keep ``walk_kwargs() == ()`` whenever the winner is
    computationally identical to untuned (config.py engineered that so
    tuned and untuned tallies share jit cache entries)."""
    from pumiumtally_tpu.ops.walk import (
        _MIN_WINDOW,
        _resolve_perm_mode,
        COND_EVERY_DEFAULT,
        WINDOW_FACTOR_DEFAULT,
    )

    out = dict(knobs)
    if out.get("walk_cond_every") == COND_EVERY_DEFAULT:
        out.pop("walk_cond_every")
    if out.get("walk_window_factor") == WINDOW_FACTOR_DEFAULT:
        out.pop("walk_window_factor")
    if out.get("walk_min_window") == _MIN_WINDOW:
        out.pop("walk_min_window")
    if out.get("walk_partition_method") == "rank":
        out.pop("walk_partition_method")
    if "walk_perm_mode" in out and out["walk_perm_mode"] == _resolve_perm_mode(
        "auto"
    ):
        out.pop("walk_perm_mode")
    if out.get("walk_table_dtype") == "float32":
        out.pop("walk_table_dtype")
    return out
