"""Walk-kernel autotuner: measure, pick, return a tuned ``TallyConfig``.

The walk kernel's throughput knobs (``TallyConfig.walk_*`` —
``cond_every`` unroll depth, cascade permutation strategy, window
shrink ratio, smallest window) have no universally best setting: the
optimum depends on the backend (TPU generation vs CPU), the mesh size
(gather-table locality), and the step-length distribution (how fast
the active set decays). The reference hard-codes its equivalents
(Kokkos launch parameters); here the deployment can measure instead of
guess — the same philosophy as XLA's own gemm autotuning.

``autotune_walk`` times a short, synthetic-but-representative workload
(same shape as bench.py's: uniform interior sources, clipped gaussian
steps) for each candidate configuration ON THE CURRENT BACKEND and
returns the fastest as a ready-to-use ``TallyConfig``. Results are
correctness-invariant by construction: every candidate runs the same
bitwise-specified walk (permutation modes are bitwise-identical;
cond_every/window changes only reorder the flux scatter within FP
tolerance), so tuning can never change physics.

Typical use (once per deployment/mesh class, ~a minute on a TPU):

    from pumiumtally_tpu.utils.autotune import autotune_walk
    cfg, report = autotune_walk(mesh, n_particles=200_000)
    tally = PumiTally(mesh, n, cfg)

Pass ``candidates=`` to sweep a custom grid, and ``base=`` to tune on
top of an existing config (device mesh, tolerances etc. are preserved).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from pumiumtally_tpu.config import TallyConfig

# Default grid: the configurations that showed up as winners or
# near-winners in the round-2/3 measurements (docs/PERF_NOTES.md).
# Small on purpose — autotuning pays one jit compile per entry.
DEFAULT_CANDIDATES: Tuple[dict, ...] = (
    {"walk_perm_mode": "packed", "walk_cond_every": 4},
    {"walk_perm_mode": "packed", "walk_cond_every": 8},
    {"walk_perm_mode": "indirect", "walk_cond_every": 4},
    {"walk_perm_mode": "packed", "walk_cond_every": 4,
     "walk_window_factor": 4},
    {"walk_perm_mode": "indirect", "walk_cond_every": 4,
     "walk_window_factor": 4},
    {"walk_perm_mode": "arrays", "walk_cond_every": 4},
    # Corners the CPU-tuned set above does not reach, in case the
    # on-chip optimum sits outside it: a coarse cascade with a large
    # unroll (fewest while-loop conds AND fewest stage boundaries),
    # and no cascade at all (pure lock-step — wins if compaction's
    # permutes cost more than the lock-step waste on this backend).
    {"walk_perm_mode": "packed", "walk_cond_every": 8,
     "walk_window_factor": 8},
    {"walk_cond_every": 4, "walk_min_window": 1 << 30},
    # Round-4 first on-chip capture: indirect won bench's runtime sweep
    # (1.09M moves/s) while packed's best static corner was cond_every
    # 8 — probe their combination too (tools/r4_onchip/digest.md).
    {"walk_perm_mode": "indirect", "walk_cond_every": 8},
    # Redistribution axis (this PR): the default stage boundary is now
    # the sort-free counting-rank done-partition. "sorted" restores the
    # element-locality argsort (r2 measured the locality worth ~1.03x —
    # worth re-probing against the saved argsort cost per chip), and
    # the argsort partition_method keeps the binary partition but
    # computes it with the old sort (isolates rank-vs-sort compute from
    # the locality effect).
    {"walk_perm_mode": "sorted", "walk_cond_every": 4},
    {"walk_perm_mode": "packed", "walk_cond_every": 4,
     "walk_partition_method": "argsort"},
    # Table-precision axis (two-tier bf16 select + f32 refine,
    # docs/PERF_NOTES.md "Table precision tiers"): measured in every
    # sweep so the chip window records the byte-halving's real rate,
    # but adopted as the winner only under allow_approximate=True —
    # the tier is NOT bitwise vs f32 (benign tie-class divergence),
    # and autotune's default contract is that tuning never changes
    # physics.
    {"walk_table_dtype": "bfloat16", "walk_cond_every": 4},
)

# Knobs that change results beyond bitwise/scatter-order equivalence;
# adopting one as the tuned winner needs the caller's explicit opt-in.
_APPROXIMATE_KNOBS = ("walk_table_dtype",)


def _is_approximate(knobs: dict) -> bool:
    return any(
        knobs.get(k) not in (None, "float32") for k in _APPROXIMATE_KNOBS
    )


def _workload(mesh, n: int, moves: int, mean_step: float, seed: int):
    """bench.py-shaped trajectory strictly inside the mesh's bbox."""
    import jax.numpy as jnp

    coords = np.asarray(mesh.coords, np.float64)
    lo, hi = coords.min(axis=0), coords.max(axis=0)
    span = hi - lo
    rng = np.random.default_rng(seed)
    pts = [lo + rng.uniform(0.05, 0.95, (n, 3)) * span]
    for _ in range(moves + 1):
        step = rng.normal(scale=mean_step / np.sqrt(3.0), size=(n, 3)) * span
        pts.append(np.clip(pts[-1] + step, lo + 0.02 * span, hi - 0.02 * span))
    dt = mesh.coords.dtype
    return [jnp.asarray(p, dt) for p in pts]


def autotune_walk(
    mesh,
    n_particles: int = 200_000,
    moves: int = 3,
    mean_step: float = 0.25,
    candidates: Optional[Sequence[dict]] = None,
    base: Optional[TallyConfig] = None,
    seed: int = 0,
    verbose: bool = False,
    allow_approximate: bool = False,
) -> Tuple[TallyConfig, List[dict]]:
    """Measure each candidate's continue-mode walk rate on the current
    backend; return (best TallyConfig, full report).

    ``mesh`` is a ``TetMesh`` (or anything ``build_box`` etc. return).
    The report is a list of ``{"knobs", "moves_per_sec"}`` dicts sorted
    fastest-first; the fastest ADOPTABLE entry produced the returned
    config: approximate-tier candidates (walk_table_dtype="bfloat16")
    are always measured and reported, but only adopted when
    ``allow_approximate=True`` — otherwise the returned config keeps
    the never-changes-physics contract. The sweep uses the raw kernel
    (``ops.walk.walk``) — no facade/staging noise — with one warmup
    (compile) move per candidate and ``moves`` timed moves.
    """
    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.api.tally import _localize_step
    from pumiumtally_tpu.ops.walk import walk

    cands = list(candidates if candidates is not None else DEFAULT_CANDIDATES)
    base = base if base is not None else TallyConfig()
    pts = _workload(mesh, n_particles, moves, mean_step, seed)

    # One shared localization (identical start state for every candidate).
    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    tol = base.resolved_tolerance(mesh.coords.dtype)
    max_iters = base.resolved_max_iters(mesh.nelems)
    x0, e0, done, _ = _localize_step(
        mesh,
        jnp.broadcast_to(c0, (n_particles, 3)),
        jnp.zeros((n_particles,), jnp.int32),
        pts[0], tol=tol, max_iters=max_iters,
    )
    if not bool(jnp.all(done)):
        raise RuntimeError("autotune workload failed to localize")
    fly = jnp.ones((n_particles,), jnp.int8)
    w = jnp.ones((n_particles,), mesh.coords.dtype)

    report = []
    mesh_lo = None  # built once, only if a bf16-tier candidate runs
    for knobs in cands:
        cfg = dataclasses.replace(base, **knobs)
        kw = dict(cfg.walk_kwargs())
        if kw.get("table_dtype") == "bfloat16":
            if mesh_lo is None:
                mesh_lo = mesh.with_lowp_tables()
            m_c = mesh_lo
        else:
            m_c = mesh
        g = jax.jit(partial(
            walk, tally=True, tol=tol, max_iters=max_iters, **kw
        ))
        flux0 = jnp.zeros((mesh.nelems,), mesh.coords.dtype)
        r = g(m_c, x0, e0, pts[1], fly, w, flux0)  # warmup/compile
        float(jnp.sum(r.flux))  # sync (block_until_ready is lazy on
        x, e, flux = r.x, r.elem, r.flux  # some remote backends)
        t0 = time.perf_counter()
        for m in range(2, moves + 2):
            r = g(m_c, x, e, pts[m], fly, w, flux)
            x, e, flux = r.x, r.elem, r.flux
        float(jnp.sum(flux))
        rate = n_particles * moves / (time.perf_counter() - t0)
        report.append({"knobs": dict(knobs), "moves_per_sec": rate})
        if verbose:
            print(f"autotune: {knobs} -> {rate / 1e6:.3f}M moves/s")

    report.sort(key=lambda r: -r["moves_per_sec"])
    adoptable = [
        r for r in report
        if allow_approximate or not _is_approximate(r["knobs"])
    ]
    if not adoptable:
        # Every candidate was approximate and adoption is disallowed:
        # the sweep's rates are still in the report, but the returned
        # config stays the (physics-identical) base.
        return dataclasses.replace(base), report
    # Mark which report entry produced the returned config — with
    # approximate candidates in the sweep, report[0] may NOT be the
    # adopted winner, and provenance printers must pair the adopted
    # settings with the adopted entry's rate, not the sweep-fastest's.
    adoptable[0]["adopted"] = True
    best = dataclasses.replace(base, **_drop_defaults(adoptable[0]["knobs"]))
    return best, report


def block_length_candidates(
    mesh, mean_step: float, base_bound: Optional[int] = 3072
) -> List[int]:
    """Block-length (``walk_vmem_max_elems``) candidates for the gather
    sub-split, derived from the workload's mean step length.

    The L-vs-mean-free-path law (the lattice's 45-round problem,
    docs/PERF_NOTES.md): a blocked walk round ends when a particle
    crosses a block face, so the expected migration rounds per move
    scale with mean_step / ell, where ell = (L / density)^(1/3) is the
    linear size of an L-element block at the mesh's element density.
    Small blocks buy table residency (the measured 2.2-2.4M moves/s
    small-table regime) but pay rounds; the break-even block keeps the
    expected crossings per move near one, i.e. ell ≈ mean_step ⇒
    L* = density · mean_step³. The candidate grid brackets L* one
    octave each way (the law fixes the scale, not the constant — the
    round cost vs residency trade is backend-measured, never guessed),
    keeps the configured base bound as the incumbent, and clips to
    [256, nelems/2] so every candidate actually sub-splits and no
    block degenerates below a VPU-lane-scale table.
    """
    coords = np.asarray(mesh.coords, np.float64)
    span = coords.max(axis=0) - coords.min(axis=0)
    vol = float(np.prod(np.maximum(span, 1e-30)))
    density = mesh.nelems / vol
    l_star = density * float(mean_step) ** 3
    lo, hi = 256, max(256, int(mesh.nelems) // 2)
    cands = {int(np.clip(round(l_star * f), lo, hi)) for f in (0.5, 1.0, 2.0)}
    if base_bound is not None:
        cands.add(int(np.clip(int(base_bound), lo, hi)))
    return sorted(cands)


def autotune_blocked(
    mesh,
    n_particles: int = 100_000,
    moves: int = 2,
    mean_step: float = 0.25,
    candidates: Optional[Sequence[int]] = None,
    base: Optional[TallyConfig] = None,
    seed: int = 0,
    verbose: bool = False,
    _measure=None,
) -> Tuple[TallyConfig, List[dict]]:
    """Measure gather-blocked engines over block-length candidates;
    adopt a candidate ONLY when it beats the incumbent configuration.

    The incumbent is ``base`` itself (its ``walk_vmem_max_elems``, or
    the unblocked engine when unset) — swept alongside the
    ``block_length_candidates`` grid, so the returned config can only
    change when a candidate measured strictly faster on THIS backend
    and workload: the law above picks the grid, the measurement picks
    the winner, and a wash keeps the incumbent (the same
    never-adopt-on-faith contract as ``autotune_walk``'s approximate
    tier). Physics is unchanged by construction — block length moves
    the walk/migrate round schedule, not the tally (the engines'
    conservation gates apply unchanged).

    Returns (config, report); report rows are
    ``{"walk_vmem_max_elems", "moves_per_sec", ["adopted"|"incumbent"]}``
    sorted fastest-first. ``_measure`` (tests) overrides the per-config
    rate measurement.
    """
    base = base if base is not None else TallyConfig()
    incumbent = (
        None if base.walk_vmem_max_elems is None
        else int(base.walk_vmem_max_elems)
    )
    if candidates is None:
        candidates = block_length_candidates(
            mesh, mean_step, base_bound=incumbent
        )
    bounds = list(dict.fromkeys(
        None if b is None else int(b)
        for b in list(candidates) + [incumbent]
    ))

    if _measure is None:
        _measure = partial(
            _blocked_rate, mesh, n_particles, moves, mean_step, seed
        )
    report = []
    for b in bounds:
        cfg = dataclasses.replace(
            base, walk_vmem_max_elems=b,
            walk_block_kernel="gather" if b is not None
            else base.walk_block_kernel,
        )
        rate = _measure(cfg)
        row = {"walk_vmem_max_elems": b, "moves_per_sec": rate}
        if b == incumbent:
            row["incumbent"] = True
        report.append(row)
        if verbose:
            print(f"autotune_blocked: L<={b} -> {rate / 1e6:.3f}M moves/s")
    report.sort(key=lambda r: -r["moves_per_sec"])
    inc_rate = next(
        r["moves_per_sec"] for r in report if r.get("incumbent")
    )
    best = report[0]
    if best.get("incumbent") or best["moves_per_sec"] <= inc_rate:
        return dataclasses.replace(base), report  # wash: keep incumbent
    best["adopted"] = True
    return dataclasses.replace(
        base, walk_vmem_max_elems=best["walk_vmem_max_elems"],
        walk_block_kernel="gather",
    ), report


def _blocked_rate(mesh, n: int, moves: int, mean_step: float, seed: int,
                  cfg: TallyConfig) -> float:
    """Continue-mode moves/s of one (possibly blocked) partitioned
    engine on the bench-shaped workload (warmup move excluded)."""
    import jax.numpy as jnp

    from pumiumtally_tpu.api.partitioned import PartitionedPumiTally

    cfg = dataclasses.replace(
        cfg, check_found_all=False, fenced_timing=False
    )
    pts = _workload(mesh, n, moves, mean_step, seed)
    t = PartitionedPumiTally(mesh, n, cfg)
    t.CopyInitialPosition(np.asarray(pts[0]).reshape(-1).copy())
    t.MoveToNextLocation(None, np.asarray(pts[1]).reshape(-1).copy())
    float(jnp.sum(t.flux))  # compile + sync
    t0 = time.perf_counter()
    for m in range(2, moves + 2):
        t.MoveToNextLocation(None, np.asarray(pts[m]).reshape(-1).copy())
    float(jnp.sum(t.flux))
    return n * moves / (time.perf_counter() - t0)


def _drop_defaults(knobs: dict) -> dict:
    """Strip knobs whose value equals the kernel default: the returned
    config must keep ``walk_kwargs() == ()`` whenever the winner is
    computationally identical to untuned (config.py engineered that so
    tuned and untuned tallies share jit cache entries)."""
    from pumiumtally_tpu.ops.walk import (
        _MIN_WINDOW,
        _resolve_perm_mode,
        COND_EVERY_DEFAULT,
        WINDOW_FACTOR_DEFAULT,
    )

    out = dict(knobs)
    if out.get("walk_cond_every") == COND_EVERY_DEFAULT:
        out.pop("walk_cond_every")
    if out.get("walk_window_factor") == WINDOW_FACTOR_DEFAULT:
        out.pop("walk_window_factor")
    if out.get("walk_min_window") == _MIN_WINDOW:
        out.pop("walk_min_window")
    if out.get("walk_partition_method") == "rank":
        out.pop("walk_partition_method")
    if "walk_perm_mode" in out and out["walk_perm_mode"] == _resolve_perm_mode(
        "auto"
    ):
        out.pop("walk_perm_mode")
    if out.get("walk_table_dtype") == "float32":
        out.pop("walk_table_dtype")
    return out
