"""Filtered multi-score tallies: energy/time-binned scoring lanes.

The reference accumulates exactly ONE score — track-length x weight
flux per element (reference PumiTallyImpl.cpp:352-380) — but its host
code's tally system (OpenMC, Romano et al. 2015) is built around
FILTERS and MULTIPLE SCORES: energy bins, time bins, flux/heating/
event-count scores per bin. This package adds that layer as a
segment-commit hook riding the existing walk:

- ``filters.EnergyFilter`` / ``filters.TimeFilter`` — bin-edge
  filters over new per-particle ``energy=`` / ``time=`` move inputs;
- ``scores`` — the score registry: what each score's per-segment
  contribution is (``flux`` = s·w, ``heating`` = s·w·E,
  ``events`` = face-crossing count);
- ``binding.ScoringSpec`` — the user-facing configuration
  (``TallyConfig.scoring``): filters x scores + the out-of-range
  policy knob (``drop``/``clamp``);
- ``binding.ScoringRuntime`` — the per-facade runtime: filter edges
  as DEVICE OPERANDS (edge values never enter any jit cache key —
  only the bin counts do, through array shapes), the jitted
  branchless-searchsorted bin resolution (entry point
  ``score_bins``), and the flattened ``[E·B·S]`` lane-bank layout.

The hook itself lives in ``ops/walk.py`` (and ``walk_local`` in
``parallel/partition.py``): at the same point where track-length x
weight is scattered into the flux lane, each score's segment
contribution scatters into the lane bank with ONE fused deterministic
scatter-add — the same scatter-order class as the flux lane, no
atomics. Scoring-off constructs nothing and every engine is bitwise
identical to a scoring-less build; scoring-on leaves the flux lane
bitwise too (the flux scatter is untouched) — both pinned across all
five facades in tests/test_scoring.py. docs/DESIGN.md "Filtered
scoring (round 10)".
"""

from pumiumtally_tpu.scoring.binding import (
    ScoreOps,
    ScoringRuntime,
    ScoringSpec,
)
from pumiumtally_tpu.scoring.filters import EnergyFilter, TimeFilter
from pumiumtally_tpu.scoring.scores import SCORES

__all__ = [
    "EnergyFilter",
    "TimeFilter",
    "SCORES",
    "ScoreOps",
    "ScoringRuntime",
    "ScoringSpec",
]
