"""The score registry: what each score contributes per committed walk
segment.

Every score is described by two static pieces the walk hook consumes:

- ``basis`` — what geometric quantity of the segment the score is
  proportional to:
  * ``"track"``: the segment's track length x weight — exactly the
    flux lane's per-crossing contribution ``(s_new − s)·‖d0‖·w``, so
    the ``flux`` score's lane values are BITWISE the flux lane's
    update stream (the bin-partition telescoping contract,
    tests/test_scoring.py);
  * ``"count"``: 1 per committed face crossing (interior neighbor
    advance, partition-face pause, or the boundary exit — the same
    event set the reference's ``inter_points`` records) — exact small
    integers, so cross-engine equality is exact, not rounding-class.
- ``factor`` — a per-particle walk-constant multiplier resolved once
  per move (scoring/binding.py): ``"one"`` (no scaling) or
  ``"energy"`` (the staged per-particle energy).

Shipped scores:

- ``flux``    — track x 1: the reference's own tally, per (bin).
- ``heating`` — track x energy: the KERMA-shaped linear-in-energy
  deposition placeholder (a production host folds its material
  response into the staged energies/weights; the lane layout is what
  this subsystem provides).
- ``events``  — crossings x 1: per-bin face-crossing counts, the
  collision-density analogue for a track-length engine.
"""

from __future__ import annotations

# name -> (basis, factor); see module docstring.
SCORES: dict = {
    "flux": ("track", "one"),
    "heating": ("track", "energy"),
    "events": ("count", "one"),
}
