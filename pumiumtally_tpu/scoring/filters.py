"""Bin-edge filters over per-particle move attributes.

A filter is a host-side, immutable description of a binned axis: a
strictly increasing edge array over one per-particle attribute the
host app stages with each move (``energy=`` / ``time=`` on
``MoveToNextLocation``). A particle's bin is resolved ONCE per move
with a branchless ``searchsorted`` (scoring/binding.py) — bins are
walk-constant, so no per-crossing filter work happens in the hot loop.

Edges are floats validated here and uploaded as DEVICE OPERANDS by the
runtime: their VALUES never enter any jit cache key (only the bin
COUNT does, through the edge array's shape), so re-binning a campaign
with different edges never recompiles an engine.
"""

from __future__ import annotations

import numpy as np


class _EdgeFilter:
    """Shared edge validation; subclasses fix the attribute they bin."""

    #: the MoveToNextLocation keyword this filter bins (set by subclass)
    attribute: str = ""

    def __init__(self, edges):
        e = np.asarray(edges, dtype=np.float64).reshape(-1)
        if e.shape[0] < 2:
            raise ValueError(
                f"{type(self).__name__} needs at least 2 edges "
                f"(1 bin), got {e.shape[0]}"
            )
        if not np.isfinite(e).all():
            raise ValueError(
                f"{type(self).__name__} edges must be finite, got {e!r}"
            )
        if not np.all(np.diff(e) > 0):
            raise ValueError(
                f"{type(self).__name__} edges must be strictly "
                f"increasing, got {e!r}"
            )
        self.edges = e

    @property
    def n_bins(self) -> int:
        return self.edges.shape[0] - 1

    def __repr__(self) -> str:
        return f"{type(self).__name__}(edges={self.edges.tolist()!r})"


class EnergyFilter(_EdgeFilter):
    """Bin by the per-particle ``energy`` staged with each move
    (OpenMC's EnergyFilter analogue). Values outside
    ``[edges[0], edges[-1])`` follow ``ScoringSpec.overflow``."""

    attribute = "energy"


class TimeFilter(_EdgeFilter):
    """Bin by the per-particle ``time`` staged with each move
    (OpenMC's TimeFilter analogue). Same out-of-range policy as the
    energy filter — one knob for the whole spec."""

    attribute = "time"
