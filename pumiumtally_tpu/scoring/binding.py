"""ScoringSpec (configuration) + ScoringRuntime (per-facade binding).

Lane-bank layout (docs/DESIGN.md "Filtered scoring"): one flattened
``[E · B · S]`` device array per engine, score-minor —
``lane(e, b, k) = e·(B·S) + b·S + k`` with ``B`` the bin count
(product over filters, time-minor) and ``S`` the score count. The
walk hook only ever needs the per-particle ``bin_off = b·S`` (or the
DROP sentinel) and the per-particle ``[S]`` factor row: both are
walk-constant, resolved ONCE per move by the jitted ``score_bins``
entry point below — a branchless ``searchsorted`` per filter over
edge arrays passed as device OPERANDS, so edge values never enter any
jit cache key (only bin counts do, through shapes).

Out-of-range policy (``ScoringSpec.overflow``, one knob for every
filter):

- ``"drop"`` (default; the OpenMC convention): values below
  ``edges[0]`` or at/above ``edges[-1]`` score into no bin — the bin
  offset becomes a sentinel ``>= bank_size`` and the lane scatter's
  ``mode="drop"`` discards it deterministically;
- ``"clamp"``: out-of-range values land in the nearest edge bin.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu.scoring.filters import (
    EnergyFilter,
    TimeFilter,
    _EdgeFilter,
)
from pumiumtally_tpu.scoring.scores import SCORES
from pumiumtally_tpu.utils.profiling import register_entry_point

OVERFLOW_POLICIES = ("drop", "clamp")


class ScoringSpec:
    """User-facing scoring configuration (``TallyConfig.scoring``).

    Args:
      filters: at most one ``EnergyFilter`` and one ``TimeFilter``
        (empty = one unfiltered bin).
      scores: names from the ``scoring.scores.SCORES`` registry, no
        duplicates, at least one.
      overflow: the out-of-range policy knob, ``"drop"``/``"clamp"``
        (module docstring).
    """

    def __init__(
        self,
        filters: Sequence[_EdgeFilter] = (),
        scores: Sequence[str] = ("flux",),
        overflow: str = "drop",
    ):
        self.energy_filter: Optional[EnergyFilter] = None
        self.time_filter: Optional[TimeFilter] = None
        for f in filters:
            if isinstance(f, EnergyFilter):
                if self.energy_filter is not None:
                    raise ValueError("at most one EnergyFilter per spec")
                self.energy_filter = f
            elif isinstance(f, TimeFilter):
                if self.time_filter is not None:
                    raise ValueError("at most one TimeFilter per spec")
                self.time_filter = f
            else:
                raise ValueError(
                    f"filters must be EnergyFilter/TimeFilter, got {f!r}"
                )
        scores = tuple(scores)
        if not scores:
            raise ValueError("ScoringSpec needs at least one score")
        if len(set(scores)) != len(scores):
            raise ValueError(f"duplicate scores in {scores!r}")
        for s in scores:
            if s not in SCORES:
                raise ValueError(
                    f"unknown score {s!r}; available: {sorted(SCORES)}"
                )
        if overflow not in OVERFLOW_POLICIES:
            raise ValueError(
                f"overflow must be one of {OVERFLOW_POLICIES}, "
                f"got {overflow!r}"
            )
        self.scores = scores
        self.overflow = overflow

    @property
    def n_ebins(self) -> int:
        return 0 if self.energy_filter is None else self.energy_filter.n_bins

    @property
    def n_tbins(self) -> int:
        return 0 if self.time_filter is None else self.time_filter.n_bins

    @property
    def n_bins(self) -> int:
        """Combined bin count (product over filters, time-minor)."""
        return max(1, self.n_ebins) * max(1, self.n_tbins)

    @property
    def n_scores(self) -> int:
        return len(self.scores)

    @property
    def kinds(self) -> Tuple[str, ...]:
        """Per-score segment basis ("track"/"count") — the STATIC half
        of the walk hook's contract."""
        return tuple(SCORES[s][0] for s in self.scores)

    @property
    def fac_kinds(self) -> Tuple[str, ...]:
        """Per-score factor source ("one"/"energy") for bin
        resolution."""
        return tuple(SCORES[s][1] for s in self.scores)

    @property
    def needs_energy(self) -> bool:
        return self.energy_filter is not None or "energy" in self.fac_kinds

    @property
    def needs_time(self) -> bool:
        return self.time_filter is not None

    def static_key(self) -> tuple:
        """The hashable spec identity for engine jit-cache keys — the
        edge VALUES are deliberately absent (they are operands of the
        ``score_bins`` program only)."""
        return (self.scores, self.overflow, self.n_ebins, self.n_tbins)

    def __repr__(self) -> str:
        fs = [f for f in (self.energy_filter, self.time_filter) if f]
        return (
            f"ScoringSpec(filters={fs!r}, scores={self.scores!r}, "
            f"overflow={self.overflow!r})"
        )


class ScoreOps(NamedTuple):
    """The walk hook's operand bundle (ops/walk.py ``walk(scoring=)``
    and ``walk_local(scoring=)``): ``kinds`` is static (a python
    tuple); the arrays are traced.

    ``bank`` is the (engine-local) flattened lane bank the walk
    accumulates into; ``bin_off`` the per-particle ``b·S`` lane offset
    (or a ``>= bank_size`` DROP sentinel); ``fac`` the per-particle
    ``[S]`` factor row."""

    kinds: Tuple[str, ...]
    bank: Any
    bin_off: Any
    fac: Any


@partial(jax.jit, static_argnames=("fac_kinds", "clamp", "sentinel"))
def _bins_and_factors(e_edges, t_edges, energy, time_, ones, *,
                      fac_kinds, clamp, sentinel):
    """Branchless per-particle bin resolution + factor rows.

    ``ones`` is an all-ones [n] template in the working dtype (fixes n
    and the dtype even when no attribute array is staged). Edge arrays
    are operands: one compile per (n, dtype, spec static key)."""
    n_scores = len(fac_kinds)
    bin_idx = jnp.zeros_like(ones, dtype=jnp.int32)
    bad = jnp.zeros(ones.shape, dtype=bool)
    for edges, vals in ((e_edges, energy), (t_edges, time_)):
        if edges is None:
            continue
        nb = edges.shape[0] - 1
        b = (
            jnp.searchsorted(edges, vals.astype(edges.dtype), side="right")
            .astype(jnp.int32) - 1
        )
        bad = bad | (b < 0) | (b >= nb)
        bin_idx = bin_idx * nb + jnp.clip(b, 0, nb - 1)
    bin_off = bin_idx * n_scores
    if not clamp:
        bin_off = jnp.where(bad, jnp.asarray(sentinel, jnp.int32), bin_off)
    cols = [ones if k == "one" else energy.astype(ones.dtype)
            for k in fac_kinds]
    return bin_off, jnp.stack(cols, axis=1)


_bins_and_factors = register_entry_point("score_bins", _bins_and_factors)


class ScoringRuntime:
    """Per-facade scoring binding: the spec's device-side edge arrays,
    the bank geometry, and the per-move bin/factor resolution.

    ``bank_size`` is the facade's OWN flattened lane-bank length —
    ``E·B·S`` for the replicated-mesh engines, the PADDED
    ``nparts·L·B·S`` for the partitioned ones. The DROP sentinel is
    ``bank_size`` itself: every lane index built from it lands at or
    past the end of any (sub-)bank slice the walk scatters into, and
    ``mode="drop"`` discards it."""

    def __init__(self, spec: ScoringSpec, nelems: int, dtype: Any,
                 bank_size: Optional[int] = None):
        self.spec = spec
        self.nelems = int(nelems)
        self.dtype = dtype
        self.stride = spec.n_bins * spec.n_scores  # lanes per element
        self.bank_size = (
            self.nelems * self.stride if bank_size is None
            else int(bank_size)
        )
        ef, tf = spec.energy_filter, spec.time_filter
        self.e_edges = (
            None if ef is None else jnp.asarray(ef.edges, dtype)
        )
        self.t_edges = (
            None if tf is None else jnp.asarray(tf.edges, dtype)
        )

    def resolve(self, energy, time_, n: int):
        """(bin_off [n] int32, fac [n,S]) for one staged move.

        ``energy``/``time_`` are [n] device (or host) arrays, or None
        when the spec does not consume the attribute; presence is the
        FACADE's contract (it validates with argument-naming errors
        before anything is staged)."""
        ones = jnp.ones((n,), self.dtype)
        return _bins_and_factors(
            self.e_edges, self.t_edges,
            None if energy is None else jnp.asarray(energy),
            None if time_ is None else jnp.asarray(time_),
            ones,
            fac_kinds=self.spec.fac_kinds,
            clamp=self.spec.overflow == "clamp",
            sentinel=self.bank_size,
        )

    def zero_bank(self) -> jnp.ndarray:
        return jnp.zeros((self.bank_size,), self.dtype)

    def ops(self, bank, bin_off, fac) -> ScoreOps:
        return ScoreOps(self.spec.kinds, bank, bin_off, fac)


def score_cell_data(spec: ScoringSpec, bank: np.ndarray,
                    volumes: np.ndarray) -> dict:
    """``<score>_bin<k>`` cell arrays for the VTK writers from a
    CANONICAL (original-element-order) flattened bank — every lane
    volume-normalized exactly like the flux array, so the 1-filter
    flux lanes sum to the written ``flux`` array (bin-partition
    telescoping). Returns {} for a None spec so scoring-off files keep
    the reference payload byte-identical."""
    if spec is None:
        return {}
    vol = np.asarray(volumes, dtype=np.float64)
    arr = np.asarray(bank, dtype=np.float64).reshape(
        vol.shape[0], spec.n_bins, spec.n_scores
    ) / vol[:, None, None]
    out = {}
    for b in range(spec.n_bins):
        for j, name in enumerate(spec.scores):
            out[f"{name}_bin{b}"] = arr[:, b, j]
    return out
