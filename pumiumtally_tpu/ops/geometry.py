"""Pure jnp geometry helpers (simplex volumes, barycentric tests, locate).

Replaces the Omega_h simplex utilities used by the reference
(``simplex_basis<3,3>`` / ``simplex_size_from_basis``,
reference PumiTallyImpl.cpp:398-399) with batched, jit-friendly
equivalents.
"""

from __future__ import annotations

import warnings
from typing import Optional

import jax.numpy as jnp
from jax import lax

_warned_tiny_chunk = False


def tet_volumes(coords: jnp.ndarray, tet2vert: jnp.ndarray) -> jnp.ndarray:
    """Signed tet volumes [E] from coords [V,3] and connectivity [E,4]."""
    v = coords[tet2vert]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    return jnp.einsum("ei,ei->e", jnp.cross(a, b), c) / 6.0


def barycentric(
    coords: jnp.ndarray, tet2vert: jnp.ndarray, elem: jnp.ndarray, p: jnp.ndarray
) -> jnp.ndarray:
    """Barycentric coordinates [N,4] of points p [N,3] w.r.t. tets elem [N]."""
    v = coords[tet2vert[elem]]  # [N,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    d = p - v[:, 0]
    vol = jnp.einsum("ni,ni->n", jnp.cross(a, b), c)
    l1 = jnp.einsum("ni,ni->n", jnp.cross(d, b), c) / vol
    l2 = jnp.einsum("ni,ni->n", jnp.cross(a, d), c) / vol
    l3 = jnp.einsum("ni,ni->n", jnp.cross(a, b), d) / vol
    l0 = 1.0 - l1 - l2 - l3
    return jnp.stack([l0, l1, l2, l3], axis=1)


def contains(
    coords: jnp.ndarray,
    tet2vert: jnp.ndarray,
    elem: jnp.ndarray,
    p: jnp.ndarray,
    tol: float = 1e-10,
) -> jnp.ndarray:
    """Boolean [N]: is point p[n] inside tet elem[n] (within tol)."""
    lam = barycentric(coords, tet2vert, elem, p)
    return jnp.all(lam >= -tol, axis=1)


def locate_chunk_by_planes(
    nmat: jnp.ndarray,  # [4E,3] face normals, row-major per element
    fo: jnp.ndarray,  # [E,4] face offsets
    valid: Optional[jnp.ndarray],  # [E] bool mask (None = all valid)
    pts: jnp.ndarray,  # [C,3]
    tol: float,
) -> jnp.ndarray:
    """Containing element id [C] (−1 = none) by half-space tests.

    A point is inside a tet iff it is on the inner side of all four
    face planes; the test over every element is ONE [C,3]×[3,4E]
    matmul — MXU-shaped, no gather — then a compare-and-reduce. Ties
    (points within tol of a shared face) go to the lowest element id
    via argmax-of-first-True: deterministic. Shared by the partitioned
    engine's sharded localization (parallel/partition.py) and the
    monolithic locate path below.
    """
    proj = pts @ nmat.T  # [C, 4E]
    ok = (proj.reshape(pts.shape[0], -1, 4) <= fo[None] + tol).all(axis=2)
    if valid is not None:
        ok = ok & valid[None, :]
    found = ok.any(axis=1)
    le = jnp.argmax(ok, axis=1).astype(jnp.int32)
    return jnp.where(found, le, -1)


def locate_by_planes(
    face_normals: jnp.ndarray,  # [E,4,3]
    face_offsets: jnp.ndarray,  # [E,4]
    pts: jnp.ndarray,  # [N,3]
    tol: float,
    chunk: Optional[int] = None,
) -> jnp.ndarray:
    """Containing element id [N] (−1 = none) for arbitrary point sets,
    chunked so the [C,4E] intermediate stays ≤ ~128 MB f32 regardless
    of mesh size (same bound as the partitioned engine's locate)."""
    n = pts.shape[0]
    ne = face_offsets.shape[0]
    nmat = face_normals.reshape(ne * 4, 3)
    # No floor: memory is the binding constraint, so on meshes past ~8M
    # elements the chunk legitimately degrades to one point at a time.
    c = chunk or max(1, min(2048, (1 << 23) // max(ne, 1)))
    if chunk is None and c < 32:
        # lax.map then runs N/c sequential tiny matmuls — orders of
        # magnitude slower than the adjacency walk. Say so once instead
        # of silently crawling (mirrors the TallyConfig CPU caveat).
        global _warned_tiny_chunk
        if not _warned_tiny_chunk:
            _warned_tiny_chunk = True
            warnings.warn(
                f"locate_by_planes: {ne} elements force a chunk of "
                f"{c} point(s); half-space localization will be very "
                "slow at this mesh size — prefer localization='walk'.",
                stacklevel=2,
            )
    c = min(c, max(n, 1))
    m = -(-n // c) * c
    if m > n:
        # Far-away pad points: inside no element.
        pts = jnp.concatenate(
            [pts, jnp.full((m - n, 3), 2e30, pts.dtype)]
        )
    out = lax.map(
        lambda p: locate_chunk_by_planes(nmat, face_offsets, None, p, tol),
        pts.reshape(-1, c, 3),
    )
    return out.reshape(-1)[:n]


def locate_bruteforce(
    coords: jnp.ndarray, tet2vert: jnp.ndarray, p: jnp.ndarray, tol: float = 1e-10
) -> jnp.ndarray:
    """Containing element id [N] for each point, by testing every tet.

    O(N·E) — intended for tests and small meshes only; production
    localization uses the adjacency walk (reference localizes by walking
    from element 0's centroid, PumiTallyImpl.cpp:195-221).
    """
    ne = tet2vert.shape[0]
    v = coords[tet2vert]  # [E,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    vol = jnp.einsum("ei,ei->e", jnp.cross(a, b), c)  # [E]
    d = p[:, None, :] - v[None, :, 0, :]  # [N,E,3]
    l1 = jnp.einsum("nei,ei->ne", jnp.cross(d, b[None]), c) / vol
    l2 = jnp.einsum("nei,ei->ne", jnp.cross(a[None], d), c) / vol
    l3 = jnp.einsum("ei,nei->ne", jnp.cross(a, b), d) / vol
    l0 = 1.0 - l1 - l2 - l3
    inside = (l0 >= -tol) & (l1 >= -tol) & (l2 >= -tol) & (l3 >= -tol)
    first = jnp.argmax(inside, axis=1)
    found = jnp.any(inside, axis=1)
    return jnp.where(found, first, -1).astype(jnp.int32)
