"""Pure jnp geometry helpers (simplex volumes, barycentric tests, locate).

Replaces the Omega_h simplex utilities used by the reference
(``simplex_basis<3,3>`` / ``simplex_size_from_basis``,
reference PumiTallyImpl.cpp:398-399) with batched, jit-friendly
equivalents.
"""

from __future__ import annotations

import jax.numpy as jnp


def tet_volumes(coords: jnp.ndarray, tet2vert: jnp.ndarray) -> jnp.ndarray:
    """Signed tet volumes [E] from coords [V,3] and connectivity [E,4]."""
    v = coords[tet2vert]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    return jnp.einsum("ei,ei->e", jnp.cross(a, b), c) / 6.0


def barycentric(
    coords: jnp.ndarray, tet2vert: jnp.ndarray, elem: jnp.ndarray, p: jnp.ndarray
) -> jnp.ndarray:
    """Barycentric coordinates [N,4] of points p [N,3] w.r.t. tets elem [N]."""
    v = coords[tet2vert[elem]]  # [N,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    d = p - v[:, 0]
    vol = jnp.einsum("ni,ni->n", jnp.cross(a, b), c)
    l1 = jnp.einsum("ni,ni->n", jnp.cross(d, b), c) / vol
    l2 = jnp.einsum("ni,ni->n", jnp.cross(a, d), c) / vol
    l3 = jnp.einsum("ni,ni->n", jnp.cross(a, b), d) / vol
    l0 = 1.0 - l1 - l2 - l3
    return jnp.stack([l0, l1, l2, l3], axis=1)


def contains(
    coords: jnp.ndarray,
    tet2vert: jnp.ndarray,
    elem: jnp.ndarray,
    p: jnp.ndarray,
    tol: float = 1e-10,
) -> jnp.ndarray:
    """Boolean [N]: is point p[n] inside tet elem[n] (within tol)."""
    lam = barycentric(coords, tet2vert, elem, p)
    return jnp.all(lam >= -tol, axis=1)


def locate_bruteforce(
    coords: jnp.ndarray, tet2vert: jnp.ndarray, p: jnp.ndarray, tol: float = 1e-10
) -> jnp.ndarray:
    """Containing element id [N] for each point, by testing every tet.

    O(N·E) — intended for tests and small meshes only; production
    localization uses the adjacency walk (reference localizes by walking
    from element 0's centroid, PumiTallyImpl.cpp:195-221).
    """
    ne = tet2vert.shape[0]
    v = coords[tet2vert]  # [E,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    vol = jnp.einsum("ei,ei->e", jnp.cross(a, b), c)  # [E]
    d = p[:, None, :] - v[None, :, 0, :]  # [N,E,3]
    l1 = jnp.einsum("nei,ei->ne", jnp.cross(d, b[None]), c) / vol
    l2 = jnp.einsum("nei,ei->ne", jnp.cross(a[None], d), c) / vol
    l3 = jnp.einsum("ei,nei->ne", jnp.cross(a, b), d) / vol
    l0 = 1.0 - l1 - l2 - l3
    inside = (l0 >= -tol) & (l1 >= -tol) & (l2 >= -tol) & (l3 >= -tol)
    first = jnp.argmax(inside, axis=1)
    found = jnp.any(inside, axis=1)
    return jnp.where(found, first, -1).astype(jnp.int32)
