"""One-kernel Pallas walk: fused two-tier select/refine/scatter with
double-buffered table streaming.

``ops/vmem_walk.py`` proved the one-hot MXU form of the partitioned
walk — table pinned in VMEM, the whole while_loop on-chip, flux
accumulated as a matmul — but only for the f32 single-tier table and
only below the fits-in-VMEM element ceiling. This kernel generalizes
it along both axes:

1. **Two-tier tables** (docs/PERF_NOTES.md "Table precision tiers"):
   the fetched row is the half-width bf16 SELECT row, lifted to the
   working dtype ONCE per table block (``_lift_bf16`` — the exact
   bit-shift upcast) and fetched by the same one-hot matmul; the
   winning face's full-precision refinement plane comes from a second
   one-hot matmul against the block's ``[Lp, 4·WALK_PLANE_WIDTH]``
   refinement operand with an exact 4-way face select. Selection and
   refinement then run the SAME row-level helpers as the gather walk
   (``ops/walk.py select_rows_lo / refine_plane_hi``), so positions,
   elements, pause points and iteration counts are BITWISE-identical
   to ``walk_local``'s two-tier path — the fetch is exact (one-hot
   rows: 0·v = 0, 1·v = v, sum of zeros is exact), and everything
   after the fetch is literally the same trace. Only the flux (and
   scoring-lane) accumulation differs: per-tile matmul partials
   summed at the end instead of cascaded scatter-adds — the
   scatter-order FP reassociation class partitioned mode already
   documents.

2. **Streaming past the VMEM ceiling**: the grid is
   ``(blocks, tiles-per-block)`` over the engine's sub-split block
   tables, and Pallas' grid pipeline DOUBLE-BUFFERS the block inputs —
   while grid step ``(b, t)`` walks, the ``(b, t+1)`` / ``(b+1, 0)``
   table blocks are prefetching into VMEM. A partition bigger than
   VMEM therefore streams through the kernel block by block at the
   two-tier byte floor (``modeled_walk_bytes``: 52 B/crossing vs the
   80 B f32 gather; the resident ``blocks == 1`` case degenerates to
   the vmem prototype's zero-table-traffic regime) instead of
   rerouting to the gather kernel.

3. **In-kernel scoring lanes** (the first block kernel with a scoring
   lowering): each crossing's lane update ``(elem·stride + bin + k,
   colv·fac)`` becomes a dense ``[w_tile, stride]`` value matrix —
   ``val[w, j] = Σ_k [sbin[w]+k == j] · colv_k[w] · fac[w, k]`` — and
   one ``ohᵀ·val`` matmul accumulates the block's ``[Lp, stride]``
   bank partial on-chip. The DROP sentinel (``scoring/binding.py``:
   ``bin_off = bank_size``, far past any stride) never matches a
   column, so dropped lanes die exactly like the gather path's
   ``mode="drop"`` scatter. Values are the same per-crossing products
   as ``score_pair``; only the accumulation order differs (the same
   benign class as flux).

Engines route here via ``TallyConfig.walk_kernel = "pallas"``
(``parallel/partition.py resolve_block_kernel``); the default config
keeps every existing trace byte-identical. Mosaic's block-shape /
while-carry laws are inherited from ``ops/vmem_walk.py`` (module
docstring there); the two table operands use whole-array minors
(16 and 20 lanes), which rank-2 blocks permit. The shared-helper
einsum/argmin select is the part of this kernel the chipless AOT
harness (tools/aot_pallas_walk_compile.py) exists to certify — the
interpret path never checks Mosaic's op coverage.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pumiumtally_tpu.mesh.tetmesh import (
    WALK_PLANE_WIDTH,
    WALK_TABLE_LO_WIDTH,
    WALK_TABLE_WIDTH,
)
from pumiumtally_tpu.ops.vmem_walk import (
    TILE_1D,
    W_TILE_DEFAULT,
    _round_up,
    backend_needs_interpret,
)
from pumiumtally_tpu.ops.walk import (
    _lift_bf16,
    refine_plane_hi,
    select_rows_lo,
)

# The refinement operand's packed block layout: element g's four
# [WALK_PLANE_WIDTH] face rows of ``table_hi`` flattened into one
# [4·WALK_PLANE_WIDTH] row, so ONE one-hot matmul fetches every
# candidate plane and the winner is an exact 4-way column select
# (row g of the packed block, cols [f·W, (f+1)·W) ≡ table_hi row
# g·4+f — pure relayout, no value changes).
HI_BLOCK_COLS = 4 * WALK_PLANE_WIDTH


def modeled_walk_bytes(kernel: str, table_dtype: str = "float32") -> int:
    """Modeled HBM table traffic per crossing, from the packed-layout
    constants (the ``state_pack_columns`` discipline: the model is
    derived from the same constants that build the tables, so a layout
    change reprices it automatically — mirrors
    ``parallel/distributed.py modeled_migration_collective_bytes``).

    - ``gather``/``float32``: one [WALK_TABLE_WIDTH] f32 row per
      crossing — the measured ~80 B floor (docs/PERF_NOTES.md).
    - ``gather``/``bfloat16`` and ``pallas``/``bfloat16``: the
      two-tier 52 B model — one bf16 SELECT row plus ONE f32
      refinement plane. The pallas kernel STREAMS these bytes as
      sequential block DMA (amortized over the block's crossings)
      instead of random row gathers; the per-crossing model is the
      same 52 B, approached from the bandwidth-friendly side.
    - ``vmem``/``float32``: 0 — the resident table pays no
      per-crossing HBM traffic at all (the regime the pallas kernel
      degenerates to at ``blocks == 1``).
    """
    if kernel == "vmem":
        if table_dtype != "float32":
            raise ValueError(
                "the vmem kernel has no two-tier lowering "
                "(ops/vmem_walk.py); use kernel='pallas' for bfloat16"
            )
        return 0
    if kernel not in ("gather", "pallas"):
        raise ValueError(
            f"kernel must be 'gather', 'vmem' or 'pallas', got {kernel!r}"
        )
    if table_dtype == "float32":
        if kernel == "pallas":
            raise ValueError(
                "the pallas walk kernel is two-tier only "
                "(walk_table_dtype='bfloat16')"
            )
        return WALK_TABLE_WIDTH * 4  # 80 B: one packed f32 row
    if table_dtype == "bfloat16":
        # 52 B: bf16 select row + ONE f32 refinement plane.
        return WALK_TABLE_LO_WIDTH * 2 + WALK_PLANE_WIDTH * 4
    raise ValueError(
        f"table_dtype must be 'float32' or 'bfloat16', got {table_dtype!r}"
    )


def pack_hi_blocks(
    table_hi: jnp.ndarray, blocks: int, L: int, Lp: int
) -> jnp.ndarray:
    """``[blocks·L·4, WALK_PLANE_WIDTH]`` refinement tier →
    ``[blocks·Lp, HI_BLOCK_COLS]`` per-block MXU operand (element-major
    flatten of each element's four face planes, rows zero-padded to the
    TILE_1D multiple — padded rows are never selected by the one-hot).
    Pure relayout: ``packed[b·Lp + g, f·W + j] == table_hi[(b·L + g)·4
    + f, j]``."""
    packed = table_hi.reshape(blocks, L, HI_BLOCK_COLS)
    if Lp != L:
        packed = jnp.concatenate(
            [packed,
             jnp.zeros((blocks, Lp - L, HI_BLOCK_COLS), table_hi.dtype)],
            axis=1,
        )
    return packed.reshape(blocks * Lp, HI_BLOCK_COLS)


def pad_lo_blocks(
    table_lo: jnp.ndarray, blocks: int, L: int, Lp: int
) -> jnp.ndarray:
    """``[blocks·L, WALK_TABLE_LO_WIDTH]`` select tier →
    ``[blocks·Lp, WALK_TABLE_LO_WIDTH]`` with zero-padded block rows
    (same contract as ``pack_hi_blocks``; bf16 zeros lift to 0.0)."""
    if Lp == L:
        return table_lo
    cols = table_lo.shape[1]
    return jnp.concatenate(
        [table_lo.reshape(blocks, L, cols),
         jnp.zeros((blocks, Lp - L, cols), table_lo.dtype)], axis=1
    ).reshape(blocks * Lp, cols)


def pallas_walk_local(
    table_lo: jnp.ndarray,  # [blocks*L, WALK_TABLE_LO_WIDTH] bf16 select
    table_hi: jnp.ndarray,  # [blocks*L*4, WALK_PLANE_WIDTH] refinement
    x: jnp.ndarray,  # [S,3]
    lelem: jnp.ndarray,  # [S] block-local element ids
    dest: jnp.ndarray,  # [S,3]
    flying: jnp.ndarray,  # [S] int8
    weight: jnp.ndarray,  # [S]
    done: jnp.ndarray,  # [S] bool
    exited: jnp.ndarray,  # [S] bool
    flux: jnp.ndarray,  # [blocks*L] owned flux
    *,
    tally: bool,
    tol: float,
    max_iters: int,
    w_tile: int = W_TILE_DEFAULT,
    interpret: Optional[bool] = None,
    vma: Optional[frozenset] = None,
    blocks: int = 1,
    scoring=None,  # ScoreOps over this chip's [blocks*L*stride] bank
) -> Tuple[jnp.ndarray, ...]:
    """Drop-in for ``parallel.partition.walk_local``'s two-tier path
    (minus its cascade knobs): returns ``(x, lelem, done, exited,
    pending, flux, iters)`` — plus the accumulated score bank as an
    EIGHTH element when ``scoring`` is armed — with identical
    pause/boundary semantics. Positions/elements/pending are bitwise
    ``walk_local``; flux and lanes differ only in accumulation order
    (module docstring).

    ``blocks``: streaming sub-split, same layout contract as
    ``vmem_walk_local`` — ``blocks`` stacked block tables, slots
    grouped by block (``cap_b = S // blocks``, ``lelem`` block-local,
    flux ``[blocks*L]``), grid ``(blocks, tiles)`` with the block
    tables double-buffered by the grid pipeline. Requires
    ``S % blocks == 0`` and ``cap_b % w_tile == 0``.

    ``vma``: see ``vmem_walk_local`` — engines disable varying-axis
    checking for pallas round programs instead; kept for a jax whose
    interpret path preserves the tags.
    """
    from jax.experimental import pallas as pl

    if table_lo.dtype != jnp.bfloat16:
        raise ValueError(
            "pallas_walk_local needs the bf16 SELECT tier "
            f"(got {table_lo.dtype}); build the partition with "
            "table_dtype='bfloat16'"
        )
    if interpret is None:
        interpret = backend_needs_interpret()
    fdtype = x.dtype
    hdtype = table_hi.dtype
    blocks = int(blocks)
    L = table_lo.shape[0] // blocks
    n = x.shape[0]
    score_on = scoring is not None
    if score_on:
        if not tally:
            raise ValueError("scoring requires a tallying walk")
        s_kinds = scoring.kinds
        stride = scoring.bank.shape[0] // flux.shape[0]
        n_scores = len(s_kinds)
        sbin, sfac, bank = scoring.bin_off, scoring.fac, scoring.bank
    if n == 0:  # walk_local handles the empty batch; match it
        out = (x, lelem, done, exited, jnp.full((0,), -1, jnp.int32),
               flux, jnp.asarray(0, jnp.int32))
        return out + (bank,) if score_on else out
    w_tile = _round_up(max(int(w_tile), 1), TILE_1D)
    if blocks > 1:
        # Sub-split layout is engine-arranged: no padding here, the
        # slot grouping IS the block routing.
        if n % blocks or (n // blocks) % w_tile:
            raise ValueError(
                f"blocked pallas walk needs slots divisible into "
                f"blocks x k x w_tile, got S={n}, blocks={blocks}, "
                f"w_tile={w_tile}"
            )
        pad = 0
    else:
        pad = (-n) % w_tile
        if pad:
            def padv(a, fill):
                return jnp.concatenate(
                    [a, jnp.full((pad, *a.shape[1:]), fill, a.dtype)]
                )

            x, dest = padv(x, 0.0), padv(dest, 0.0)
            lelem = padv(lelem, 0)
            flying = padv(flying, 0)
            weight = padv(weight, 0.0)
            done = padv(done, True)  # pad slots are inert
            exited = padv(exited, False)
            if score_on:
                sbin = padv(sbin, 0)  # inert slots add exact zeros
                sfac = padv(sfac, 0.0)

    d0 = dest - x
    seg_len = jnp.linalg.norm(d0, axis=1)
    eff_w = jnp.where(flying.astype(bool), weight * seg_len, 0.0)
    T = (n + pad) // w_tile // blocks  # tiles per block
    max_iters = int(max_iters)
    Lp = _round_up(L, TILE_1D)
    lo_p = pad_lo_blocks(table_lo, blocks, L, Lp)
    hi_p = pack_hi_blocks(table_hi, blocks, L, Lp)

    def kernel(*refs):
        refs = list(refs)
        (lo_ref, hi_ref, x_ref, lelem_ref, dest_ref, effw_ref, done_ref,
         exited_ref) = refs[:8]
        i = 8
        if score_on:
            sbin_ref, sfac_ref = refs[i:i + 2]
            i += 2
        (s_out, lelem_out, done_out, exited_out, pending_out,
         it_out) = refs[i:i + 6]
        i += 6
        flux_out = refs[i] if tally else None
        i += int(tally)
        bank_out = refs[i] if score_on else None

        x0 = x_ref[:]
        # walk_local's two-tier advance rebuilds dest from the carried
        # ray invariants (dest_c = x0 + d0) — reproduce that EXACT
        # float, not the original dest, or parity is off by an ulp.
        d0_c = dest_ref[:] - x0
        dest_c = x0 + d0_c
        effw_c = effw_ref[:]
        one_k = jnp.asarray(1.0, fdtype)
        # Lift the whole bf16 block ONCE per grid step (elementwise and
        # exact, so lift-then-fetch == fetch-then-lift bitwise); the
        # while body then fetches working-dtype rows.
        lo_v = _lift_bf16(lo_ref[:], fdtype)
        hi_v = hi_ref[:]
        iota = lax.broadcasted_iota(jnp.int32, (w_tile, Lp), 1)
        if vma and hasattr(lax, "pvary"):
            # See vmem_walk_local: iota computed from no input stays
            # "unvarying" under shard_map's vma checking.
            iota = lax.pvary(iota, tuple(vma))
        if score_on:
            j_iota = lax.broadcasted_iota(jnp.int32, (w_tile, stride), 1)
            if vma and hasattr(lax, "pvary"):
                j_iota = lax.pvary(j_iota, tuple(vma))
            sbin_c = sbin_ref[:]
            sfac_c = sfac_ref[:]

        # flux/bank/iters live in per-BLOCK output blocks revisited by
        # every tile t (index_map ignores t): zero on the block's first
        # tile, reduce in VMEM across tiles — the revisited-block
        # reduction pattern from vmem_walk_local.
        t_id = pl.program_id(1)

        @pl.when(t_id == 0)
        def _init():
            it_out[:] = jnp.zeros_like(it_out)
            if tally:
                flux_out[:] = jnp.zeros_like(flux_out)
            if score_on:
                bank_out[:] = jnp.zeros_like(bank_out)

        # Loop state in the per-tile OUTPUT refs + two-scalar while
        # carry; seeds derived from kernel inputs — both Mosaic/vma
        # laws inherited from vmem_walk_local (see the long comments
        # there; do not "simplify").
        s_out[:] = x0[:, 0] * jnp.asarray(0, fdtype)
        lelem_out[:] = lelem_ref[:]
        done_out[:] = done_ref[:]
        exited_out[:] = exited_ref[:]
        pending_out[:] = (lelem_ref[:] - lelem_ref[:]) - 1

        def body(carry):
            it, _n_active = carry
            s = s_out[:]
            lelem = lelem_out[:]
            done = done_out[:] != 0
            exited = exited_out[:] != 0
            pending = pending_out[:]
            active = (~done) & (pending < 0)
            oh = lelem[:, None] == iota
            oh_f = oh.astype(fdtype)
            # One-hot row fetch is exact for finite table values
            # (0·v = 0, 1·v = v, + 0 exact) — bitwise the gather.
            row = jnp.dot(oh_f, lo_v, preferred_element_type=fdtype)
            s_sel, f_exit = select_rows_lo(row, s, dest_c, d0_c, tol,
                                           one_k)
            oh_h = oh_f if hdtype == fdtype else oh.astype(hdtype)
            hi4 = jnp.dot(oh_h, hi_v, preferred_element_type=hdtype)
            # Winning face's plane: exact 4-way column select (pure
            # selection — no arithmetic touches the values).
            cols = []
            for j in range(WALK_PLANE_WIDTH):
                v = hi4[:, 3 * WALK_PLANE_WIDTH + j]
                for f in (2, 1, 0):
                    v = jnp.where(
                        f_exit == f, hi4[:, f * WALK_PLANE_WIDTH + j], v
                    )
                cols.append(v)
            plane = jnp.stack(cols, axis=1)
            s_exit, nxt = refine_plane_hi(plane, s, s_sel, dest_c, d0_c,
                                          tol, one_k)
            # walk_local's advance tail, verbatim.
            reached = s_exit >= one_k
            s_new = jnp.where(reached, one_k, s_exit)
            hit_boundary = (~reached) & (nxt == -1)
            goes_remote = (~reached) & (nxt <= -2)
            if tally:
                contrib = jnp.where(active, (s_new - s) * effw_c, 0.0)
            if score_on:
                crossed = (active & ~reached).astype(contrib.dtype)
                # score_pair's lane values as a dense [w_tile, stride]
                # matrix: column j = bin_off + k collects
                # colv_k · fac[:, k] (module docstring; sentinel
                # bin_off sits far past stride and never matches).
                val = (contrib * 0)[:, None] * jnp.zeros(
                    (1, stride), fdtype
                )
                for k, kind in enumerate(s_kinds):
                    colv = contrib if kind == "track" else crossed
                    hit = (sbin_c + jnp.int32(k))[:, None] == j_iota
                    val = val + jnp.where(
                        hit, (colv * sfac_c[:, k])[:, None],
                        jnp.asarray(0.0, fdtype),
                    )
            moving = active & ~reached & ~hit_boundary & ~goes_remote
            lelem = jnp.where(moving, nxt, lelem)
            s = jnp.where(active, s_new, s)
            pending = jnp.where(active & goes_remote, -nxt - 2, pending)
            done = done | (active & (reached | hit_boundary))
            exited = exited | (active & hit_boundary)
            s_out[:] = s
            lelem_out[:] = lelem
            done_out[:] = done.astype(jnp.int32)
            exited_out[:] = exited.astype(jnp.int32)
            pending_out[:] = pending
            if tally:
                flux_out[:] = flux_out[:] + jnp.dot(
                    contrib[None, :], oh_f,
                    preferred_element_type=flux_out.dtype,
                )[0]
            if score_on:
                # ohᵀ · val: the block's [Lp, stride] lane partial.
                bank_out[:] = bank_out[:] + lax.dot_general(
                    oh_f, val, (((0,), (0,)), ((), ())),
                    preferred_element_type=bank_out.dtype,
                )
            n_active = jnp.sum(
                ((~done) & (pending < 0)).astype(jnp.int32)
            )
            return it + jnp.int32(1), n_active

        def cond(carry):
            it, n_active = carry
            return (it < max_iters) & (n_active > 0)

        n0 = jnp.sum((done_ref[:] == 0).astype(jnp.int32))
        it, _ = lax.while_loop(cond, body, (jnp.int32(0), n0))
        it_out[:] = jnp.maximum(it_out[:], it)

    S = T * w_tile * blocks
    tile = lambda: pl.BlockSpec(  # noqa: E731
        (w_tile,), lambda b, t: (b * T + t,))
    tile3 = lambda: pl.BlockSpec(  # noqa: E731
        (w_tile, 3), lambda b, t: (b * T + t, 0))
    in_specs = [
        pl.BlockSpec((Lp, WALK_TABLE_LO_WIDTH), lambda b, t: (b, 0)),
        pl.BlockSpec((Lp, HI_BLOCK_COLS), lambda b, t: (b, 0)),
        tile3(), tile(), tile3(), tile(), tile(), tile(),
    ]
    if score_on:
        in_specs += [
            tile(),
            pl.BlockSpec((w_tile, n_scores), lambda b, t: (b * T + t, 0)),
        ]

    def sds(shape, dtype):
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        return jax.ShapeDtypeStruct(shape, dtype)

    out_specs = [
        tile(), tile(), tile(), tile(), tile(),
        pl.BlockSpec((TILE_1D,), lambda b, t: (b,)),
    ]
    out_shape = [
        sds((S,), fdtype),
        sds((S,), jnp.int32),
        sds((S,), jnp.int32),
        sds((S,), jnp.int32),
        sds((S,), jnp.int32),
        sds((blocks * TILE_1D,), jnp.int32),
    ]
    if tally:
        out_specs.append(pl.BlockSpec((Lp,), lambda b, t: (b,)))
        out_shape.append(sds((blocks * Lp,), flux.dtype))
    if score_on:
        out_specs.append(pl.BlockSpec((Lp, stride), lambda b, t: (b, 0)))
        out_shape.append(sds((blocks * Lp, stride), bank.dtype))
    inputs = [lo_p, hi_p, x, lelem, dest, eff_w,
              done.astype(jnp.int32), exited.astype(jnp.int32)]
    if score_on:
        inputs += [sbin, sfac]
    outs = pl.pallas_call(
        kernel,
        grid=(blocks, T),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    s_o, lelem_o, done_o, exited_o, pending_o, iters = outs[:6]
    i = 6
    if tally:
        fpart = outs[i]
        i += 1
    if score_on:
        bpart = outs[i]
    s_o, lelem_o = s_o[:n], lelem_o[:n]
    done_o = done_o[:n] != 0
    exited_o = exited_o[:n] != 0
    pending_o = pending_o[:n]
    # d0 was computed AFTER padding, so these slices are exactly the
    # unpadded invariants (reconstructing x0 as dest - d0 would be off
    # by an ulp — float subtraction does not invert addition).
    dest, d0, x0 = dest[:n], d0[:n], x[:n]
    if tally:
        # Per-block accumulated partials: drop the row padding, flatten
        # back to the [blocks*L] flux layout.
        flux = flux + fpart.reshape(blocks, Lp)[:, :L].reshape(blocks * L)
    if score_on:
        bank = bank + bpart.reshape(blocks, Lp, stride)[:, :L, :].reshape(
            blocks * L * stride
        )
    # Same materialization rule as walk_local.
    x_fin = jnp.where(
        (done_o & ~exited_o)[:, None], dest, x0 + s_o[:, None] * d0
    )
    out = (x_fin, lelem_o, done_o, exited_o, pending_o, flux,
           jnp.max(iters))
    return out + (bank,) if score_on else out
